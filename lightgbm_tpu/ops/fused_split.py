"""Fused per-split Pallas kernel: partition + smaller-child histogram.

TPU-native re-design of the reference's per-split device work (reference:
CUDA kernels GenDataToLeftBitVectorKernel / AggregateBlockOffsetKernel /
SplitInnerKernel, src/treelearner/cuda/cuda_data_partition.cu:288,679,907,
plus CUDAConstructHistogramDenseKernel,
src/treelearner/cuda/cuda_histogram_constructor.cu:17-68 — there three
separate kernel launches per split; here ONE fused streaming walk).

The XLA compact path (ops/compact.py) implements the same stable partition as
a chain of slice / compare / one-hot-matmul / roll / cond-flush ops per
2048-row block; measured on v5e it sustains only ~22-45 Mrows/s in context
because every block is ~10 separate XLA ops and the Pallas histogram calls
inside the dynamic while_loop cannot pipeline. This kernel internalizes the
whole walk:

  * the parent leaf's contiguous segment streams HBM -> VMEM once, with
    double-buffered DMA;
  * each block stably partitions via ONE dest-indexed one-hot MXU matmul
    (dest = carry_offset + rank, so the carry append costs nothing extra);
  * left rows flush in place into the PARENT's residency array (the left
    write cursor can never overtake the read cursor); right rows flush to
    the OTHER array at the same global offsets (dual residency);
  * the SMALLER child's histogram accumulates in VMEM whenever that stream
    flushes a full block — histogram work is n_smaller rows exactly, like the
    reference's smaller-leaf trick (serial_tree_learner.cpp:404);
  * `mode=1` turns the kernel into a plain segment histogram (used for the
    root), skipping all partition work.

Dual residency (round 4): every leaf segment owns the SAME address range
[start, start+count) in both arrays but is live in exactly one of them,
tracked by a per-leaf side bit. A split reads the parent from its side,
keeps the left child there, and writes the right child to the other array —
whose bytes in that range are dead by induction (they were the parent's
range). This removes the whole copy-back pass of the previous design, which
re-streamed the entire right child (read scratch + read work + blend +
write) after every split — about a third of the old kernel's DMA traffic.
The grower merges the two arrays once per tree (ops/grower_compact.py).

Alignment: Mosaic requires dynamic DMA offsets provably divisible by the
sublane tiling (8 rows; 32 covers int8 packing), so the segment start is
rounded down to 32 and the `phi` pre-segment rows ride the left stream as
preserved head rows (they rank first in block 0, flush back to their original
slots, and are masked out of the histogram). The right stream's first block
similarly spans `psi` pre-rows and its last block may overrun the segment —
both are read-modify-write blended against the destination array so live
neighbour segments resident there survive. All DMA offsets in the kernel are
of the form `32*t + k*BS`, which the compiler can prove aligned.

Numerics: row bytes move through the permutation matmul as (byte - 128) int8
values at 2x the bf16 MXU rate (one-hot contraction, i32 accumulate — exact;
a spare padding lane carries the per-slot receive indicator so the offset is
undone exactly at flush). With no spare lane the kernel falls back to bf16
(0..255 exact, f32 accumulate). Histogram channels use the same hi/lo-bf16
split as ops/pallas_histogram.py: counts exact, grad/hess ~2^-17 relative.

Batched-M histogram pipeline (round 6): the histogram contraction's output
has only 8 rows (the channel count), so a per-block issue runs at M=8 of the
MXU's 128 rows — the round-5 decomposition's dominant waste. The kernel now
stages K = ``mbatch`` row blocks (bins + TRANSPOSED [8, bs] channel
operands) in a pending ring and issues ONE contraction per feature group
with a block-diagonal [8K, K*bs] channel LHS against the K blocks'
row-concatenated one-hots — M = 8K = 64-128 MXU rows per issue, the TPU
analogue of the reference CUDA constructor accumulating many row-blocks per
launch (cuda_histogram_constructor.cu:17-68). The drain flushes the
``pushes % K`` remainder exactly (stale slots zero out on the channel side).
If Mosaic relayouts dominate at B <= 64 despite the batching, the next
fallback is the bins-on-sublanes layout (VERDICT r5 attack (c)): transpose
the ONE-HOT operand instead so bins provide the M rows — not implemented
while the block-diagonal path holds.
"""
from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is TPU/Mosaic only; CPU tests use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams after 0.4.x, and the
    # has_side_effects field only exists on the newer class; the kernel's
    # outputs are always consumed, so on older jax the flag is safely absent
    _cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    try:
        _SIDE_EFFECT_PARAMS = _cls(has_side_effects=True)
    except TypeError:
        _SIDE_EFFECT_PARAMS = _cls()
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from .compact import RowLayout

_A = 32  # row alignment every DMA offset is provably divisible by

# ---- scoped-VMEM accounting (shared with boosting/gbdt.py and tpulint) ----
# The kernel's fixed streaming buffers (inbuf/carries/stages/aux) scale with
# block_size * num_cols; 49152 is the empirical bs*C product the round-3
# kernel tolerated on v5e. The batched-M pending ring (hist_flush) ADDS
# mbatch-proportional residency: the staged bin blocks, the transposed
# channel slots, and the per-feature-group one-hot + block-diagonal
# transients of the ONE big contraction — so the block size must shrink as
# the ring deepens, bounded by _VMEM_RING_BUDGET.
_VMEM_STREAM_CAP = 49152
_VMEM_RING_BUDGET = 4 << 20


def fused_ring_bytes(block_size: int, num_cols: int, mbatch: int,
                     quant: bool = False, hist_layout: str = "lane") -> int:
    """Scoped-VMEM bytes of the pending ring + its flush transients.

    Counted per slot: the [bs, C] u8 bin block (``num_cols`` already
    reflects the nibble-packed width under RowLayout.packed4 — the packed
    layout halves this term, it does not escape the accounting), the
    channel operand, the row-concatenated one-hot of one feature group
    (<= 512 lanes bf16, which covers the int8 layout too), and the
    block-diagonal channel operand of the batched contraction.

    ``hist_layout``: the lane layout stages channels TRANSPOSED [8, bs]
    (bf16 padded to 16 sublanes / int8 to 32); the sublane layout stages
    them row-major [bs, 8], which the VMEM tiling pads to the full
    128-lane width — a 4-8x larger channel-slot term that must be charged,
    not assumed away."""
    elt = 1 if quant else 2
    bins = mbatch * block_size * num_cols
    if hist_layout == "sublane":
        cht = mbatch * block_size * 128 * elt
    else:
        cht = mbatch * (32 if quant else 16) * block_size * elt
    oh = mbatch * block_size * 512 * elt
    diag = 8 * mbatch * mbatch * block_size * elt
    return bins + cht + oh + diag


def fused_block_cap(num_cols: int, mbatch: int, quant: bool = False,
                    hist_layout: str = "lane") -> int:
    """Largest 32-multiple block size whose streaming buffers AND pending
    ring fit the scoped-VMEM caps (the automatic derivation and the
    LGBM_TPU_FUSED_BS clamp both go through here)."""
    bs = max(32, (_VMEM_STREAM_CAP // max(num_cols, 1)) // 32 * 32)
    while bs > 32 and fused_ring_bytes(bs, num_cols, mbatch, quant,
                                       hist_layout) > _VMEM_RING_BUDGET:
        bs -= 32
    return bs

# sp scalar-prefetch vector layout (i32[16])
_MODE, _BASE_T, _PHI, _COUNT, _NLEFT, _FEAT, _BIN, _DLEFT, _NANBIN, _ISCAT, \
    _SMALLER_L, _RBASE_T, _PSI, _SIDE = range(14)

# smem bookkeeping slots
_LCNT, _RCNT, _LF, _RF, _CBW, _PEND = range(6)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _hist_packing(f: int, b: int):
    """Histogram lane packing: (bin stride per feature, padded feature
    count, matmul group width in features).

    Bin counts that tile 128 lanes exactly (64/32/16/128/256...) pack
    tightly — at B <= 64 that fits 2+ features per lane tile (the
    reference's GPU learner defaults to 63 bins for the same reason,
    ref: docs/GPU-Performance.rst:133). Awkward bin counts whose
    lcm(b, 128) exceeds the 512-lane matmul target fall back to
    128-padded strides so the one-hot operand stays bounded."""
    align = 128 // math.gcd(b, 128)
    stride = b
    if align * b > 512:
        stride = _round_up(b, 128)
        align = 1
    f_pad = _round_up(f, align)
    group = align * max(1, 512 // (align * stride))
    return stride, f_pad, group


def _assemble_f32(blk_i32, off: int):
    """4 u8 lanes at static offset ``off`` -> f32 column [BS, 1].

    Assembles via multiplies, NOT shifts: Mosaic miscompiles `<< 16` on
    values cast from u8 (observed on v5e: some lanes come back zero), while
    integer multiply wraps correctly — byte3 * 2^24 overflowing into the sign
    bit is exactly the bit pattern we want.
    """
    w = (blk_i32[:, off:off + 1] + blk_i32[:, off + 1:off + 2] * 256
         + blk_i32[:, off + 2:off + 3] * 65536
         + blk_i32[:, off + 3:off + 4] * 16777216)
    return lax.bitcast_convert_type(w, jnp.float32)


def _fused_kernel(sp_ref, bits_ref, work_in, scr_in, work_out, scr_out,
                  hist_ref, sem_in, sem_l, sem_r, sem_aux, inbuf, lcarry,
                  rcarry, lstage, rstage, auxbuf, pendbuf, pendch, smem, *,
                  layout: RowLayout, num_bins: int, bs: int,
                  bitset_words: int, use_int8: bool,
                  interpret: bool, dual: bool,
                  hist_debug: str = "", quant: bool = False,
                  mbatch: int = 1, hist_layout: str = "lane"):
    # dual=True: dual residency — rights land LIVE in the other array at the
    #   same offsets (RMW blends protect neighbour segments; auxbuf=[bs,C]
    #   rmw buffer, sem_aux=single DMA sem). The grower merges once per tree.
    # dual=False: copy-back — side must be 0, rights stage through scratch
    #   (garbage there is dead) and a copy-back epilogue blends them into
    #   work (auxbuf=[2,bs,C] staging ring, sem_aux=(2,) DMA sems). This is
    #   the round-3 behavior, kept as a bisect probe and safe fallback.
    F = layout.num_features
    C = layout.num_cols
    B = num_bins
    BS_, F_pad, _ = _hist_packing(F, B)   # BS_: bin stride per feature
    packed4 = layout.packed4
    i32 = jnp.int32

    def bin_col(bins_i32, j):
        """Bin column of LOGICAL feature ``j`` (static) as [bs, 1] i32.

        packed4 records store two features per byte: the byte at column
        j >> 1 carries feature j in the nibble selected by j & 1. The
        & 0xF mask is load-bearing — without it the neighbour feature's
        nibble rides along and every one-hot compare mismatches
        (tpulint R004 flags unmasked pack4 nibble extracts)."""
        if packed4:
            byte = bins_i32[:, j // 2:j // 2 + 1]
            return (byte >> (4 * (j % 2))) & 0xF
        return bins_i32[:, j:j + 1]

    mode = sp_ref[_MODE]
    base = sp_ref[_BASE_T] * _A
    phi = sp_ref[_PHI]
    count = sp_ref[_COUNT]
    n_left = sp_ref[_NLEFT]
    feature = sp_ref[_FEAT]
    bin_ = sp_ref[_BIN]
    default_left = sp_ref[_DLEFT]
    nan_bin = sp_ref[_NANBIN]
    is_cat = sp_ref[_ISCAT]
    smaller_left = sp_ref[_SMALLER_L]
    rbase = sp_ref[_RBASE_T] * _A
    psi = sp_ref[_PSI]
    side = sp_ref[_SIDE]

    start = base + phi
    span = phi + count
    nblocks = (span + bs - 1) // bs
    n_rows = work_out.shape[0]          # static padded row count

    def clamp_base(b):
        """Clamp a 32-aligned row base into [0, n_rows - bs], keeping the
        provable alignment Mosaic's DMA checker needs (t * 32 form).
        Defense-in-depth: a split whose scan-side n_left disagrees with the
        kernel's own routing (garbage histograms, or a latent scan bug) must
        corrupt data at worst — never DMA outside the arrays and fault the
        worker."""
        cap_t = (n_rows - bs) // _A
        return jnp.clip(b // _A, 0, cap_t) * _A

    hist_ref[:, :] = jnp.zeros_like(hist_ref)
    smem[_LCNT] = 0
    smem[_RCNT] = psi
    smem[_LF] = 0
    smem[_RF] = 0
    smem[_CBW] = 0
    smem[_PEND] = 0
    lcarry[:, :] = jnp.zeros_like(lcarry)
    rcarry[:, :] = jnp.zeros_like(rcarry)
    auxbuf[...] = jnp.zeros_like(auxbuf)

    iota = lax.broadcasted_iota(i32, (bs, 1), 0)[:, 0]
    lane = lax.broadcasted_iota(i32, (bs, C), 1)
    io2 = lax.broadcasted_iota(i32, (bs, bs), 0)
    jo2 = lax.broadcasted_iota(i32, (bs, bs), 1)
    # strict lower triangular: ranks via MXU (int8 runs at 2x bf16 rate)
    lt = (io2 > jo2).astype(jnp.int8 if use_int8 else jnp.bfloat16)
    iota4 = lax.broadcasted_iota(i32, (4 * bs, bs), 0)

    def carry_block_i32(c):
        """First BS carry rows as exact [BS, C] i32 byte values.

        int8 mode stores carries in offset form (byte - 128, with lane C-1
        carrying the receive indicator from the permutation matmul); the
        +128 correction applies only to filled slots and the indicator lane
        is zeroed so flushed bytes match the bf16/XLA paths bit-for-bit."""
        if use_int8:
            fixed = c[:bs] + 128 * c[:bs, C - 1:C]
            return jnp.where(lane == C - 1, 0, fixed)
        return c[:bs].astype(i32)

    def start_read(i, slot):
        """Issue the parent-segment block read from its residency array."""
        if not dual:
            pltpu.make_async_copy(
                work_out.at[pl.ds(base + i * bs, bs), :], inbuf.at[slot],
                sem_in.at[slot]).start()
            return

        @pl.when(side == 0)
        def _():
            pltpu.make_async_copy(
                work_out.at[pl.ds(base + i * bs, bs), :], inbuf.at[slot],
                sem_in.at[slot]).start()

        @pl.when(side != 0)
        def _():
            pltpu.make_async_copy(
                scr_out.at[pl.ds(base + i * bs, bs), :], inbuf.at[slot],
                sem_in.at[slot]).start()

    def wait_read(slot):
        # wait is by semaphore + transfer size; the source ref is a stand-in
        pltpu.make_async_copy(
            work_out.at[pl.ds(0, bs), :], inbuf.at[slot],
            sem_in.at[slot]).wait()

    def rmw_read(off):
        """Synchronously fetch one block of the right-destination array
        (dual residency only — the destination may hold live neighbours)."""
        off = clamp_base(off)

        @pl.when(side == 0)
        def _():
            pltpu.make_async_copy(
                scr_out.at[pl.ds(off, bs), :], auxbuf, sem_aux).start()

        @pl.when(side != 0)
        def _():
            pltpu.make_async_copy(
                work_out.at[pl.ds(off, bs), :], auxbuf, sem_aux).start()
        pltpu.make_async_copy(
            work_out.at[pl.ds(0, bs), :], auxbuf, sem_aux).wait()

    def assemble_ch8(rows_u8, mask_f32):
        """Masked rows of a [BS, C] u8 buffer -> the [BS, 8] channel operand.

        f32 mode (bf16 output): (grad-hi, hess-hi, in-bag, raw, grad-lo,
        hess-lo, 0, 0) — the hi/lo split recovers ~f32 accuracy.
        quant mode (int8 output): the PACKED integer channel layout
        (qgrad, qhess, in-bag, raw, 0, 0, 0, 0) — the grad/hess columns
        hold small integer discretizer codes (exact in f32), so the hi/lo
        split collapses and the one-hot contraction runs
        int8 x int8 -> int32 at 2x the bf16 MXU rate with exact sums."""
        rows = rows_u8.astype(i32)
        m = mask_f32[:, None]                              # [BS, 1]
        g = _assemble_f32(rows, layout.grad_off) * m
        h = _assemble_f32(rows, layout.hess_off) * m
        cw = _assemble_f32(rows, layout.cnt_off)
        inbag = jnp.where(cw != 0.0, m, 0.0)
        lane8 = lax.broadcasted_iota(i32, (bs, 8), 1)
        if quant:
            chq = [g, h, inbag, m]
            ch8 = jnp.zeros((bs, 8), jnp.float32)
            for k, c in enumerate(chq):
                ch8 = ch8 + jnp.where(lane8 == k, c, 0.0)
            # f32 -> int8 is exact: codes are integers with |code| <= 127
            return ch8.astype(i32).astype(jnp.int8)
        if interpret:
            # interpret mode traces through XLA, where
            # --xla_allow_excess_precision elides f32->bf16->f32 as identity
            # (zeroing the lo channels); reduce_precision is not elidable
            ghi = lax.reduce_precision(g, exponent_bits=8, mantissa_bits=7)
            hhi = lax.reduce_precision(h, exponent_bits=8, mantissa_bits=7)
        else:
            # Mosaic has no reduce_precision lowering and does not elide the
            # round-trip today (verified on v5e)
            ghi = g.astype(jnp.bfloat16).astype(jnp.float32)
            hhi = h.astype(jnp.bfloat16).astype(jnp.float32)
        chans = [ghi, hhi, inbag, m, g - ghi, h - hhi,
                 jnp.zeros_like(g), jnp.zeros_like(g)]
        ch8 = jnp.zeros((bs, 8), jnp.float32)
        for k, c in enumerate(chans):
            ch8 = ch8 + jnp.where(lane8 == k, c, 0.0)
        return ch8.astype(jnp.bfloat16)

    def hist_matmuls(rows_u8, ch8):
        """One-hot contraction of a block's bins against its channel
        operand, accumulated into hist_ref.

        The one-hot for a feature group is built as a per-feature compare
        of that feature's bin column against a [BS, BS_] lane iota, with
        the per-feature results concatenated group-wide so each group is
        contracted in ONE MXU matmul (grouping bounds the one-hot operand
        near 512 lanes, see _hist_packing). A jnp.repeat-based batched
        lane spread was tried instead of the per-feature compare loop and
        lowers to far slower relayouts on this Mosaic toolchain (0.54 vs
        1.07 it/s on the 10.5M higgs bench)."""
        bins = rows_u8.astype(i32)[:, :layout.feat_cols]
        # tightly packed: each feature spans B lanes (not 128-padded), so
        # B <= 64 fits 2+ features per lane tile; group widths and offsets
        # stay 128-aligned via the align unit from _hist_packing
        # (a jnp.repeat-based batched lane spread was tried and lowers to
        # far slower relayouts on this Mosaic toolchain: 0.54 vs 1.07 it/s
        # on the 10.5M higgs bench)
        _, _, w = _hist_packing(F, B)   # group width (features)
        iota_b = lax.broadcasted_iota(i32, (bs, BS_), 1)
        zero_col = jnp.full((bs, 1), -1, i32)   # matches no bin lane
        # quant: int8 one-hot x int8 packed channels -> int32 (exact, 2x
        # MXU rate); f32: bf16 one-hot with f32 accumulation
        oh_t = jnp.int8 if quant else jnp.bfloat16
        acc_t = jnp.int32 if quant else jnp.float32
        fc = 0
        while fc < F_pad:
            wc = min(w, F_pad - fc)
            oh = jnp.concatenate(
                [((bin_col(bins, fc + j) if fc + j < F else zero_col)
                  == iota_b).astype(oh_t)
                 for j in range(wc)], axis=1)            # [BS, wc*BS_]
            part = lax.dot_general(
                ch8, oh, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=acc_t)            # [8, wc*BS_]
            hist_ref[:, fc * BS_:(fc + wc) * BS_] += part
            fc += wc

    cht = jnp.int8 if quant else jnp.bfloat16
    eye_bs = (io2 == jo2).astype(cht)   # transpose-by-matmul identity

    def transpose_ch(ch8):
        """[bs, 8] channel operand -> [8, bs] via an identity contraction.

        Mosaic relayout transposes are catastrophically slow on this
        toolchain (see hist_matmuls), so the transpose rides the MXU:
        ch8^T = ch8^T @ I. Exact: one nonzero per output element, i32
        accumulation for int8 codes / f32 for bf16 channels (whose values
        are already bf16-representable, so the round-trip cast is exact)."""
        if quant:
            return lax.dot_general(
                ch8, eye_bs, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=i32).astype(jnp.int8)
        return lax.dot_general(
            ch8, eye_bs, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)

    def hist_flush(n_valid):
        """ONE batched one-hot contraction per feature group over the first
        ``n_valid`` staged blocks of the pending ring (the batched-M
        tentpole): the staged transposed channel operands form a
        block-diagonal [8K, K*bs] LHS and the staged blocks' one-hots
        concatenate row-wise into a [K*bs, group] RHS, so each MXU issue
        carries M = 8*mbatch output rows (64-128 at K=8-16) instead of 8.
        The K per-block partial sums come back stacked on the sublane axis
        and reduce with K-1 vector adds. Slots past ``n_valid`` (a partial
        drain, or stale data from a previous ring wrap) are zeroed on the
        channel side, so whatever their bins one-hot into contributes
        exactly zero — counts stay bit-identical to the K=1 sync path and
        int32 quantized sums stay exact.

        hist_layout="sublane" (tpu_hist_layout, the B <= 64 Mosaic
        layout): the SAME staged operands contract with swapped roles —
        channels stay row-major [bs, 8] (no transpose matmul per push),
        tile into the [K*bs, 8K] lane-banded RHS, and the one-hot LHS
        contracts over its sublane axis, so the output lands BIN-major
        [group, 8K] with bins along sublanes; the K row-window partials
        sit in lane bands and reduce with K-1 adds of [group, 8] slices.
        Counts/int32 sums stay bit-identical (same products, regrouped)."""
        bins_k = [pendbuf[t].astype(i32)[:, :layout.feat_cols]
                  for t in range(mbatch)]
        _, _, w = _hist_packing(F, B)
        iota_b = lax.broadcasted_iota(i32, (bs, BS_), 1)
        zero_col = jnp.full((bs, 1), -1, i32)
        oh_t = jnp.int8 if quant else jnp.bfloat16
        acc_t = jnp.int32 if quant else jnp.float32

        def group_ohs(fc, wc):
            return [jnp.concatenate(
                [((bin_col(bins, fc + j) if fc + j < F else zero_col)
                  == iota_b).astype(oh_t)
                 for j in range(wc)], axis=1)             # [bs, wc*BS_]
                for bins in bins_k]

        if hist_layout == "sublane":
            bands = []
            for t in range(mbatch):
                chR = pendch[t]                           # [bs, 8]
                chR = jnp.where(n_valid > t, chR, jnp.zeros_like(chR))
                parts = []
                if t:
                    parts.append(jnp.zeros((bs, t * 8), cht))
                parts.append(chR)
                if mbatch - 1 - t:
                    parts.append(jnp.zeros((bs, (mbatch - 1 - t) * 8), cht))
                bands.append(parts[0] if len(parts) == 1
                             else jnp.concatenate(parts, axis=1))
            ch_bd = (bands[0] if mbatch == 1
                     else jnp.concatenate(bands, axis=0))  # [K*bs, 8K]
            fc = 0
            while fc < F_pad:
                wc = min(w, F_pad - fc)
                ohs = group_ohs(fc, wc)
                oh = ohs[0] if mbatch == 1 \
                    else jnp.concatenate(ohs, axis=0)      # [K*bs, wc*BS_]
                part = lax.dot_general(
                    oh, ch_bd, dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=acc_t)          # [wc*BS_, 8K]
                red = part[:, 0:8]
                for t in range(1, mbatch):
                    red = red + part[:, 8 * t:8 * (t + 1)]
                hist_ref[fc * BS_:(fc + wc) * BS_, :] += red
                fc += wc
            return

        blocks = []
        for t in range(mbatch):
            chT = pendch[t]                               # [8, bs]
            chT = jnp.where(n_valid > t, chT, jnp.zeros_like(chT))
            parts = []
            if t:
                parts.append(jnp.zeros((8, t * bs), cht))
            parts.append(chT)
            if mbatch - 1 - t:
                parts.append(jnp.zeros((8, (mbatch - 1 - t) * bs), cht))
            blocks.append(parts[0] if len(parts) == 1
                          else jnp.concatenate(parts, axis=1))
        ch_diag = (blocks[0] if mbatch == 1
                   else jnp.concatenate(blocks, axis=0))  # [8K, K*bs]
        fc = 0
        while fc < F_pad:
            wc = min(w, F_pad - fc)
            ohs = group_ohs(fc, wc)
            oh = ohs[0] if mbatch == 1 else jnp.concatenate(ohs, axis=0)
            part = lax.dot_general(
                ch_diag, oh, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=acc_t)             # [8K, wc*BS_]
            red = part[0:8]
            for t in range(1, mbatch):
                red = red + part[8 * t:8 * (t + 1)]
            hist_ref[:, fc * BS_:(fc + wc) * BS_] += red
            fc += wc

    def hist_accum(rows_u8, mask_f32):
        """Batched-M histogram push: the block's channel operand is
        assembled and transposed NOW (VPU chain + one tiny M=8 matmul),
        staged into the K-deep pending ring, and the one-hot contractions
        issue once per K pushes as ONE M=8K matmul per feature group
        (hist_flush) — both deferring the MXU work off the assembly's
        critical path (the round-5 double buffer's job, measured ~0.6
        s/tree on v5e) and filling the MXU rows the M=8 issue wasted."""
        if hist_debug == "off":
            return  # timing bisect: histograms disabled (results invalid)
        if hist_debug == "assembly":
            ch8 = assemble_ch8(rows_u8, mask_f32)
            ones = jnp.ones((bs, 128), jnp.bfloat16)
            hist_ref[:, 0:128] += lax.dot_general(
                ch8, ones, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return
        if hist_debug == "matmul":
            hist_matmuls(rows_u8, jnp.ones((bs, 8), jnp.bfloat16))
            return
        if hist_debug == "matmul2":
            # data-dependent but trivially cheap ch8: defeats constant
            # folding/hoisting so the matmuls' true cost is measured
            cheap = (rows_u8[:, :8].astype(i32) + 1).astype(jnp.bfloat16)
            hist_matmuls(rows_u8, cheap)
            return
        if hist_debug == "sync":
            # the pre-pipelining, pre-batching behavior (timing comparison)
            hist_matmuls(rows_u8, assemble_ch8(rows_u8, mask_f32))
            return

        pushes = smem[_PEND]
        cur = lax.rem(pushes, mbatch)
        pendbuf[cur] = rows_u8
        if hist_layout == "sublane":
            # bins-on-sublanes flush contracts row-major channels — the
            # per-push transpose matmul disappears entirely
            pendch[cur] = assemble_ch8(rows_u8, mask_f32)
        else:
            pendch[cur] = transpose_ch(assemble_ch8(rows_u8, mask_f32))
        smem[_PEND] = pushes + 1

        @pl.when(cur == mbatch - 1)
        def _():
            hist_flush(jnp.asarray(mbatch, i32))

    def hist_drain():
        """Flush the partial pending batch (end of kernel): exactly the
        ``pushes % mbatch`` blocks staged since the last full-ring flush."""
        pushes = smem[_PEND]
        pending = lax.rem(pushes, mbatch)

        @pl.when(pending > 0)
        def _():
            hist_flush(pending)
            smem[_PEND] = pushes - pending

    def stage_flush(stream, data_u8, hbm_base, do_hist, hist_mask):
        """Write one full block via the stream's staging ring; maybe hist."""
        stage, sem, cslot = ((lstage, sem_l, _LF) if stream == 0
                             else (rstage, sem_r, _RF))
        # left stream writes the parent's residency array, right the other
        to_work = (side == 0) if stream == 0 else (side != 0)
        cnt = smem[cslot]
        slot = lax.rem(cnt, 2)

        @pl.when(cnt >= 2)
        def _():
            pltpu.make_async_copy(
                stage.at[slot], work_out.at[pl.ds(0, bs), :],
                sem.at[slot]).wait()

        stage[slot] = data_u8
        hbm_base = clamp_base(hbm_base)

        @pl.when(to_work)
        def _():
            pltpu.make_async_copy(
                stage.at[slot], work_out.at[pl.ds(hbm_base, bs), :],
                sem.at[slot]).start()

        @pl.when(jnp.logical_not(to_work))
        def _():
            pltpu.make_async_copy(
                stage.at[slot], scr_out.at[pl.ds(hbm_base, bs), :],
                sem.at[slot]).start()

        @pl.when(do_hist)
        def _():
            hist_accum(stage[slot], hist_mask)
        smem[cslot] = cnt + 1

    def drain(stream):
        stage, sem, cslot = ((lstage, sem_l, _LF) if stream == 0
                             else (rstage, sem_r, _RF))
        cnt = smem[cslot]
        for back in (2, 1):
            @pl.when(cnt >= back)
            def _():
                slot = lax.rem(cnt - back, 2)
                pltpu.make_async_copy(
                    stage.at[slot], work_out.at[pl.ds(0, bs), :],
                    sem.at[slot]).wait()

    # ---------------- main walk ----------------
    @pl.when(nblocks > 0)
    def _():
        start_read(0, 0)

    def body(i, _):
        slot = lax.rem(i, 2)

        @pl.when(i + 1 < nblocks)
        def _():
            start_read(i + 1, lax.rem(i + 1, 2))

        wait_read(slot)
        blk_u8 = inbuf[slot]
        blk = blk_u8.astype(i32)
        g_idx = base + i * bs + iota
        in_seg = jnp.logical_and(g_idx >= start, g_idx < start + count)

        @pl.when(mode == 1)
        def _():
            hist_accum(blk_u8, in_seg.astype(jnp.float32))

        @pl.when(mode == 0)
        def _():
            head = g_idx < start
            if packed4:
                # two features per byte: select the byte column, then the
                # nibble (the & 0xF mask strips the neighbour feature)
                byte = jnp.sum(
                    jnp.where(lane == (feature >> 1), blk, 0), axis=1)
                col = (byte >> ((feature & 1) * 4)) & 0xF
            else:
                col = jnp.sum(jnp.where(lane == feature, blk, 0), axis=1)
            # routing predicate — mirrors ops/split.py go_left_pred
            gl_num = jnp.logical_or(
                col <= bin_,
                jnp.logical_and(default_left != 0, col == nan_bin))
            word = col >> 5
            bw = jnp.zeros_like(col)
            for wd in range(bitset_words):
                bw = jnp.where(word == wd, bits_ref[wd].astype(i32), bw)
            gl_cat = ((bw >> (col & 31)) & 1) != 0
            # no select on i1 vectors in Mosaic — combine logically
            gl = jnp.logical_or(jnp.logical_and(is_cat != 0, gl_cat),
                                jnp.logical_and(is_cat == 0, gl_num))
            sel_l = jnp.logical_or(jnp.logical_and(gl, in_seg), head)
            sel_r = jnp.logical_and(jnp.logical_not(gl), in_seg)

            lane2 = lax.broadcasted_iota(i32, (bs, 2), 1)
            sel2i = jnp.where(lane2 == 0,
                              sel_l.astype(i32)[:, None],
                              sel_r.astype(i32)[:, None])
            if use_int8:
                ranks = lax.dot_general(
                    lt, sel2i.astype(jnp.int8),
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=i32)                 # [BS, 2]
            else:
                ranks = lax.dot_general(
                    lt, sel2i.astype(jnp.float32).astype(jnp.bfloat16),
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(i32)
            rank_l = ranks[:, 0]
            rank_r = ranks[:, 1]
            nl_b = jnp.sum(sel_l.astype(i32))
            nr_b = jnp.sum(sel_r.astype(i32))

            lcnt = smem[_LCNT]
            rcnt = smem[_RCNT]
            dest = jnp.where(
                sel_l, lcnt + rank_l,
                jnp.where(sel_r, 2 * bs + rcnt + rank_r, 4 * bs))
            oh = (iota4 == dest[None, :])                       # [4BS, BS] i1
            if use_int8:
                # bytes ride the MXU as (b - 128) int8; lane C-1 is repurposed
                # as a constant 1 so each dest slot also receives a "filled"
                # indicator, letting carry_block_i32 undo the offset exactly
                blk8 = jnp.where(lane == C - 1, 1, blk - 128).astype(jnp.int8)
                comp = lax.dot_general(
                    oh.astype(jnp.int8), blk8,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=i32)                 # [4BS, C]
            else:
                blk_bf = blk.astype(jnp.float32).astype(jnp.bfloat16)
                comp = lax.dot_general(
                    oh.astype(jnp.bfloat16), blk_bf,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            lcarry[:, :] = lcarry[:, :] + comp[:2 * bs]
            rcarry[:, :] = rcarry[:, :] + comp[2 * bs:]

            new_l = lcnt + nl_b
            new_r = rcnt + nr_b

            @pl.when(new_l >= bs)
            def _():
                lf = smem[_LF]
                h0 = jnp.where(lf == 0, phi, 0)
                stage_flush(
                    0, carry_block_i32(lcarry).astype(jnp.uint8),
                    base + lf * bs, smaller_left == 1,
                    (iota >= h0).astype(jnp.float32))
                lcarry[:, :] = jnp.concatenate(
                    [lcarry[bs:], jnp.zeros_like(lcarry[:bs])], axis=0)
            smem[_LCNT] = new_l - bs * (new_l >= bs).astype(i32)

            @pl.when(new_r >= bs)
            def _():
                rf = smem[_RF]
                h0 = jnp.where(rf == 0, psi, 0)
                if dual:
                    @pl.when(rf == 0)
                    def _():
                        # RMW blend: the psi pre-rows belong to a segment
                        # that may be live in the destination array
                        rmw_read(rbase)
                    keep = jnp.logical_and(rf == 0, iota < psi)
                    data = jnp.where(keep[:, None], auxbuf[:, :].astype(i32),
                                     carry_block_i32(rcarry))
                else:
                    # copy-back mode: the psi head slots land in dead
                    # scratch bytes; no blend needed
                    data = carry_block_i32(rcarry)
                stage_flush(
                    1, data.astype(jnp.uint8),
                    rbase + rf * bs, smaller_left == 0,
                    (iota >= h0).astype(jnp.float32))
                rcarry[:, :] = jnp.concatenate(
                    [rcarry[bs:], jnp.zeros_like(rcarry[:bs])], axis=0)
            smem[_RCNT] = new_r - bs * (new_r >= bs).astype(i32)
        return 0

    lax.fori_loop(0, nblocks, body, 0)

    # ---------------- tails ----------------
    @pl.when(jnp.logical_and(mode == 0, count > 0))
    def _():
        lcnt = smem[_LCNT]
        rcnt = smem[_RCNT]

        @pl.when(lcnt > 0)
        def _():
            lf = smem[_LF]
            # RMW blend: rows beyond lcnt may belong to a live neighbour
            # (read from the parent's own residency array — lefts stay there)
            start_read_at = base + lf * bs
            if not dual:
                pltpu.make_async_copy(
                    work_out.at[pl.ds(start_read_at, bs), :], inbuf.at[0],
                    sem_in.at[0]).start()
            else:
                @pl.when(side == 0)
                def _():
                    pltpu.make_async_copy(
                        work_out.at[pl.ds(start_read_at, bs), :],
                        inbuf.at[0], sem_in.at[0]).start()

                @pl.when(side != 0)
                def _():
                    pltpu.make_async_copy(
                        scr_out.at[pl.ds(start_read_at, bs), :],
                        inbuf.at[0], sem_in.at[0]).start()
            wait_read(0)
            blend = jnp.where(
                (iota < lcnt)[:, None], carry_block_i32(lcarry),
                inbuf[0].astype(i32)).astype(jnp.uint8)
            h0 = jnp.where(lf == 0, phi, 0)
            mask = jnp.logical_and(iota >= h0, iota < lcnt)
            stage_flush(0, blend, base + lf * bs, smaller_left == 1,
                        mask.astype(jnp.float32))

        @pl.when(rcnt > 0)
        def _():
            rf = smem[_RF]
            h0 = jnp.where(rf == 0, psi, 0)
            valid = jnp.logical_and(iota >= h0, iota < rcnt)
            if dual:
                # RMW blend against the destination array: the psi head rows
                # (rf == 0) and everything beyond rcnt may be live neighbours
                rmw_read(rbase + rf * bs)
                data = jnp.where(valid[:, None], carry_block_i32(rcarry),
                                 auxbuf[:, :].astype(i32))
            else:
                # copy-back mode: full-block write, overrun lands in dead
                # scratch bytes
                data = carry_block_i32(rcarry)
            stage_flush(1, data.astype(jnp.uint8),
                        rbase + rf * bs, smaller_left == 0,
                        valid.astype(jnp.float32))

        drain(0)
        drain(1)

        if not dual:
            # ------------- copy-back of the right stream -------------
            # blend the scratch-staged right rows into work over the exact
            # row range; neighbours resident in work survive bit-for-bit
            n_right_cb = count - n_left
            nb_cb = (psi + n_right_cb + bs - 1) // bs

            def cb_body(t, _):
                win = clamp_base(rbase + t * bs)
                d1 = pltpu.make_async_copy(
                    scr_out.at[pl.ds(win, bs), :], inbuf.at[0], sem_in.at[0])
                d2 = pltpu.make_async_copy(
                    work_out.at[pl.ds(win, bs), :], inbuf.at[1], sem_in.at[1])
                d1.start()
                d2.start()
                d1.wait()
                d2.wait()
                g = win + iota
                keep = jnp.logical_and(g >= start + n_left,
                                       g < start + count)
                out = jnp.where(keep[:, None], inbuf[0].astype(i32),
                                inbuf[1].astype(i32)).astype(jnp.uint8)
                cw = smem[_CBW]
                slot = lax.rem(cw, 2)

                @pl.when(cw >= 2)
                def _():
                    pltpu.make_async_copy(
                        auxbuf.at[slot], work_out.at[pl.ds(0, bs), :],
                        sem_aux.at[slot]).wait()
                auxbuf[slot] = out
                pltpu.make_async_copy(
                    auxbuf.at[slot], work_out.at[pl.ds(win, bs), :],
                    sem_aux.at[slot]).start()
                smem[_CBW] = cw + 1
                return 0

            lax.fori_loop(0, nb_cb, cb_body, 0)
            cw = smem[_CBW]
            for back in (2, 1):
                @pl.when(cw >= back)
                def _():
                    pltpu.make_async_copy(
                        auxbuf.at[lax.rem(cw - back, 2)],
                        work_out.at[pl.ds(0, bs), :],
                        sem_aux.at[lax.rem(cw - back, 2)]).wait()

    # deferred histogram block from the software pipeline (both modes)
    hist_drain()


@functools.partial(
    jax.jit,
    static_argnames=("layout", "num_bins", "block_size", "bitset_words",
                     "interpret", "dual", "hist_debug", "num_rows", "quant",
                     "mbatch", "hist_layout"))
def fused_split(
    work: jnp.ndarray,          # [N + pad, C] u8, C % 128 == 0
    scratch: jnp.ndarray,       # [N + pad, C] u8
    mode: jnp.ndarray,          # i32: 0 = partition+hist, 1 = hist-only
    start: jnp.ndarray,         # i32 segment start
    count: jnp.ndarray,         # i32 segment rows
    n_left: jnp.ndarray,        # i32 exact left-row count (from the scan)
    feature: jnp.ndarray,
    bin_: jnp.ndarray,
    default_left: jnp.ndarray,  # bool/i32
    nan_bin: jnp.ndarray,
    is_cat: jnp.ndarray,        # bool/i32
    cat_bitset: jnp.ndarray,    # [W] u32
    layout: RowLayout,
    num_bins: int,
    block_size: int = 512,
    bitset_words: int = 8,
    interpret: bool = False,
    smaller_left=None,
    side=None,                  # i32: 0 = parent lives in work, 1 = scratch
    dual: bool = True,
    hist_debug: str = "",       # timing bisect only (see GrowerParams)
    num_rows: int = None,       # real (unpadded) row count, for pad checks
    quant: bool = False,        # packed int8 channel layout -> int32 hist
    mbatch: int = 8,            # batched-M pending-ring depth (1-16)
    hist_layout: str = "lane",  # lane | sublane (tpu_hist_layout, B <= 64)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One fused split. Returns (work', scratch', hist_smaller [F, B, 4]);
    the histogram is int32 when ``quant`` (quantized-gradient codes,
    int8 x int8 -> int32 contraction — see assemble_ch8).

    ``mbatch`` (env/param ``tpu_hist_mbatch``) is the depth of the
    histogram pending ring: K staged row blocks issue ONE one-hot
    contraction per feature group with M = 8K MXU rows (hist_flush)
    instead of K matmuls at M = 8. K = 1 is the sync reference path
    (counts and int32 histograms bit-identical at any K; bf16 grad/hess
    within ~2^-17 relative — the f32 accumulation regroups). The ring
    multiplies histogram-side VMEM residency by K, so callers must size
    ``block_size`` through :func:`fused_block_cap`.

    CONTRACT — pad >= block_size: the row arrays must be padded past the
    real row count by at least ``block_size`` rows (internal callers pad by
    ``fused_block + 32``, boosting/gbdt._setup_compact_state), because the
    kernel's aligned block writes may overrun a segment end by up to one
    block. The scalar sanitization below clamps ``count`` to
    ``n_rows - block_size - start`` as defense-in-depth; with a smaller pad
    that clamp would silently drop legitimate tail rows. Pass ``num_rows``
    (the real row count, a static int) to turn a violated pad contract into
    a static ValueError instead of silent row loss.

    In mode 1 the partition is skipped and the histogram covers the whole
    segment (hist channels: grad, hess, in-bag count, raw count).

    ``smaller_left`` overrides which side's histogram is accumulated —
    the data-parallel learner must histogram the GLOBALLY smaller child on
    every shard even where it is locally the larger one.

    ``side`` selects the parent's residency array (dual residency, see the
    module docstring): the left child stays there, the right child lands in
    the other array at the same global offsets.

    ``dual=False`` selects the copy-back variant: every segment lives in
    ``work`` (side must be 0), rights stage through scratch and a copy-back
    epilogue re-streams them into work. ~1/3 more DMA per split, but no RMW
    blends and no side-dependent DMA — the round-3 design, kept as a safe
    fallback while the dual-residency fault on EFB-bundled deep trees is
    open (see boosting/gbdt._setup_compact_state).
    """
    F = layout.num_features
    C = layout.num_cols
    if C % 128:
        raise ValueError(f"fused_split needs 128-aligned row records, C={C}")
    if block_size % _A:
        raise ValueError(f"block_size must be a multiple of {_A}")
    B = num_bins
    BS_, F_pad, _ = _hist_packing(F, B)
    i32 = jnp.int32

    n_rows = work.shape[0]
    if num_rows is not None:
        pad_rows = n_rows - int(num_rows)
        if pad_rows < block_size:
            raise ValueError(
                f"fused_split pad contract violated: work has {n_rows} rows "
                f"for num_rows={int(num_rows)} real rows (pad={pad_rows}), "
                f"but block_size={block_size} requires pad >= block_size — "
                "the defense-in-depth count clamp would silently drop tail "
                "rows. Pad the row arrays by at least block_size (internal "
                "callers use fused_block + 32).")
    # scalar sanitization (defense-in-depth, no effect on legit inputs):
    # bounds the kernel's block-loop trip counts and read windows even if a
    # caller hands a segment produced from corrupt histograms
    start = jnp.clip(start.astype(i32), 0, n_rows - _A)
    count = jnp.clip(count.astype(i32), 0,
                     jnp.maximum(n_rows - block_size - start, 0))
    n_left = jnp.clip(n_left.astype(i32), 0, count)
    n_left_eff = jnp.where(mode == 1, count, n_left)
    base_t = start // _A
    phi = start - base_t * _A
    rstart = start + n_left_eff
    rbase_t = rstart // _A
    psi = rstart - rbase_t * _A
    n_right = count - n_left_eff
    if smaller_left is None:
        smaller_left = (n_left_eff <= n_right).astype(i32)
    smaller_left = jnp.where(mode == 1, jnp.asarray(1, i32),
                             smaller_left.astype(i32))
    if side is None:
        side = jnp.asarray(0, i32)
    if not dual:
        # the copy-back variant's invariant is that every segment lives in
        # work; enforce it here rather than trusting distant callers
        side = jnp.zeros_like(jnp.asarray(side, i32))
    sp = jnp.stack([
        mode.astype(i32), base_t, phi, count, n_left_eff,
        feature.astype(i32), bin_.astype(i32), default_left.astype(i32),
        nan_bin.astype(i32), is_cat.astype(i32), smaller_left, rbase_t, psi,
        side.astype(i32), jnp.asarray(0, i32), jnp.asarray(0, i32)])

    bs = block_size
    W = bitset_words
    if quant:
        hist_debug = ""     # bisect probes assume the bf16 channel layout
    if hist_layout not in ("lane", "sublane"):
        raise ValueError(f"hist_layout must be 'lane' or 'sublane', "
                         f"got {hist_layout!r}")
    if hist_layout == "sublane":
        hist_debug = ""     # bisect probes assume the lane accumulator
    mbatch = max(1, min(int(mbatch), 16))   # 8*mbatch <= 128 MXU rows
    # int8 MXU path needs one free padding lane for the receive indicator
    use_int8 = layout.num_real_cols < C
    carry_t = jnp.int32 if use_int8 else jnp.float32
    hist_t = jnp.int32 if quant else jnp.float32
    ch_t = jnp.int8 if quant else jnp.bfloat16
    kernel = functools.partial(
        _fused_kernel, layout=layout, num_bins=B, bs=bs, bitset_words=W,
        use_int8=use_int8, interpret=interpret, dual=dual,
        hist_debug=hist_debug, quant=quant, mbatch=mbatch,
        hist_layout=hist_layout)

    work_o, scr_o, hist8 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pltpu.VMEM)],
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),      # sem_in
                pltpu.SemaphoreType.DMA((2,)),      # sem_l
                pltpu.SemaphoreType.DMA((2,)),      # sem_r
                # dual: single rmw sem + [bs, C] rmw buffer;
                # copy-back: (2,) staging sems + [2, bs, C] staging ring
                (pltpu.SemaphoreType.DMA if dual
                 else pltpu.SemaphoreType.DMA((2,))),       # sem_aux
                pltpu.VMEM((2, bs, C), jnp.uint8),  # inbuf
                pltpu.VMEM((2 * bs, C), carry_t),   # lcarry
                pltpu.VMEM((2 * bs, C), carry_t),   # rcarry
                pltpu.VMEM((2, bs, C), jnp.uint8),  # lstage
                pltpu.VMEM((2, bs, C), jnp.uint8),  # rstage
                (pltpu.VMEM((bs, C), jnp.uint8) if dual
                 else pltpu.VMEM((2, bs, C), jnp.uint8)),   # auxbuf
                # batched-M pending ring: K staged bin blocks + their
                # channel operands — TRANSPOSED [8, bs] for the lane
                # layout, row-major [bs, 8] for sublane (hist_flush)
                pltpu.VMEM((mbatch, bs, C), jnp.uint8),   # pendbuf
                (pltpu.VMEM((mbatch, bs, 8), ch_t)
                 if hist_layout == "sublane"
                 else pltpu.VMEM((mbatch, 8, bs), ch_t)),  # pendch
                pltpu.SMEM((8,), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(work.shape, work.dtype),
            jax.ShapeDtypeStruct(scratch.shape, scratch.dtype),
            (jax.ShapeDtypeStruct((F_pad * BS_, 8), hist_t)
             if hist_layout == "sublane"
             else jax.ShapeDtypeStruct((8, F_pad * BS_), hist_t)),
        ],
        input_output_aliases={2: 0, 3: 1},
        compiler_params=_SIDE_EFFECT_PARAMS,
        interpret=interpret,
    )(sp, cat_bitset, work, scratch)

    if hist_layout == "sublane":
        # bin-major accumulator: [F*BS_, 8] -> [F, B, 4] with no transpose
        hb = hist8.reshape(F_pad, BS_, 8)[:F, :B, :]
        hist = hb[:, :, :4] + hb[:, :, 4:]
    else:
        hist8 = hist8.reshape(8, F_pad, BS_)[:, :F, :B]
        hist = jnp.transpose(hist8[:4] + hist8[4:], (1, 2, 0))  # [F, B, 4]
    return work_o, scr_o, hist


def fused_available() -> bool:
    """The fused Mosaic kernel needs a real TPU backend."""
    if not _HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
