"""Device-side helpers for the 4-bit packed bin matrix.

Features whose realized bin count is <= 16 fit two bins per byte; the host
packer (io/dataset.py pack4_matrix) stores column ``2j`` in the low nibble
and ``2j+1`` in the high nibble of packed column ``j`` (reference: the
4-bit mode of the dense bin store, src/io/dense_bin.hpp DenseBin<true> —
same nibble order). Packing halves the HBM footprint of a served request
matrix; consumers unpack *inside* their gathers so the full-width [N, F]
matrix never materializes on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def unpack4(packed: jax.Array, num_features: int) -> jax.Array:
    """[..., ceil(F/2)] u8 nibble-packed -> [..., F] u8.

    The histogram engines call this on one streamed row block at a time
    (ops/histogram.py), so the unpacked width is a transient the size of
    one block, not the dataset.
    """
    lo = packed & jnp.uint8(0x0F)
    hi = (packed >> 4) & jnp.uint8(0x0F)
    full = jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], -1)
    return full[..., :num_features]


def gather_bin(binned: jax.Array, rows: jax.Array, col: jax.Array,
               packed: bool) -> jax.Array:
    """Per-row dynamic column gather ``binned[rows, col]`` -> i32.

    With ``packed`` the byte at column ``col >> 1`` is gathered and the
    nibble selected by ``col & 1`` is extracted — one gather either way,
    which is what keeps the packed predict walk the same number of
    dispatches as the u8 one.
    """
    if packed:
        byte = binned[rows, col >> 1].astype(jnp.int32)
        return (byte >> ((col & 1) * 4)) & 0xF
    return binned[rows, col].astype(jnp.int32)
