"""Best-split search over histograms.

TPU-native re-design of the reference's per-feature threshold scan
(reference: FeatureHistogram::FindBestThresholdSequentially
src/treelearner/feature_histogram.hpp:832 and the CUDA variant
src/treelearner/cuda/cuda_best_split_finder.cu:772 FindBestSplitsForLeafKernel).

Where the reference scans bins sequentially per feature (one OpenMP task or CUDA
block per feature), here the scan is a vectorized cumulative sum over the bin
axis of the whole ``[F, B]`` histogram, followed by a masked gain computation and
a single argmax — one fused XLA op chain, no per-feature loop.

Both missing-value default directions are evaluated (the reference's two-direction
scan): "missing right" is the plain left-cumulative scan (the NaN bin is the last
bin), "missing left" re-adds the NaN-bin mass to the left side for thresholds
below the NaN bin.

Categorical features use one-hot splits (left = {bin == b}); the reference's
sorted many-category scan (feature_histogram.hpp categorical branch) is a later
addition.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_EPS = 1e-15


class SplitParams(NamedTuple):
    """Static split hyper-parameters (subset of reference Config)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    # categorical-split knobs (reference: config.h:480-501)
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    # static gate: skip the sorted-categorical machinery entirely when the
    # dataset has no categorical features (set from the dataset by the GBDT)
    enable_sorted_cat: bool = True
    # monotone constraints, basic method (reference:
    # BasicLeafConstraints, monotone_constraints.hpp:465) + split-gain
    # penalty (:357); static gate keeps the unconstrained path unchanged
    use_monotone: bool = False
    monotone_penalty: float = 0.0
    # path smoothing (reference: CalculateSplittedLeafOutput USE_SMOOTHING,
    # feature_histogram.hpp: w*(n/s)/(n/s+1) + parent/(n/s+1))
    path_smooth: float = 0.0
    # cost-effective gradient boosting (reference:
    # cost_effective_gradient_boosting.hpp DeltaGain — per-split data cost +
    # one-time coupled feature-acquisition cost, both scaled by tradeoff)
    use_cegb: bool = False
    cegb_split_pen: float = 0.0    # tradeoff * cegb_penalty_split
    # extremely randomized trees: each feature evaluates ONE random
    # threshold instead of the full scan (reference: USE_RAND branch of
    # FindBestThresholdSequentially, rand_threshold)
    extra_trees: bool = False


class SplitResult(NamedTuple):
    """Best split of one leaf (reference: SplitInfo, src/treelearner/split_info.hpp)."""
    gain: jnp.ndarray          # shifted gain; > 0 means valid split
    feature: jnp.ndarray       # i32
    bin: jnp.ndarray           # i32 threshold bin (numerical: left is bin <= t)
    default_left: jnp.ndarray  # bool
    left_grad: jnp.ndarray
    left_hess: jnp.ndarray
    left_count: jnp.ndarray    # weighted (in-bag) row count
    left_rows: jnp.ndarray     # raw row count (drives the physical partition)
    # categorical splits: left = {bins whose bit is set}; [W] u32 with
    # W = ceil(B/32) (reference: SplitInfo::cat_threshold bitset)
    cat_bitset: jnp.ndarray
    # True when the winning split is a sorted-many-category split (leaf
    # outputs then use lambda_l2 + cat_l2 — reference: l2 += cat_l2)
    is_cat_l2: jnp.ndarray


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """Soft-threshold by the L1 regularization (reference:
    feature_histogram.hpp ThresholdL1)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_grad, sum_hess, p: SplitParams, l2: Optional[float] = None):
    """Optimal leaf value -ThL1(G)/(H + l2), clipped by max_delta_step
    (reference: FeatureHistogram::CalculateSplittedLeafOutput). ``l2``
    overrides lambda_l2 (sorted-categorical splits add cat_l2)."""
    if l2 is None:
        l2 = p.lambda_l2
    out = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + l2 + _EPS)
    if p.max_delta_step > 0.0:
        out = jnp.clip(out, -p.max_delta_step, p.max_delta_step)
    return out

def leaf_gain(sum_grad, sum_hess, p: SplitParams, l2: Optional[float] = None):
    """Gain contribution of a leaf: ThL1(G)^2 / (H + l2)
    (reference: FeatureHistogram::GetLeafGain)."""
    if l2 is None:
        l2 = p.lambda_l2
    if p.max_delta_step > 0.0:
        # with clipped output the gain is -(2*G*w + (H+l2)*w^2)... evaluated at w
        w = leaf_output(sum_grad, sum_hess, p, l2)
        return -(2.0 * sum_grad * w + (sum_hess + l2) * w * w) \
            - 2.0 * p.lambda_l1 * jnp.abs(w)
    t = threshold_l1(sum_grad, p.lambda_l1)
    return (t * t) / (sum_hess + l2 + _EPS)


def gain_given_output(sum_grad, sum_hess, w, p: SplitParams, l2=None):
    """Leaf gain at a FIXED output (reference: GetLeafGainGivenOutput) —
    used when constraints/smoothing move the output off the optimum."""
    if l2 is None:
        l2 = p.lambda_l2
    sg = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg * w + (sum_hess + l2) * w * w)


def child_output(sum_grad, sum_hess, cnt, p: SplitParams, l2=None,
                 parent_output=0.0, cmin=None, cmax=None):
    """Constrained/smoothed child output (reference:
    CalculateSplittedLeafOutput with USE_SMOOTHING + BasicConstraint clip)."""
    w = leaf_output(sum_grad, sum_hess, p, l2)
    if p.path_smooth > 0.0:
        ratio = cnt / p.path_smooth
        w = w * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)
    if p.use_monotone and cmin is not None:
        w = jnp.clip(w, cmin, cmax)
    return w


def depth_gate(gain, depth, max_depth: int, depth_budget=None):
    """Mask a split candidate's gain by the tree-depth limit.

    The exact-keyed path bakes the static ``max_depth`` into the program
    (the unlimited case compiles away entirely). Under the bucketed step
    ladder (``GrowerParams.step_buckets``) the jit key carries only the
    DEPTH BUCKET — ``max_depth`` is -1 (unlimited) or +1 (bounded) — and
    the actual bound rides as the traced scalar ``depth_budget``, so one
    program serves every bounded depth at a given leaf rung."""
    if depth_budget is not None:
        ok = depth < depth_budget
    else:
        ok = jnp.logical_or(max_depth <= 0, depth < max_depth)
    return jnp.where(ok, gain, _NEG_INF)


def monotone_penalty_factor(depth, penalty: float):
    """(reference: ComputeMonotoneSplitGainPenalty,
    monotone_constraints.hpp:357)"""
    d = depth.astype(jnp.float32)
    small = 1.0 - penalty / jnp.exp2(d) + _EPS
    large = 1.0 - jnp.exp2(penalty - 1.0 - d) + _EPS
    out = jnp.where(penalty <= 1.0, small, large)
    return jnp.where(penalty >= d + 1.0, _EPS, out)


def pack_bin_bitset(mask: jnp.ndarray) -> jnp.ndarray:
    """[B] bool bin-membership -> [ceil(B/32)] u32 bitset words."""
    b = mask.shape[0]
    w = -(-b // 32)
    pad = w * 32 - b
    m = jnp.pad(mask.astype(jnp.uint32), (0, pad)).reshape(w, 32)
    return (m << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
        axis=1, dtype=jnp.uint32)


def bitset_contains(words: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Vectorized membership test: is bit ``idx`` set in the [W] u32 bitset?

    Avoids a table gather (slow on TPU): the word is selected with W
    compare+select lanes, then shifted — all elementwise.
    """
    w = words.shape[0]
    word_id = (idx // 32).astype(jnp.uint32)
    sel = jnp.zeros_like(idx, dtype=jnp.uint32)
    for j in range(w):
        sel = jnp.where(word_id == j, words[j].astype(jnp.uint32), sel)
    return ((sel >> (idx.astype(jnp.uint32) % 32)) & 1) != 0


def go_left_pred(col: jnp.ndarray, bin_: jnp.ndarray, default_left,
                 nan_bin, is_cat, cat_bitset: jnp.ndarray) -> jnp.ndarray:
    """THE left-child routing predicate, shared by the masked grower, the
    compact partition, and prediction routing — it must agree bit-for-bit
    with the histogram cumulative semantics above (reference: Tree::Decision/
    Tree::CategoricalDecision, include/LightGBM/tree.h)."""
    col = col.astype(jnp.int32)
    return jnp.where(
        is_cat,
        bitset_contains(cat_bitset, col),
        (col <= bin_) | (default_left & (col == nan_bin)),
    )


def left_rows_of_split(hist: jnp.ndarray, feature, bin_, default_left,
                       nan_bin, is_cat, cat_bitset) -> jnp.ndarray:
    """Raw rows routed left by an already-decided split, recovered from a
    histogram's raw-count channel (every row of a bin routes identically).

    The data-parallel compact grower uses this to derive the SHARD-LOCAL
    left count from the shard-local histogram while the split decision
    itself comes from the psum-ed global histogram (reference:
    DataParallelTreeLearner keeps global_data_count_in_leaf_ beside the
    local partition, data_parallel_tree_learner.cpp:300-340)."""
    raw = hist[feature, :, 3]                                  # [B]
    bins = jnp.arange(hist.shape[1], dtype=jnp.int32)
    gl = go_left_pred(bins, bin_, default_left, nan_bin, is_cat, cat_bitset)
    return jnp.sum(raw * gl).astype(jnp.int32)


def extend_hist_efb(hist: jnp.ndarray, efb, n_virtual: int, bmax: int
                    ) -> jnp.ndarray:
    """Append virtual per-feature histogram rows for EFB-bundled features.

    ``hist`` is [C, B, K] over STORED columns (passthrough features and
    bundle columns). Each bundled original feature's non-default bins live
    at ``offset+1 .. offset+nb`` of its bundle column; its default-bin mass
    is the leaf total minus the range sum (reference: FixHistogram /
    sum_of_hessian bookkeeping, include/LightGBM/bin.h). The scan then
    treats virtual rows as ordinary numerical features.
    """
    col_of_ext, off_ext, nb_ext, dbin_ext = efb[0], efb[2], efb[3], efb[4]
    C, B, K = hist.shape
    bcol = col_of_ext[C:]                  # [Fb]
    off = off_ext[C:]
    nb = nb_ext[C:]
    dbin = dbin_ext[C:]
    j = jnp.arange(bmax, dtype=jnp.int32)[None, :]          # [1, Bmax]
    idx = jnp.minimum(off[:, None] + 1 + j, B - 1)
    gathered = hist[bcol[:, None], idx, :]                  # [Fb, Bmax, K]
    gathered = gathered * (j < nb[:, None])[:, :, None]
    totals = hist[0].sum(axis=0)                            # [K] leaf totals
    default = totals[None, :] - gathered.sum(axis=1)        # [Fb, K]
    virtual = gathered.at[jnp.arange(n_virtual), dbin].add(default)
    virtual = jnp.pad(virtual, ((0, 0), (0, B - bmax), (0, 0)))
    return jnp.concatenate([hist, virtual], axis=0)


def apply_efb_bitset(sp: "SplitResult", efb, n_cols: int, B: int
                     ) -> "SplitResult":
    """Translate a winning split on a VIRTUAL (bundled) feature into a
    bundle-column bitset so every router (partition, fused kernel,
    route_one_tree) treats it as a ready-made categorical-style split:
    left = {v in (off, off+1+t]} | {v outside the member's range, when the
    member's default bin <= t}."""
    off_ext, nb_ext, dbin_ext = efb[2], efb[3], efb[4]
    f = sp.feature
    bundled = f >= n_cols
    o = off_ext[f]
    nb = nb_ext[f]
    d = dbin_ext[f]
    v = jnp.arange(B, dtype=jnp.int32)
    in_r = jnp.logical_and(v > o, v <= o + nb)
    left = jnp.logical_or(
        jnp.logical_and(in_r, v <= o + 1 + sp.bin),
        jnp.logical_and(jnp.logical_not(in_r), d <= sp.bin))
    bits = pack_bin_bitset(left)
    return sp._replace(
        cat_bitset=jnp.where(bundled, bits, sp.cat_bitset))


def go_left_scalar_np(col: int, bin_: int, default_left: bool, nan_bin: int,
                      is_cat: bool, cat_bitset) -> bool:
    """Numpy scalar twin of go_left_pred for host-side consumers (TreeSHAP);
    MUST mirror go_left_pred bit-for-bit."""
    if is_cat:
        w = int(cat_bitset[col // 32]) if col // 32 < len(cat_bitset) else 0
        return bool((w >> (col % 32)) & 1)
    return col <= bin_ or (default_left and col == nan_bin)


def best_split(
    hist: jnp.ndarray,        # [F, B, K>=3] (grad, hess, count-weight[, raw-count])
    parent_grad: jnp.ndarray,
    parent_hess: jnp.ndarray,
    parent_count: jnp.ndarray,
    num_bins: jnp.ndarray,    # [F] i32
    nan_bin: jnp.ndarray,     # [F] i32 (bin NaN maps to; == num_bins-1 iff MissingType::NaN)
    has_nan_bin: jnp.ndarray, # [F] bool
    is_cat: jnp.ndarray,      # [F] bool
    feat_mask: jnp.ndarray,   # [F] bool: features allowed at this node
    p: SplitParams,
    mono_types: Optional[jnp.ndarray] = None,   # [F] i8 in {-1, 0, +1}
    cmin: Optional[jnp.ndarray] = None,         # scalar: leaf output bounds
    cmax: Optional[jnp.ndarray] = None,
    parent_output: float = 0.0,                 # for path smoothing
    depth: Optional[jnp.ndarray] = None,        # for the monotone penalty
    cegb_pen: Optional[jnp.ndarray] = None,     # [F] remaining coupled costs
    extra_key: Optional[jnp.ndarray] = None,    # PRNG key (extra_trees)
    feature_contri: Optional[jnp.ndarray] = None,  # [F] gain multipliers
    quant_scales: Optional[tuple] = None,       # (g_scale, h_scale) f32
) -> SplitResult:
    """Find the best (feature, threshold, direction) for one leaf.

    ``quant_scales``: the histogram holds int32 quantized-gradient code sums
    (ops/histogram.py int8 path); the per-bin sums dequantize HERE — leaf
    scale multiply on the grad/hess channels — before any gain computation,
    so the scan/gain machinery below is dtype-blind (reference: the int
    histogram is unpacked with grad_scale/hess_scale inside the best-split
    kernel, cuda_best_split_finder.cu)."""
    if quant_scales is not None:
        from .histogram import dequantize_hist
        hist = dequantize_hist(hist, quant_scales[0], quant_scales[1])
    f, b, k = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    # raw (unweighted) row counts drive the compact grower's physical
    # partition; histograms without the channel fall back to the weighted one
    r = hist[:, :, 3] if k > 3 else c
    cg = jnp.cumsum(g, axis=1)
    ch = jnp.cumsum(h, axis=1)
    cc = jnp.cumsum(c, axis=1)
    cr = jnp.cumsum(r, axis=1)

    t_iota = jnp.arange(b, dtype=jnp.int32)[None, :]        # [1, B]
    is_cat_b = is_cat[:, None]

    # numerical: left = bins <= t (cumulative); categorical one-hot: left = {bin == t}
    left_g1 = jnp.where(is_cat_b, g, cg)
    left_h1 = jnp.where(is_cat_b, h, ch)
    left_c1 = jnp.where(is_cat_b, c, cc)
    left_r1 = jnp.where(is_cat_b, r, cr)

    # direction 2 ("missing left"): move the NaN-bin mass to the left side for
    # thresholds strictly below the NaN bin. Only for numerical features with NaN.
    nan_g = jnp.take_along_axis(g, nan_bin[:, None], axis=1)
    nan_h = jnp.take_along_axis(h, nan_bin[:, None], axis=1)
    nan_c = jnp.take_along_axis(c, nan_bin[:, None], axis=1)
    nan_r = jnp.take_along_axis(r, nan_bin[:, None], axis=1)
    below = t_iota < nan_bin[:, None]
    left_g2 = cg + jnp.where(below, nan_g, 0.0)
    left_h2 = ch + jnp.where(below, nan_h, 0.0)
    left_c2 = cc + jnp.where(below, nan_c, 0.0)
    left_r2 = cr + jnp.where(below, nan_r, 0.0)

    parent_gain = leaf_gain(parent_grad, parent_hess, p)
    gain_shift = parent_gain + p.min_gain_to_split

    constrained = p.use_monotone or p.path_smooth > 0.0

    def dir_score(lg, lh, lc, extra_valid):
        rg = parent_grad - lg
        rh = parent_hess - lh
        rc = parent_count - lc
        valid = (
            extra_valid
            & feat_mask[:, None]
            & (lc >= p.min_data_in_leaf)
            & (rc >= p.min_data_in_leaf)
            & (lh >= p.min_sum_hessian_in_leaf)
            & (rh >= p.min_sum_hessian_in_leaf)
        )
        if constrained:
            # outputs move off the optimum (clip/smooth), so gains are
            # evaluated at the realized outputs (reference: GetSplitGains ->
            # GetSplitGainsGivenOutputs path)
            lw = child_output(lg, lh, lc, p, None, parent_output, cmin, cmax)
            rw = child_output(rg, rh, rc, p, None, parent_output, cmin, cmax)
            gain = gain_given_output(lg, lh, lw, p) \
                + gain_given_output(rg, rh, rw, p) - gain_shift
            if p.use_monotone and mono_types is not None:
                mt = mono_types[:, None].astype(jnp.int32)
                valid &= jnp.logical_not((mt > 0) & (lw > rw))
                valid &= jnp.logical_not((mt < 0) & (lw < rw))
                if p.monotone_penalty > 0.0:
                    pen = monotone_penalty_factor(depth, p.monotone_penalty)
                    gain = jnp.where(mt != 0, gain * pen, gain)
        else:
            gain = leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p) - gain_shift
        if p.use_cegb and cegb_pen is not None:
            # (reference: CostEfficientGradientBoosting::DeltaGain)
            gain = gain - cegb_pen[:, None] \
                - p.cegb_split_pen * parent_count
        if feature_contri is not None:
            # per-feature split-gain scaling (reference: config.h
            # feature_contri / feature_histogram.hpp meta_->penalty)
            gain = jnp.where(gain > 0, gain * feature_contri[:, None], gain)
        return jnp.where(valid, gain, _NEG_INF)

    # categorical one-hot splits (only for low-cardinality features,
    # reference: use_onehot = num_bin <= max_cat_to_onehot) may use any bin
    # (incl. last) as the "left" category; numerical thresholds must leave
    # the last bin on the right
    onehot_ok = is_cat_b & (num_bins[:, None] <= p.max_cat_to_onehot)
    cat_tmask = jnp.where(is_cat_b, onehot_ok & (t_iota < num_bins[:, None]),
                          t_iota < num_bins[:, None] - 1)
    if p.extra_trees and extra_key is not None:
        # one random candidate threshold per feature (reference: USE_RAND
        # rand_threshold per feature in FindBestThresholdSequentially)
        import jax as _jax
        # numerical thresholds live in [0, num_bins-1); one-hot categorical
        # candidates may use any bin incl. the last
        hi = jnp.where(is_cat, num_bins, num_bins - 1)
        rnd = _jax.random.randint(extra_key, (f,), 0, jnp.maximum(hi, 1))
        cat_tmask = cat_tmask & (t_iota == rnd[:, None])
        below_rand = (t_iota == rnd[:, None])
    else:
        below_rand = None
    score1 = dir_score(left_g1, left_h1, left_c1, cat_tmask)
    dir2_ok = (~is_cat_b) & has_nan_bin[:, None] & below \
        & (t_iota < num_bins[:, None] - 1)
    if below_rand is not None:
        dir2_ok = dir2_ok & below_rand
    score2 = dir_score(left_g2, left_h2, left_c2, dir2_ok)

    scores = jnp.stack([score1, score2], axis=-1)            # [F, B, 2]
    flat = scores.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    best_f = (best // (b * 2)).astype(jnp.int32)
    best_b = ((best // 2) % b).astype(jnp.int32)
    best_dir2 = (best % 2).astype(bool)

    lg = jnp.where(best_dir2, left_g2[best_f, best_b], left_g1[best_f, best_b])
    lh = jnp.where(best_dir2, left_h2[best_f, best_b], left_h1[best_f, best_b])
    lc = jnp.where(best_dir2, left_c2[best_f, best_b], left_c1[best_f, best_b])
    lr = jnp.where(best_dir2, left_r2[best_f, best_b], left_r1[best_f, best_b])

    # ---- sorted many-category splits -------------------------------------
    # (reference: FindBestThresholdCategoricalInner's sorted branch,
    # src/treelearner/feature_histogram.cpp:243-339 — categories sorted by
    # grad/(hess+cat_smooth), prefix scans from both ends, l2 += cat_l2.)
    # Vectorized over features; the stateful min_data_per_group gating runs
    # as a lax.scan over the <= max_cat_threshold prefix positions. The
    # reference estimates per-bin counts from hessians (cnt_factor); exact
    # counts from the histogram's count channel are used here instead.
    sorted_any = bool(b > 1) and p.enable_sorted_cat
    cs, cbest = _sorted_cat_split(
        g, h, c, r, is_cat, num_bins, feat_mask, parent_grad, parent_hess,
        parent_count, gain_shift, p, parent_output, cmin,
        cmax, cegb_pen, extra_key, feature_contri) \
        if sorted_any else (None, None)
    if cs is not None:
        use_sorted = cbest["gain"] > best_gain
    else:
        use_sorted = jnp.asarray(False)

    w = -(-b // 32)
    # bitset for the numerical/one-hot winner: one-hot cat -> single bin bit
    best_is_cat = is_cat[best_f]
    onehot_mask = (jnp.arange(b) == best_b) & best_is_cat
    bitset_a = pack_bin_bitset(onehot_mask)

    if cs is not None:
        gain_ = jnp.where(use_sorted, cbest["gain"], best_gain)
        feat_ = jnp.where(use_sorted, cbest["feature"], best_f)
        bin_ = jnp.where(use_sorted, 0, best_b)
        dl_ = jnp.where(use_sorted, False, best_dir2)
        lg = jnp.where(use_sorted, cbest["left_grad"], lg)
        lh = jnp.where(use_sorted, cbest["left_hess"], lh)
        lc = jnp.where(use_sorted, cbest["left_count"], lc)
        lr = jnp.where(use_sorted, cbest["left_rows"], lr)
        bitset = jnp.where(use_sorted, cbest["bitset"], bitset_a)
    else:
        gain_, feat_, bin_, dl_ = best_gain, best_f, best_b, best_dir2
        bitset = bitset_a

    return SplitResult(
        gain=gain_,
        feature=feat_,
        bin=bin_,
        default_left=dl_,
        left_grad=lg,
        left_hess=lh,
        left_count=lc,
        left_rows=lr,
        cat_bitset=bitset,
        is_cat_l2=use_sorted,
    )


def _sorted_cat_split(g, h, c, r, is_cat, num_bins, feat_mask, parent_grad,
                      parent_hess, parent_count, gain_shift, p: SplitParams,
                      parent_output=0.0, cmin=None, cmax=None, cegb_pen=None,
                      extra_key=None, feature_contri=None):
    """Best sorted-many-category split over all features; returns
    (True, dict) or (None, None) when no feature qualifies statically."""
    f, b = g.shape
    if not bool(is_cat.shape):  # pragma: no cover - shape guard
        return None, None
    mct = int(min(p.max_cat_threshold, b))
    if mct <= 0:
        return None, None
    l2c = p.lambda_l2 + p.cat_l2

    sort_mode = is_cat & (num_bins > p.max_cat_to_onehot) & feat_mask  # [F]
    elig = sort_mode[:, None] & (c >= p.cat_smooth)                    # [F, B]
    used_bin = elig.sum(axis=1).astype(jnp.int32)                      # [F]
    ratio = jnp.where(elig, g / (h + p.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True)                    # [F, B]
    sg = jnp.take_along_axis(g, order, axis=1)
    sh = jnp.take_along_axis(h, order, axis=1)
    sc = jnp.take_along_axis(c, order, axis=1)
    sr = jnp.take_along_axis(r, order, axis=1)
    zpad = jnp.zeros((f, 1), jnp.float32)
    cg = jnp.concatenate([zpad, jnp.cumsum(sg, axis=1)], axis=1)  # [F, B+1]
    ch = jnp.concatenate([zpad, jnp.cumsum(sh, axis=1)], axis=1)
    cc = jnp.concatenate([zpad, jnp.cumsum(sc, axis=1)], axis=1)
    cr = jnp.concatenate([zpad, jnp.cumsum(sr, axis=1)], axis=1)

    tot_idx = used_bin[:, None]                                       # [F, 1]
    max_num_cat = jnp.minimum(mct, (used_bin + 1) // 2)               # [F]

    # prefix tensors for all candidate set sizes t in 1..mct at once:
    # forward = first t sorted categories; reverse = last t eligible ones
    ts = jnp.arange(1, mct + 1, dtype=jnp.int32)                      # [T]
    idx_fwd = jnp.minimum(ts[None, :], b)                             # [F?,T]
    idx_fwd = jnp.broadcast_to(idx_fwd, (f, mct))
    idx_rev = jnp.maximum(tot_idx - ts[None, :], 0)                   # [F, T]

    def pref(csum):
        top = jnp.take_along_axis(csum, tot_idx, axis=1)              # [F, 1]
        fwd = jnp.take_along_axis(csum, idx_fwd, axis=1)              # [F, T]
        rev = top - jnp.take_along_axis(csum, idx_rev, axis=1)        # [F, T]
        return jnp.stack([fwd, rev], axis=2)                          # [F, T, 2]

    lg_t = pref(cg)
    lh_t = pref(ch)
    lc_t = pref(cc)
    lr_t = pref(cr)
    in_range = ((ts[None, :] <= used_bin[:, None])
                & (ts[None, :] <= max_num_cat[:, None])
                & sort_mode[:, None])                                 # [F, T]
    step_cnt = jnp.diff(lc_t, axis=1, prepend=0.0)                    # [F, T, 2]

    # stateful gating scan over t (cnt_cur_group accumulation + break flags)
    def gate(state, inputs):
        grp, dead = state                                             # [F, 2]
        sc_t, lct, lht, ok_t = inputs
        grp = grp + sc_t
        left_ok = (lct >= p.min_data_in_leaf) & \
            (lht >= p.min_sum_hessian_in_leaf)
        rc = parent_count - lct
        rh = parent_hess - lht
        brk = (rc < p.min_data_in_leaf) | (rc < p.min_data_per_group) | \
            (rh < p.min_sum_hessian_in_leaf)
        alive = jnp.logical_not(dead) & ok_t[:, None]
        evald = alive & left_ok & jnp.logical_not(brk) & \
            (grp >= p.min_data_per_group)
        grp = jnp.where(evald, 0.0, grp)
        dead = dead | (alive & brk)
        return (grp, dead), evald

    state0 = (jnp.zeros((f, 2), jnp.float32), jnp.zeros((f, 2), bool))
    _, evald = lax.scan(
        gate, state0,
        (jnp.moveaxis(step_cnt, 1, 0), jnp.moveaxis(lc_t, 1, 0),
         jnp.moveaxis(lh_t, 1, 0), jnp.moveaxis(in_range, 1, 0)))
    evald = jnp.moveaxis(evald, 0, 1)                                 # [F, T, 2]

    rg_t = parent_grad - lg_t
    rh_t = parent_hess - lh_t
    if p.use_monotone or p.path_smooth > 0.0:
        # gains at realized (clipped/smoothed) outputs so they stay
        # comparable with the numerical candidates' constrained gains
        # (reference: GetSplitGains with constraints in the cat branch)
        rc_t = parent_count - lc_t
        lw_t = child_output(lg_t, lh_t, lc_t, p, l2c, parent_output,
                            cmin, cmax)
        rw_t = child_output(rg_t, rh_t, rc_t, p, l2c, parent_output,
                            cmin, cmax)
        gains = gain_given_output(lg_t, lh_t, lw_t, p, l2c) \
            + gain_given_output(rg_t, rh_t, rw_t, p, l2c) - gain_shift
    else:
        gains = leaf_gain(lg_t, lh_t, p, l2c) + leaf_gain(rg_t, rh_t, p, l2c) \
            - gain_shift
    if p.use_cegb and cegb_pen is not None:
        gains = gains - cegb_pen[:, None, None] \
            - p.cegb_split_pen * parent_count
    if feature_contri is not None:
        gains = jnp.where(gains > 0,
                          gains * feature_contri[:, None, None], gains)
    if p.extra_trees and extra_key is not None:
        # one random prefix size per feature (reference: USE_RAND
        # rand_threshold in the categorical branch)
        import jax as _jax
        rnd_t = _jax.random.randint(
            _jax.random.fold_in(extra_key, 1), (f,), 0,
            jnp.maximum(max_num_cat, 1))
        gains = jnp.where(
            (jnp.arange(mct)[None, :, None] == rnd_t[:, None, None]),
            gains, _NEG_INF)
    gains = jnp.where(evald, gains, _NEG_INF)

    flatc = gains.reshape(-1)
    cb = jnp.argmax(flatc)
    cgain = flatc[cb]
    cf = (cb // (mct * 2)).astype(jnp.int32)
    ct = ((cb // 2) % mct).astype(jnp.int32)          # t-1
    cdir_rev = (cb % 2).astype(bool)

    # chosen category set -> bin bitset
    pos = jnp.arange(b, dtype=jnp.int32)
    t_best = ct + 1
    ub = used_bin[cf]
    pos_mask = jnp.where(cdir_rev,
                         (pos >= ub - t_best) & (pos < ub),
                         pos < t_best)
    bin_mask = jnp.zeros((b,), bool).at[order[cf]].set(pos_mask)
    bitset = pack_bin_bitset(bin_mask)

    sel = (cf, ct, jnp.where(cdir_rev, 1, 0))
    cbest = {
        "gain": cgain,
        "feature": cf,
        "left_grad": lg_t[sel],
        "left_hess": lh_t[sel],
        "left_count": lc_t[sel],
        "left_rows": lr_t[sel],
        "bitset": bitset,
    }
    return True, cbest
