"""Best-split search over histograms.

TPU-native re-design of the reference's per-feature threshold scan
(reference: FeatureHistogram::FindBestThresholdSequentially
src/treelearner/feature_histogram.hpp:832 and the CUDA variant
src/treelearner/cuda/cuda_best_split_finder.cu:772 FindBestSplitsForLeafKernel).

Where the reference scans bins sequentially per feature (one OpenMP task or CUDA
block per feature), here the scan is a vectorized cumulative sum over the bin
axis of the whole ``[F, B]`` histogram, followed by a masked gain computation and
a single argmax — one fused XLA op chain, no per-feature loop.

Both missing-value default directions are evaluated (the reference's two-direction
scan): "missing right" is the plain left-cumulative scan (the NaN bin is the last
bin), "missing left" re-adds the NaN-bin mass to the left side for thresholds
below the NaN bin.

Categorical features use one-hot splits (left = {bin == b}); the reference's
sorted many-category scan (feature_histogram.hpp categorical branch) is a later
addition.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

_NEG_INF = -1e30
_EPS = 1e-15


class SplitParams(NamedTuple):
    """Static split hyper-parameters (subset of reference Config)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0


class SplitResult(NamedTuple):
    """Best split of one leaf (reference: SplitInfo, src/treelearner/split_info.hpp)."""
    gain: jnp.ndarray          # shifted gain; > 0 means valid split
    feature: jnp.ndarray       # i32
    bin: jnp.ndarray           # i32 threshold bin (left: bin <= t); cat: left == t
    default_left: jnp.ndarray  # bool
    left_grad: jnp.ndarray
    left_hess: jnp.ndarray
    left_count: jnp.ndarray    # weighted (in-bag) row count
    left_rows: jnp.ndarray     # raw row count (drives the physical partition)


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """Soft-threshold by the L1 regularization (reference:
    feature_histogram.hpp ThresholdL1)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_grad, sum_hess, p: SplitParams):
    """Optimal leaf value -ThL1(G)/(H + l2), clipped by max_delta_step
    (reference: FeatureHistogram::CalculateSplittedLeafOutput)."""
    out = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + p.lambda_l2 + _EPS)
    if p.max_delta_step > 0.0:
        out = jnp.clip(out, -p.max_delta_step, p.max_delta_step)
    return out

def leaf_gain(sum_grad, sum_hess, p: SplitParams):
    """Gain contribution of a leaf: ThL1(G)^2 / (H + l2)
    (reference: FeatureHistogram::GetLeafGain)."""
    if p.max_delta_step > 0.0:
        # with clipped output the gain is -(2*G*w + (H+l2)*w^2)... evaluated at w
        w = leaf_output(sum_grad, sum_hess, p)
        return -(2.0 * sum_grad * w + (sum_hess + p.lambda_l2) * w * w) \
            - 2.0 * p.lambda_l1 * jnp.abs(w)
    t = threshold_l1(sum_grad, p.lambda_l1)
    return (t * t) / (sum_hess + p.lambda_l2 + _EPS)


def best_split(
    hist: jnp.ndarray,        # [F, B, K>=3] (grad, hess, count-weight[, raw-count])
    parent_grad: jnp.ndarray,
    parent_hess: jnp.ndarray,
    parent_count: jnp.ndarray,
    num_bins: jnp.ndarray,    # [F] i32
    nan_bin: jnp.ndarray,     # [F] i32 (bin NaN maps to; == num_bins-1 iff MissingType::NaN)
    has_nan_bin: jnp.ndarray, # [F] bool
    is_cat: jnp.ndarray,      # [F] bool
    feat_mask: jnp.ndarray,   # [F] bool: features allowed at this node
    p: SplitParams,
) -> SplitResult:
    """Find the best (feature, threshold, direction) for one leaf."""
    f, b, k = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    # raw (unweighted) row counts drive the compact grower's physical
    # partition; histograms without the channel fall back to the weighted one
    r = hist[:, :, 3] if k > 3 else c
    cg = jnp.cumsum(g, axis=1)
    ch = jnp.cumsum(h, axis=1)
    cc = jnp.cumsum(c, axis=1)
    cr = jnp.cumsum(r, axis=1)

    t_iota = jnp.arange(b, dtype=jnp.int32)[None, :]        # [1, B]
    is_cat_b = is_cat[:, None]

    # numerical: left = bins <= t (cumulative); categorical one-hot: left = {bin == t}
    left_g1 = jnp.where(is_cat_b, g, cg)
    left_h1 = jnp.where(is_cat_b, h, ch)
    left_c1 = jnp.where(is_cat_b, c, cc)
    left_r1 = jnp.where(is_cat_b, r, cr)

    # direction 2 ("missing left"): move the NaN-bin mass to the left side for
    # thresholds strictly below the NaN bin. Only for numerical features with NaN.
    nan_g = jnp.take_along_axis(g, nan_bin[:, None], axis=1)
    nan_h = jnp.take_along_axis(h, nan_bin[:, None], axis=1)
    nan_c = jnp.take_along_axis(c, nan_bin[:, None], axis=1)
    nan_r = jnp.take_along_axis(r, nan_bin[:, None], axis=1)
    below = t_iota < nan_bin[:, None]
    left_g2 = cg + jnp.where(below, nan_g, 0.0)
    left_h2 = ch + jnp.where(below, nan_h, 0.0)
    left_c2 = cc + jnp.where(below, nan_c, 0.0)
    left_r2 = cr + jnp.where(below, nan_r, 0.0)

    parent_gain = leaf_gain(parent_grad, parent_hess, p)
    gain_shift = parent_gain + p.min_gain_to_split

    def dir_score(lg, lh, lc, extra_valid):
        rg = parent_grad - lg
        rh = parent_hess - lh
        rc = parent_count - lc
        valid = (
            extra_valid
            & feat_mask[:, None]
            & (lc >= p.min_data_in_leaf)
            & (rc >= p.min_data_in_leaf)
            & (lh >= p.min_sum_hessian_in_leaf)
            & (rh >= p.min_sum_hessian_in_leaf)
        )
        gain = leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p) - gain_shift
        return jnp.where(valid, gain, _NEG_INF)

    # categorical one-hot splits may use any bin (incl. last) as the "left"
    # category; numerical thresholds must leave the last bin on the right
    cat_tmask = jnp.where(is_cat_b, t_iota < num_bins[:, None],
                          t_iota < num_bins[:, None] - 1)
    score1 = dir_score(left_g1, left_h1, left_c1, cat_tmask)
    dir2_ok = (~is_cat_b) & has_nan_bin[:, None] & below \
        & (t_iota < num_bins[:, None] - 1)
    score2 = dir_score(left_g2, left_h2, left_c2, dir2_ok)

    scores = jnp.stack([score1, score2], axis=-1)            # [F, B, 2]
    flat = scores.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    best_f = (best // (b * 2)).astype(jnp.int32)
    best_b = ((best // 2) % b).astype(jnp.int32)
    best_dir2 = (best % 2).astype(bool)

    lg = jnp.where(best_dir2, left_g2[best_f, best_b], left_g1[best_f, best_b])
    lh = jnp.where(best_dir2, left_h2[best_f, best_b], left_h1[best_f, best_b])
    lc = jnp.where(best_dir2, left_c2[best_f, best_b], left_c1[best_f, best_b])
    lr = jnp.where(best_dir2, left_r2[best_f, best_b], left_r1[best_f, best_b])
    return SplitResult(
        gain=best_gain,
        feature=best_f,
        bin=best_b,
        default_left=best_dir2,
        left_grad=lg,
        left_hess=lh,
        left_count=lc,
        left_rows=lr,
    )
