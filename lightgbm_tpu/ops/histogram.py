"""Histogram construction on TPU.

TPU-native re-design of the reference's histogram kernels
(reference: CUDA shared-memory atomicAdd kernels in
src/treelearner/cuda/cuda_histogram_constructor.cu:17-68 and the CPU templated
``Dataset::ConstructHistograms`` include/LightGBM/dataset.h:727).

TPUs have no fast scatter/atomics, so the scatter-add is re-formulated as a
one-hot contraction that XLA maps onto the MXU:

    hist[f, b, k] = sum_r (binned[r, f] == b) * channels[r, k]

``channels`` carries (grad, hess, count-weight) per row, already multiplied by
the leaf-membership mask.

Two implementations sit behind ``impl=``:

  * ``xla``    — chunked one-hot einsum (rows scanned in blocks to bound the
                 materialized one-hot); f32 HIGHEST precision, runs anywhere.
  * ``pallas`` — Mosaic kernel that forms the one-hot in VMEM and feeds the
                 MXU directly (ops/pallas_histogram.py); TPU only.
  * ``auto``   — pallas on a TPU backend, else xla.

The dispatch is resolved at trace time (backend is static under jit).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# target elements for the materialized one-hot per scan step
_CHUNK_ELEMS = 1 << 23


def _chunk_rows(n: int, f: int, b: int) -> int:
    per_row = max(1, f * b)
    c = max(128, _CHUNK_ELEMS // per_row)
    # round to a multiple of 128 rows for clean TPU tiling
    c = (c // 128) * 128
    return max(128, min(c, max(128, n)))


def _xla_histogram(binned, channels, num_bins: int):
    n, f = binned.shape
    k = channels.shape[1]
    b = num_bins
    chunk = _chunk_rows(n, f, b)
    iota = jnp.arange(b, dtype=jnp.int32)

    # histogram sums need full f32 accuracy (hessian sums drive leaf outputs;
    # SURVEY §7 "bf16 is out for hessian sums") — the TPU MXU's default bf16
    # matmul precision is not enough, so force the fp32-accurate mode.
    prec = lax.Precision.HIGHEST

    if n <= chunk:
        onehot = (binned.astype(jnp.int32)[:, :, None] == iota).astype(channels.dtype)
        hist = jnp.einsum("rfb,rk->fbk", onehot, channels, precision=prec)
    else:
        n_chunks = -(-n // chunk)
        pad = n_chunks * chunk - n
        if pad:
            binned = jnp.pad(binned, ((0, pad), (0, 0)))
            channels = jnp.pad(channels, ((0, pad), (0, 0)))
        binned_c = binned.reshape(n_chunks, chunk, f)
        channels_c = channels.reshape(n_chunks, chunk, k)

        def step(hist, inp):
            bc, cc = inp
            onehot = (bc.astype(jnp.int32)[:, :, None] == iota).astype(cc.dtype)
            return hist + jnp.einsum("rfb,rk->fbk", onehot, cc,
                                     precision=prec), None

        hist0 = jnp.zeros((f, b, k), dtype=channels.dtype)
        hist, _ = lax.scan(step, hist0, (binned_c, channels_c))
    return hist


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "axis_name", "impl"))
def histogram(
    binned: jax.Array,      # [N, F] uint8/uint16/int32
    channels: jax.Array,    # [N, K] f32
    num_bins: int,          # B (static)
    axis_name: Optional[str] = None,
    impl: str = "auto",
) -> jax.Array:             # [F, B, K] f32
    """Accumulate per-(feature, bin) sums of ``channels`` columns."""
    # "auto" currently resolves to the XLA one-hot contraction: on the v5e
    # it sustains ~190 Gelem/s of one-hot work and the Mosaic kernel does not
    # beat it yet (pallas stays opt-in for development until it wins the A/B)
    use_pallas = False
    if impl == "pallas":
        from .pallas_histogram import pallas_available
        use_pallas = pallas_available()
        if not use_pallas:
            raise RuntimeError(
                "tpu_hist_impl=pallas requires a TPU backend; use 'xla'")
    if use_pallas:
        from .pallas_histogram import pallas_histogram
        hist = pallas_histogram(binned, channels, num_bins)
    else:
        hist = _xla_histogram(binned, channels, num_bins)

    if axis_name is not None:
        # distributed data-parallel: the reference reduce-scatters histograms over
        # its socket/MPI Network (src/treelearner/data_parallel_tree_learner.cpp:223-300);
        # on TPU the equivalent is a psum over the ICI mesh axis.
        hist = lax.psum(hist, axis_name)
    return hist
