"""Histogram construction on TPU.

TPU-native re-design of the reference's histogram kernels
(reference: CUDA shared-memory atomicAdd kernels in
src/treelearner/cuda/cuda_histogram_constructor.cu:17-68 and the CPU templated
``Dataset::ConstructHistograms`` include/LightGBM/dataset.h:727).

TPUs have no fast scatter/atomics, so the scatter-add is re-formulated as a
one-hot contraction that XLA maps onto the MXU:

    hist[f, b, k] = sum_r (binned[r, f] == b) * channels[r, k]

``channels`` carries (grad, hess, count-weight) per row, already multiplied by
the leaf-membership mask.

Two implementations sit behind ``impl=``:

  * ``xla``    — chunked one-hot einsum (rows scanned in blocks to bound the
                 materialized one-hot); f32 HIGHEST precision, runs anywhere.
  * ``pallas`` — Mosaic kernel that forms the one-hot in VMEM and feeds the
                 MXU directly (ops/pallas_histogram.py); TPU only.
  * ``auto``   — pallas on a TPU backend, else xla.

The dispatch is resolved at trace time (backend is static under jit).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.spans import span

# target elements for the materialized one-hot per scan step
_CHUNK_ELEMS = 1 << 23


def _chunk_rows(n: int, f: int, b: int) -> int:
    per_row = max(1, f * b)
    c = max(128, _CHUNK_ELEMS // per_row)
    # round to a multiple of 128 rows for clean TPU tiling
    c = (c // 128) * 128
    return max(128, min(c, max(128, n)))


def _xla_histogram(binned, channels, num_bins: int, mbatch: int = 1,
                   chunk_f: int = 0):
    n, f = binned.shape
    k = channels.shape[1]
    b = num_bins
    # batched-M port (ops/fused_split.py hist_flush is the reference
    # design): the XLA engine's analogue of staging K row blocks per MXU
    # issue is contracting K chunks of rows in ONE einsum — the scan trip
    # count drops K-fold and XLA sees a K-times-deeper contraction to
    # tile, instead of K back-to-back launches over small one-hots.
    # ``chunk_f`` overrides the feature count the row-chunk size derives
    # from: a feature-GROUP call (hist_overlap) must keep the full-width
    # call's chunk boundaries, or the f32 accumulation order changes and
    # the grouped histogram stops being bit-identical to the full one
    chunk = _chunk_rows(n, chunk_f or f, b) * max(1, int(mbatch))
    chunk = max(128, min(chunk, -(-max(n, 1) // 128) * 128))
    iota = jnp.arange(b, dtype=jnp.int32)

    quantized = jnp.issubdtype(channels.dtype, jnp.integer)
    acc_dtype = jnp.int32 if quantized else channels.dtype

    def contract(onehot, ch):
        if quantized:
            # quantized-gradient path (reference: gradient_discretizer.cpp
            # + the int histogram kernels, cuda_histogram_constructor
            # .cu:249-524): int8 one-hot x int8 codes accumulate
            # int8*int8 -> int32 on the MXU. preferred_element_type=int32
            # is load-bearing: without it XLA's dot output dtype follows
            # the int8 operands and the sums wrap (tpulint R003).
            return jnp.einsum("rfb,rk->fbk", onehot, ch,
                              preferred_element_type=jnp.int32)
        # histogram sums need full f32 accuracy (hessian sums drive leaf
        # outputs; SURVEY §7 "bf16 is out for hessian sums") — the TPU
        # MXU's default bf16 matmul precision is not enough, so force the
        # fp32-accurate mode.
        return jnp.einsum("rfb,rk->fbk", onehot, ch,
                          precision=lax.Precision.HIGHEST)

    if n <= chunk:
        onehot = (binned.astype(jnp.int32)[:, :, None] == iota).astype(channels.dtype)
        hist = contract(onehot, channels)
    else:
        n_chunks = -(-n // chunk)
        pad = n_chunks * chunk - n
        if pad:
            binned = jnp.pad(binned, ((0, pad), (0, 0)))
            channels = jnp.pad(channels, ((0, pad), (0, 0)))
        binned_c = binned.reshape(n_chunks, chunk, f)
        channels_c = channels.reshape(n_chunks, chunk, k)

        def step(hist, inp):
            bc, cc = inp
            onehot = (bc.astype(jnp.int32)[:, :, None] == iota).astype(cc.dtype)
            return hist + contract(onehot, cc), None

        hist0 = jnp.zeros((f, b, k), dtype=acc_dtype)
        hist, _ = lax.scan(step, hist0, (binned_c, channels_c))
    return hist


# narrowed (16-bit) quantized accumulation: the packed-pair radix. Two code
# sums share one f32 channel exactly when the per-chunk sums stay below the
# radix: with R = 4096 and chunk sums capped at R - 1 = 4095, the worst
# packed chunk sum is R * 4095 + 4095 = 4095 * 4097 = 2^24 - 1 — the last
# exactly-representable f32 integer, so no larger power-of-two radix works.
_NARROW_RADIX = 4096
_NARROW_SHIFT = 12


def narrow_chunk_rows(quant_max: int) -> int:
    """Largest row chunk whose packed-pair sums stay exact (128-multiple).

    The bound: chunk * quant_max <= RADIX - 1 keeps the hess-code sum
    strictly below the radix (unpackable) and the packed grad+hess sum
    below 2^24 (exact in f32). Returns 0 when ``quant_max`` is too large
    for even a 128-row chunk — callers must keep the int32 path then."""
    c = ((_NARROW_RADIX - 1) // max(1, quant_max)) // 128 * 128
    return c if c >= 128 else 0


def _xla_histogram_narrow(binned, channels, num_bins: int, quant_max: int,
                          chunk_f: int = 0):
    """16-bit narrowed quantized histogram (reference: the narrow hist-bits
    mode of GradientDiscretizer::GetHistBitsInLeaf + the 16-bit packed
    gradient-hessian histogram entries, gradient_discretizer.cpp).

    The int8 grad/hess codes pack as ``P = qg * 4096 + qh`` and the {0,1}
    count channels as ``W = inbag * 4096 + raw`` — TWO f32 channels instead
    of four — and the one-hot contraction rides the fp32-HIGHEST MXU/BLAS
    path. Per chunk the packed sums are exact f32 integers (see
    narrow_chunk_rows), unpack to int32 with an arithmetic shift/mask pair,
    and accumulate int32 across chunks, so the result is BIT-IDENTICAL to
    the int8 x int8 -> int32 engine at half the contraction work."""
    n, f = binned.shape
    b = num_bins
    if channels.shape[1] != 4:
        raise ValueError(
            f"acc_bits=16 packs the (qgrad, qhess, inbag, raw) channel "
            f"quad; got {channels.shape[1]} channels — the narrowed "
            "engine has no packing for other channel layouts")
    chunk = narrow_chunk_rows(quant_max)
    if not chunk:
        raise ValueError(
            f"acc_bits=16 needs quant_max <= {(_NARROW_RADIX - 1) // 128} "
            f"(got {quant_max}): a 128-row chunk's code sums must stay "
            "below the packing radix")
    chunk = min(chunk, _chunk_rows(n, chunk_f or f, b))
    iota = jnp.arange(b, dtype=jnp.int32)
    radix = jnp.float32(_NARROW_RADIX)

    def pack2(ch):
        chf = ch.astype(jnp.float32)
        p = chf[:, 0] * radix + chf[:, 1]       # qg*R + qh (qh >= 0 < R)
        w = chf[:, 2] * radix + chf[:, 3]       # inbag*R + raw
        return jnp.stack([p, w], axis=1)

    def unpack2(part):
        # exact integer-valued f32 -> int32, then split each packed sum
        # with an arithmetic shift (floor division by the radix) and the
        # low-bits mask — exact for negative grad sums too
        pi = part.astype(jnp.int32)
        hi = pi >> _NARROW_SHIFT
        lo = pi & (_NARROW_RADIX - 1)
        return jnp.stack([hi[..., 0], lo[..., 0], hi[..., 1], lo[..., 1]],
                         axis=-1)               # [F, B, 4]

    def contract(bc, cc):
        onehot = (bc.astype(jnp.int32)[:, :, None] == iota) \
            .astype(jnp.float32)
        part = jnp.einsum("rfb,rk->fbk", onehot, pack2(cc),
                          precision=lax.Precision.HIGHEST)
        return unpack2(part)

    if n <= chunk:
        return contract(binned, channels)
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        channels = jnp.pad(channels, ((0, pad), (0, 0)))
    binned_c = binned.reshape(n_chunks, chunk, f)
    channels_c = channels.reshape(n_chunks, chunk, channels.shape[1])

    def step(hist, inp):
        bc, cc = inp
        return hist + contract(bc, cc), None

    hist0 = jnp.zeros((f, b, 4), jnp.int32)
    hist, _ = lax.scan(step, hist0, (binned_c, channels_c))
    return hist


def dequantize_hist(hist: jax.Array, g_scale, h_scale) -> jax.Array:
    """int32 quantized histogram ``[..., 4+]`` -> f32.

    THE sanctioned int->f32 histogram boundary (tpulint R003 contract): the
    grad/hess code sums multiply by the per-iteration scales; count channels
    cast exactly (int32 counts are exact at any row count, unlike the f32
    path's 2^24 ceiling). Split finding calls this on the LEAF's int32
    per-bin sums right before gain computation (reference:
    CUDABestSplitFinder unpacks the int histogram with grad_scale/hess_scale,
    cuda_best_split_finder.cu)."""
    g = hist[..., 0:1].astype(jnp.float32) * g_scale
    h = hist[..., 1:2].astype(jnp.float32) * h_scale
    rest = hist[..., 2:].astype(jnp.float32)
    return jnp.concatenate([g, h, rest], axis=-1)


def _resolve_impl(impl: str, num_bins: int, num_features: int = 0) -> str:
    """Resolve 'auto' to a concrete implementation.

    Measured on v5e (2026-07, 1M rows x 28 features): at B=256 the Mosaic
    kernel sustains ~0.59 Telem/s of one-hot work vs ~0.007 for the chunked
    XLA einsum (which materializes the one-hot in HBM and goes
    bandwidth-bound); at B<=64 the XLA path is competitive (~0.45 Telem/s)
    because the one-hot is 4x smaller. Pallas needs the per-feature one-hot
    width to tile cleanly into 128 lanes, so it takes over at B >= 128.
    Wide F*B makes the Mosaic kernel's unrolled chunk loop spill registers
    past the VMEM budget (F=320 at B=256 wants 149MB of spill slots on
    v5e) — those configs stay on the XLA path.
    """
    if impl != "auto":
        return impl
    from .pallas_histogram import pallas_available
    if (num_bins >= 128 and pallas_available()
            and num_features * num_bins <= 50_000):
        return "pallas"
    return "xla"


def histogram_block(
    binned: jax.Array,      # [BS, F] uint8
    channels: jax.Array,    # [BS, K] f32, or int8 (quantized-gradient path)
    num_bins: int,
    impl: str = "auto",
    mbatch: int = 1,
    packed4_features: int = 0,
    layout: str = "lane",
    acc_bits: int = 32,
    quant_max: int = 127,
    chunk_f: int = 0,
) -> jax.Array:             # [F, B, K] f32 (int32 for int8 channels)
    """Histogram of one already-sliced row block (no psum, no jit wrapper —
    call sites are inside jitted loops).

    Integer ``channels`` select the quantized-gradient pipeline: int8
    one-hot x int8 codes contracted with ``preferred_element_type=int32``
    (native int8 MXU throughput, exact int32 sums).

    ``mbatch`` (env/param ``tpu_hist_mbatch``) is the batched-M depth:
    the Mosaic kernel issues M = 8*mbatch MXU rows per contraction, the
    XLA engine contracts mbatch row chunks per einsum. Counts and int32
    sums are bit-identical across mbatch values.

    ``packed4_features``: the block arrives nibble-packed
    ([BS, ceil(F/2)] u8, ``tpu_bin_pack4`` — io/dataset.py pack4_matrix)
    and is unpacked here, inside the jitted block loop, so only one
    block's full width ever materializes while the HBM-resident matrix
    stays at half size. Fed by both the serving path and, since round 6,
    the pack4 TRAINING path (ops/compact.py segment_histogram with a
    ``RowLayout.packed4`` record layout).

    ``layout`` selects the Mosaic one-hot register layout
    (ops/pallas_histogram.py): "lane" keeps bins along lanes (channel-major
    output), "sublane" lays bins along sublanes for B <= 64 so the one-hot
    compare fills the register tile (tpu_hist_layout).

    ``acc_bits=16`` selects the narrowed quantized accumulation for integer
    channels (reference: GetHistBitsInLeaf): grad/hess and inbag/raw code
    pairs pack into ONE f32 channel each (exact below the packing radix,
    see narrow_chunk_rows), halving the contraction work; ``quant_max``
    must bound |code| (the trainer passes num_grad_quant_bins + 1).
    Results stay bit-identical int32.

    ``chunk_f``: feature count the XLA engines derive their row-chunk
    size from, when the call covers only a feature GROUP of a wider
    build (hist_overlap) — same chunk boundaries keep the f32 sums
    bit-identical to the full-width call."""
    if packed4_features:
        from .packed import unpack4
        binned = unpack4(binned, packed4_features)
    quantized = jnp.issubdtype(channels.dtype, jnp.integer)
    if acc_bits == 16 and quantized:
        # narrowed engine: packed f32 channels through the fp32-HIGHEST
        # contraction, exact int32 out (no Mosaic variant — the MXU's
        # int8 path already accumulates s32 natively, so narrowing buys
        # nothing there; this path wins where integer dots lack fast
        # kernels, e.g. the XLA CPU backend)
        return _xla_histogram_narrow(binned, channels, num_bins, quant_max,
                                     chunk_f=chunk_f)
    # resolve 'auto' from the FULL build width when this call covers only
    # a feature group (chunk_f): engine choice must match the ungrouped
    # call or the grouped sums lose bit-identity across the f32 engines
    impl = _resolve_impl(impl, num_bins, chunk_f or binned.shape[1])
    if impl == "pallas":
        from .pallas_histogram import pallas_histogram
        if quantized:
            return pallas_histogram(binned, channels, num_bins, mode="int8",
                                    mbatch=mbatch, hist_layout=layout)
        return pallas_histogram(binned, channels, num_bins, mbatch=mbatch,
                                hist_layout=layout)
    return _xla_histogram(binned, channels, num_bins, mbatch=mbatch,
                          chunk_f=chunk_f)


def overlap_groups(f: int, overlap: int):
    """Contiguous feature-group bounds for the async-collective overlap.

    Splits ``f`` features into ``overlap`` near-equal contiguous groups
    (empty tail groups dropped): the distributed histogram build issues
    one collective per group as soon as that group's contraction
    finishes, so group g's reduce rides under group g+1's MXU work."""
    g = max(1, int(overlap))
    per = -(-f // g)
    return [(lo, min(lo + per, f)) for lo in range(0, f, per)]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "axis_name", "impl",
                                    "mbatch", "layout", "acc_bits",
                                    "quant_max", "overlap"))
def histogram(
    binned: jax.Array,      # [N, F] uint8/uint16/int32
    channels: jax.Array,    # [N, K] f32
    num_bins: int,          # B (static)
    axis_name: Optional[str] = None,
    impl: str = "auto",
    mbatch: int = 1,
    layout: str = "lane",
    acc_bits: int = 32,
    quant_max: int = 127,
    overlap: int = 0,
) -> jax.Array:             # [F, B, K] f32
    """Accumulate per-(feature, bin) sums of ``channels`` columns.

    ``overlap`` > 1 with an ``axis_name`` builds the histogram in that
    many contiguous feature groups with ONE psum per group, each issued
    while the next group still contracts (tpu_hist_overlap) — XLA's
    async scheduler hides the collective under the remaining MXU work.
    ``chunk_f`` pins the engines' row-chunk size to the full width, so
    the grouped sums are bit-identical to the ungrouped ones, and the
    per-element psum addends are unchanged — same bytes, same result."""
    if impl == "pallas":
        from .pallas_histogram import pallas_available
        if not pallas_available():
            raise RuntimeError(
                "tpu_hist_impl=pallas requires a TPU backend; use 'xla'")
    f = binned.shape[1]
    if axis_name is not None and overlap > 1 and f > 1:
        parts = []
        for lo, hi in overlap_groups(f, overlap):
            part = histogram_block(
                binned[:, lo:hi], channels, num_bins, impl=impl,
                mbatch=mbatch, layout=layout, acc_bits=acc_bits,
                quant_max=quant_max, chunk_f=f)
            # the reduce of group g is independent of group g+1's
            # contraction: XLA issues it async (-start/-done twins)
            with span("collective_reduce"):
                parts.append(lax.psum(part, axis_name))
        return jnp.concatenate(parts, axis=0)
    hist = histogram_block(binned, channels, num_bins, impl=impl,
                           mbatch=mbatch, layout=layout, acc_bits=acc_bits,
                           quant_max=quant_max)

    if axis_name is not None:
        # distributed data-parallel: the reference reduce-scatters histograms over
        # its socket/MPI Network (src/treelearner/data_parallel_tree_learner.cpp:223-300);
        # on TPU the equivalent is a psum over the ICI mesh axis.
        with span("collective_reduce"):
            hist = lax.psum(hist, axis_name)
    return hist
