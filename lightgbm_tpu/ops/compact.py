"""Compacted (physically partitioned) row storage for the serial tree learner.

TPU-native re-design of the reference's DataPartition
(reference: src/treelearner/data_partition.hpp Split — per-thread stable
partition of leaf row indices; CUDA variant
src/treelearner/cuda/cuda_data_partition.cu:288 GenDataToLeftBitVectorKernel +
:679 AggregateBlockOffsetKernel + :907 SplitInnerKernel — bitvector, prefix
sums, stable scatter).

The reference keeps an index permutation and gathers rows through it. On TPU,
random gathers/scatters run ~100x slower than streaming (measured ~0.05-0.1
Gelem/s vs 800 GB/s streams on v5e), so this module keeps the *rows
themselves* physically partitioned instead: every leaf owns a contiguous
segment of a packed row-record array, and each split streams the parent's
segment once, stably partitioning it in place. All data movement is
contiguous DMA (dynamic_slice / dynamic_update_slice), prefix sums, and
one-hot MXU matmuls — no gather/scatter anywhere.

Row records pack into a single uint8 matrix ``[N, C]``:

    [0, F)          binned features (uint8)
    [F, F+4)        grad   (f32 bytes, pre-multiplied by the sample weight)
    [F+4, F+8)      hess   (f32 bytes, pre-multiplied by the sample weight)
    [F+8, F+12)     sample weight (f32 bytes: 0 = out of bag, GOSS rows carry
                    their amplification — persists across trees so a bag
                    drawn in one row order stays the same *set of rows* after
                    later permutations, like the reference's bag_data_indices)
    [F+12, ..+4E)   E extra f32 columns carried through the permutation
                    (scores, label, weight — anything that must stay
                    row-aligned across trees)

f32 fields move through the one-hot compaction matmul as 4 exact uint8
columns (bf16 represents 0..255 exactly; each output row receives exactly one
input row, so the contraction is exact).

In-block stable compaction is a one-hot permutation matmul: rows' destination
slots are ranks from a prefix sum over the predicate, applied on the MXU.
Cross-block stitching uses double-width carry buffers flushed in full blocks
at dynamic offsets; right-child rows stream to a scratch array at their final
offsets and are copied back after the walk (in-place forward writes of the
right stream could overtake the read cursor).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .split import go_left_pred


class RowLayout(NamedTuple):
    """Static description of the packed row record (part of the jit key).

    ``packed4``: the bin columns are NIBBLE-packed — two features per byte
    (low nibble = even feature, high nibble = odd, the io/dataset.py
    pack4_matrix layout; reference: the 4-bit dense bin store,
    src/io/dense_bin.hpp DenseBin<true>). ``num_features`` stays the
    LOGICAL feature count; ``feat_cols`` is the stored byte width. Every
    consumer extracts nibbles with ``(byte >> 4*(f & 1)) & 0xF`` at its
    read site, so the full-width matrix never materializes and the
    streamed bin bytes halve (tpu_bin_pack4 training)."""
    num_features: int
    num_extra: int          # number of carried f32 columns (scores/label/...)
    packed4: bool = False   # bin columns nibble-packed (two features/byte)

    @property
    def feat_cols(self) -> int:
        """Stored bin byte columns (ceil(F/2) when nibble-packed)."""
        if self.packed4:
            return (self.num_features + 1) // 2
        return self.num_features

    @property
    def grad_off(self) -> int:
        return self.feat_cols

    @property
    def hess_off(self) -> int:
        return self.feat_cols + 4

    @property
    def cnt_off(self) -> int:
        return self.feat_cols + 8

    @property
    def extra_off(self) -> int:
        return self.feat_cols + 12

    @property
    def num_real_cols(self) -> int:
        """Columns carrying actual record bytes (rest is lane padding)."""
        return self.feat_cols + 12 + 4 * self.num_extra

    @property
    def num_cols(self) -> int:
        c = self.num_real_cols
        # round lanes up to the full 128-lane tile: TPU HBM layouts pad the
        # minor dimension to 128 anyway (tiled storage), so this costs no
        # physical memory, and the fused Pallas kernel (ops/fused_split.py)
        # requires the logical and physical layouts to coincide
        return -(-c // 128) * 128


def _f32_to_u8(x: jnp.ndarray) -> jnp.ndarray:
    """[N] f32 -> [N, 4] u8 (exact bitcast)."""
    return lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint8)


def _u8_to_f32(x: jnp.ndarray) -> jnp.ndarray:
    """[..., 4] u8 -> [...] f32 (exact bitcast)."""
    return lax.bitcast_convert_type(x, jnp.float32)


def pack_rows(
    binned: jnp.ndarray,     # [N, F] uint8
    grad: jnp.ndarray,       # [N] f32
    hess: jnp.ndarray,       # [N] f32
    cnt: jnp.ndarray,        # [N] f32/bool {0,1} in-bag mask
    extras: jnp.ndarray,     # [E, N] f32 carried columns
    layout: RowLayout,
    pad_rows: int,
) -> jnp.ndarray:
    """Pack per-row arrays into the work matrix, padded by ``pad_rows``
    garbage rows so blocked dynamic slices never clamp at the array end.

    With ``layout.packed4`` a full-width [N, F] bin matrix nibble-packs
    here (an already-packed [N, ceil(F/2)] matrix passes through)."""
    n = binned.shape[0]
    if layout.packed4 and binned.shape[1] == layout.num_features:
        if layout.num_features % 2:
            binned = jnp.pad(binned, ((0, 0), (0, 1)))
        binned = (binned[:, 0::2] | (binned[:, 1::2] << 4))
    parts = [
        binned.astype(jnp.uint8),
        _f32_to_u8(grad),
        _f32_to_u8(hess),
        _f32_to_u8(cnt.astype(jnp.float32)),
    ]
    if layout.num_extra:
        e = _f32_to_u8(extras.T.astype(jnp.float32))  # [N, E, 4]
        parts.append(e.reshape(n, 4 * layout.num_extra))
    work = jnp.concatenate(parts, axis=1)
    c = layout.num_cols
    pad_c = c - work.shape[1]
    return jnp.pad(work, ((0, pad_rows), (0, pad_c)))


def unpack_rows(work: jnp.ndarray, n: int, layout: RowLayout):
    """Inverse of pack_rows (on the first ``n`` rows; packed4 layouts
    unpack the nibbles back to the full [n, F] width)."""
    f = layout.num_features
    binned = work[:n, :layout.feat_cols]
    if layout.packed4:
        from .packed import unpack4
        binned = unpack4(binned, f)
    grad = _u8_to_f32(work[:n, layout.grad_off:layout.grad_off + 4])
    hess = _u8_to_f32(work[:n, layout.hess_off:layout.hess_off + 4])
    cnt = _u8_to_f32(work[:n, layout.cnt_off:layout.cnt_off + 4])
    if layout.num_extra:
        e = work[:n, layout.extra_off:layout.extra_off + 4 * layout.num_extra]
        extras = _u8_to_f32(e.reshape(n, layout.num_extra, 4)).T
    else:
        extras = jnp.zeros((0, n), jnp.float32)
    return binned, grad, hess, cnt, extras


def block_grad_hess_cnt(block: jnp.ndarray, layout: RowLayout):
    """Extract (grad, hess, sample weight) from a row-record block [BS, C]."""
    g = _u8_to_f32(block[:, layout.grad_off:layout.grad_off + 4])
    h = _u8_to_f32(block[:, layout.hess_off:layout.hess_off + 4])
    c = _u8_to_f32(block[:, layout.cnt_off:layout.cnt_off + 4])
    return g, h, c


def _compact_block(block: jnp.ndarray, go_left: jnp.ndarray, valid: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Stable-partition one block: returns ([2*BS, C] u8 with lefts compacted
    at [0, BS) and rights at [BS, 2*BS), n_left, n_right).

    One one-hot permutation matmul on the MXU (exact: each destination row
    receives exactly one 0..255-valued source row; bf16 holds 0..255 exactly
    and accumulation is f32).
    """
    bs, c = block.shape
    sel_l = go_left & valid
    sel_r = jnp.logical_not(go_left) & valid
    rank_l = jnp.cumsum(sel_l.astype(jnp.int32)) - sel_l
    rank_r = jnp.cumsum(sel_r.astype(jnp.int32)) - sel_r
    n_l = rank_l[-1] + sel_l[-1]
    n_r = rank_r[-1] + sel_r[-1]
    dest = jnp.where(sel_l, rank_l, jnp.where(sel_r, bs + rank_r, 2 * bs))
    iota2 = jnp.arange(2 * bs, dtype=jnp.int32)
    onehot = (dest[None, :] == iota2[:, None]).astype(jnp.bfloat16)  # [2BS, BS]
    comp = lax.dot_general(
        onehot, block.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return comp.astype(jnp.uint8), n_l, n_r


def _append_buf(buf: jnp.ndarray, cnt: jnp.ndarray, rows: jnp.ndarray,
                nrows: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append the first ``nrows`` of ``rows`` [BS, C] into the double-width
    carry buffer [2*BS, C] at offset ``cnt`` (zeros elsewhere)."""
    bs = rows.shape[0]
    iota = jnp.arange(bs, dtype=jnp.int32)
    masked = jnp.where((iota < nrows)[:, None], rows, 0)
    shifted = jnp.roll(jnp.pad(masked, ((0, bs), (0, 0))), cnt, axis=0)
    return buf + shifted, cnt + nrows


def _flush_full(dst: jnp.ndarray, buf: jnp.ndarray, cnt: jnp.ndarray,
                ptr: jnp.ndarray):
    """If the carry holds >= BS rows, write one full block at ``ptr``."""
    bs = buf.shape[0] // 2

    def do(args):
        dst, buf, cnt, ptr = args
        dst = lax.dynamic_update_slice(dst, buf[:bs], (ptr, 0))
        buf = jnp.concatenate([buf[bs:], jnp.zeros_like(buf[:bs])], axis=0)
        return dst, buf, cnt - bs, ptr + bs

    return lax.cond(cnt >= bs, do, lambda a: a, (dst, buf, cnt, ptr))


def _flush_tail(dst: jnp.ndarray, buf: jnp.ndarray, cnt: jnp.ndarray,
                ptr: jnp.ndarray) -> jnp.ndarray:
    """Blend-write the remaining < BS carry rows at ``ptr`` (read-modify-write
    so rows beyond the segment are preserved)."""
    bs = buf.shape[0] // 2
    cur = lax.dynamic_slice(dst, (ptr, 0), (bs, dst.shape[1]))
    iota = jnp.arange(bs, dtype=jnp.int32)
    out = jnp.where((iota < cnt)[:, None], buf[:bs], cur)
    return lax.dynamic_update_slice(dst, out, (ptr, 0))


def partition_segment(
    work: jnp.ndarray,       # [N + pad, C] u8 row records
    scratch: jnp.ndarray,    # [N + pad, C] u8 scratch (right-stream staging)
    start: jnp.ndarray,      # i32 segment start
    count: jnp.ndarray,      # i32 segment row count
    n_left: jnp.ndarray,     # i32 exact left-row count (from the split scan)
    feature: jnp.ndarray,    # i32 split feature
    bin_: jnp.ndarray,       # i32 threshold bin
    default_left: jnp.ndarray,
    nan_bin: jnp.ndarray,    # i32 NaN bin of the split feature
    is_cat: jnp.ndarray,     # bool
    cat_bitset: jnp.ndarray,  # [W] u32 bin bitset (categorical splits)
    block_size: int,
    packed4: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stably partition ``work[start:start+count]`` so left-child rows occupy
    ``[start, start+n_left)`` and right-child rows the remainder.

    ``packed4``: bin columns are nibble-packed (RowLayout.packed4) — the
    routing column reads the byte at ``feature >> 1`` and extracts the
    nibble selected by ``feature & 1``.

    Returns the updated (work, scratch). Everything streams: per block one
    contiguous read, one one-hot compaction matmul, and carry-buffered
    contiguous writes (lefts in place — the left write cursor can never
    overtake the read cursor; rights via scratch at final offsets, copied
    back afterwards).
    """
    bs = block_size
    c = work.shape[1]
    nblocks = (count + bs - 1) // bs
    iota = jnp.arange(bs, dtype=jnp.int32)
    zeros2 = jnp.zeros((2 * bs, c), jnp.uint8)

    def body(state):
        i, work, scratch, lbuf, lcnt, lptr, rbuf, rcnt, rptr = state
        blk = lax.dynamic_slice(work, (start + i * bs, 0), (bs, c))
        if packed4:
            byte = lax.dynamic_slice_in_dim(
                blk, feature >> 1, 1, axis=1)[:, 0].astype(jnp.int32)
            col = (byte >> ((feature & 1) * 4)) & 0xF
        else:
            col = lax.dynamic_slice_in_dim(blk, feature, 1, axis=1)[:, 0]
        valid = iota < (count - i * bs)
        gl = go_left_pred(col, bin_, default_left, nan_bin, is_cat,
                          cat_bitset)
        comp, n_l, n_r = _compact_block(blk, gl, valid)
        lbuf, lcnt = _append_buf(lbuf, lcnt, comp[:bs], n_l)
        rbuf, rcnt = _append_buf(rbuf, rcnt, comp[bs:], n_r)
        work, lbuf, lcnt, lptr = _flush_full(work, lbuf, lcnt, lptr)
        scratch, rbuf, rcnt, rptr = _flush_full(scratch, rbuf, rcnt, rptr)
        return i + 1, work, scratch, lbuf, lcnt, lptr, rbuf, rcnt, rptr

    state = (jnp.asarray(0, jnp.int32), work, scratch,
             zeros2, jnp.asarray(0, jnp.int32), start,
             zeros2, jnp.asarray(0, jnp.int32), start + n_left)
    state = lax.while_loop(lambda s: s[0] < nblocks, body, state)
    _, work, scratch, lbuf, lcnt, lptr, rbuf, rcnt, rptr = state

    work = _flush_tail(work, lbuf, lcnt, lptr)
    scratch = _flush_tail(scratch, rbuf, rcnt, rptr)

    # copy the right stream back from scratch (contiguous, block-aligned)
    n_right = count - n_left
    rblocks = (n_right + bs - 1) // bs

    def copy_body(state):
        j, work = state
        off = start + n_left + j * bs
        blk = lax.dynamic_slice(scratch, (off, 0), (bs, c))
        cur = lax.dynamic_slice(work, (off, 0), (bs, c))
        keep = iota < (n_right - j * bs)
        out = jnp.where(keep[:, None], blk, cur)
        work = lax.dynamic_update_slice(work, out, (off, 0))
        return j + 1, work

    _, work = lax.while_loop(
        lambda s: s[0] < rblocks, copy_body,
        (jnp.asarray(0, jnp.int32), work))
    return work, scratch


def segment_histogram(
    work: jnp.ndarray,       # [N + pad, C] u8
    start: jnp.ndarray,
    count: jnp.ndarray,
    layout: RowLayout,
    num_bins: int,
    block_size: int,
    impl: str = "auto",
    quantized: bool = False,
    mbatch: int = 1,
    acc_bits: int = 32,
    quant_max: int = 127,
    hist_layout: str = "lane",
    feat_idx=None,           # static int sequence: stored columns to build
    chunk_f: int = 0,        # feature width the row-chunk size derives from
) -> jnp.ndarray:            # [F, B, 4] f32 (int32 when quantized)
    """Histogram of one contiguous leaf segment, streamed in fixed blocks.

    Channels: (grad, hess, in-bag count, raw count). The in-bag count is the
    {0,1} indicator of a nonzero sample weight (reference: cnt_ counts bagged
    rows, not their weights). Counts accumulate in f32 and stay exact below
    2^24 rows — the raw-count channel drives the physical partition offsets,
    so exactness is required, not a nicety.

    ``quantized``: the grad/hess columns hold integer discretizer codes
    (|code| <= 127, stored as exact f32 — the row-record layout is
    unchanged); they re-pack into an int8 channel matrix per block and the
    contraction runs int8 x int8 -> int32 on the MXU (ops/histogram.py).
    All four channels come back as exact int32 sums (the GBDT bounds
    global num_data * quant_bins inside int32 before selecting this path).

    ``acc_bits=16`` (quantized only) selects the narrowed packed-pair
    accumulation — bit-identical int32 sums at half the contraction work
    where leaf bounds allow (ops/histogram.py _xla_histogram_narrow;
    reference: GetHistBitsInLeaf). ``layout.packed4`` streams nibble-packed
    bin bytes and unpacks per block inside histogram_block.

    ``feat_idx`` restricts the build to a feature GROUP (hist_overlap):
    only those stored columns are histogrammed, in the given order, so
    the distributed grower can issue one collective per group while the
    next group's walk still accumulates. ``chunk_f`` then pins the XLA
    engines' row-chunk size to the FULL feature width — the group build
    keeps the full-width call's accumulation order and stays
    bit-identical to the corresponding slice of the ungrouped histogram.
    """
    from .histogram import histogram_block

    f = layout.num_features
    b = num_bins
    bs = block_size
    c = work.shape[1]
    if feat_idx is not None:
        if layout.packed4:
            raise ValueError("feat_idx feature groups need byte-addressed "
                             "bin columns; packed4 layouts build ungrouped")
        feat_idx = jnp.asarray(feat_idx, jnp.int32)
        f = int(feat_idx.shape[0])
    nblocks = (count + bs - 1) // bs
    iota = jnp.arange(bs, dtype=jnp.int32)

    def body(state):
        j, acc = state
        blk = lax.dynamic_slice(work, (start + j * bs, 0), (bs, c))
        g, h, cw = block_grad_hess_cnt(blk, layout)
        if quantized:
            valid = iota < (count - j * bs)
            v8 = valid.astype(jnp.int8)
            inbag = (cw != 0.0).astype(jnp.int8) * v8
            # f32 -> int8 casts are exact: the codes are integers <= 127
            chans = jnp.stack([g.astype(jnp.int8) * v8,
                               h.astype(jnp.int8) * v8, inbag, v8], axis=1)
        else:
            valid = (iota < (count - j * bs)).astype(jnp.float32)
            cw = (cw != 0.0).astype(jnp.float32)
            chans = jnp.stack([g * valid, h * valid, cw * valid, valid],
                              axis=1)
        cols = blk[:, :layout.feat_cols]
        if feat_idx is not None:
            cols = jnp.take(cols, feat_idx, axis=1)
        acc = acc + histogram_block(
            cols, chans, b, impl=impl, mbatch=mbatch,
            packed4_features=f if layout.packed4 else 0,
            layout=hist_layout, acc_bits=acc_bits, quant_max=quant_max,
            chunk_f=chunk_f)
        return j + 1, acc

    acc0 = jnp.zeros((f, b, 4), jnp.int32 if quantized else jnp.float32)
    _, acc = lax.while_loop(
        lambda s: s[0] < nblocks, body, (jnp.asarray(0, jnp.int32), acc0))
    return acc


def segments_to_leaf_vectors(
    leaf_start: jnp.ndarray,   # [L] i32 (final leaf segments, disjoint tiling)
    leaf_rows: jnp.ndarray,    # [L] i32
    leaf_value: jnp.ndarray,   # [L] f32
    n: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expand per-leaf segments into per-row (leaf_id, leaf_value) vectors.

    Because final leaf segments tile [0, N) disjointly, a sparse
    delta-then-cumsum is exact (each closing delta cancels its opening delta
    completely before the next segment opens): no gathers, two O(N) scans.
    """
    ends = leaf_start + leaf_rows
    # 2L-point sparse delta arrays (tiny scatters), then exact prefix sums.
    # Values go through an int32 cumsum of their f32 *bit patterns*: wrapping
    # integer deltas cancel exactly (modular arithmetic) even when an open and
    # a close collide on the same scatter index, so every row reads back its
    # leaf value bit-for-bit — no gathers, two O(N) scans.
    idx = jnp.concatenate([leaf_start, ends])
    lid = jnp.arange(leaf_start.shape[0], dtype=jnp.int32)
    d_leaf = jnp.concatenate([lid, -lid])
    bits = lax.bitcast_convert_type(leaf_value.astype(jnp.float32), jnp.int32)
    d_val = jnp.concatenate([bits, -bits])
    # leaves with zero rows contribute cancelling deltas at the same index
    delta_leaf = jnp.zeros((n + 1,), jnp.int32).at[idx].add(d_leaf, mode="drop")
    delta_val = jnp.zeros((n + 1,), jnp.int32).at[idx].add(d_val, mode="drop")
    row_leaf = jnp.cumsum(delta_leaf)[:n]
    row_val = lax.bitcast_convert_type(jnp.cumsum(delta_val)[:n], jnp.float32)
    return row_leaf, row_val
