"""Leaf-wise (best-first) tree growth, fully on device.

TPU-native re-design of the reference's device tree learner
(reference: CUDASingleGPUTreeLearner::Train,
src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:158-345 — the loop
ConstructHistogramForLeaf -> SubtractHistogramForLeaf -> FindBestSplitsForLeaf ->
FindBestFromAllSplits -> Split; CPU analogue SerialTreeLearner::Train,
src/treelearner/serial_tree_learner.cpp:179).

Design, by TPU constraints (static shapes, no atomics, no cheap host
round-trips):

  * The whole tree grows inside one ``jax.lax.fori_loop`` — zero host syncs per
    tree (the CUDA learner ships one SplitInfo struct to host per split; we
    ship none).
  * Row->leaf assignment is a dense ``[N]`` int vector updated by masked where,
    instead of the reference's index-partition scatter
    (cuda_data_partition.cu:288 GenDataToLeftBitVectorKernel + prefix sums).
    The split column is read from a transposed ``[F, N]`` bin matrix so the
    per-split partition is one contiguous dynamic row slice, not a strided
    gather over the whole ``[N, F]`` matrix.
  * Per-leaf histograms stay resident in HBM (``[L, F, B, 3]``) and each split
    builds only the SMALLER child's histogram with one masked pass; the larger
    child is parent − smaller — the reference's histogram-subtraction trick
    (serial_tree_learner.cpp:404, cuda_histogram_constructor.cu:723
    SubtractHistogramKernel).
  * Early stop (no leaf with positive gain) becomes a ``done`` flag that turns
    remaining iterations into no-ops via ``lax.cond`` (skipping the histogram
    work), since ``fori_loop`` has a static trip count.

The same function runs under GSPMD sharding for data-parallel training: rows
are sharded, per-leaf histograms are ``psum``-ed over the mesh axis (replacing
the reference's socket/MPI ReduceScatter in data_parallel_tree_learner.cpp:
223-300), and every shard then takes identical split decisions.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.spans import span
from .histogram import histogram
from .split import (SplitParams, SplitResult, best_split, child_output,
                    depth_gate, go_left_pred, leaf_output)

_NEG_INF = -1e30

# rescan PRNG domain separator (compact grower's monotone-intermediate
# rescan): a fixed first fold keeps the extra_trees rescan draws
# independent of the leaf-array size — a rung-padded program
# (step_buckets) draws the same thresholds as the exact-keyed one — and
# out of the node-draw fold domain (direct folds stay <= 2*num_leaves+2
# < this for every legal num_leaves)
_RESCAN_FOLD_STRIDE = 1 << 20


def leaf_rung(num_leaves: int) -> int:
    """Power-of-two leaf-count rung of the bucketed step ladder.

    The grower's per-leaf state arrays (histogram cache, best-split cache,
    segment table) and its ``fori_loop`` trip count are sized by the jit
    key's ``num_leaves``; keying on the RUNG instead of the exact count
    means every ``num_leaves`` in (rung/2, rung] lowers the same program —
    inactive leaves are masked segments with zero-weight histograms, and
    the actual budget rides as a traced scalar (``leaf_budget``)."""
    r = 2
    while r < num_leaves:
        r *= 2
    return r


def depth_rung(max_depth: int) -> int:
    """Depth bucket of the step-ladder key.

    Training programs carry no depth-dependent shapes (depth only gates
    candidate gains), so the depth axis of the ladder collapses to two
    buckets: -1 = unlimited (the gate compiles away), +1 = bounded (the
    actual bound is the traced ``depth_budget``). That is the <= O(log
    max_depth) end of the compile-budget contract — one bounded-depth
    program per leaf rung, not one per max_depth value."""
    return -1 if max_depth <= 0 else 1


class GrowerParams(NamedTuple):
    """Static tree-growth hyper-parameters (hashable; part of the jit key)."""
    num_leaves: int = 31
    max_depth: int = -1
    num_bins: int = 256
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    # categorical-split knobs (reference: config.h:480-501)
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    any_cat: bool = True     # static: dataset has categorical features
    # voting-parallel (PV-Tree): per-shard top-k feature vote caps the
    # histogram reduction at 2k features (0 = off; reference: top_k config)
    voting_k: int = 0
    voting_shards: int = 0
    # constraints / per-node sampling (statics; defaults compile away)
    use_monotone: bool = False
    monotone_penalty: float = 0.0
    # intermediate monotone method (reference: IntermediateLeafConstraints,
    # monotone_constraints.hpp:516) — compact grower only; the masked
    # grower keeps the basic method
    mono_intermediate: bool = False
    path_smooth: float = 0.0
    use_interaction: bool = False
    bynode_fraction: float = 1.0
    use_cegb: bool = False
    cegb_split_pen: float = 0.0
    extra_trees: bool = False
    axis_name: Optional[str] = None
    hist_impl: str = "auto"  # auto | xla | pallas (ops/histogram.py dispatch)
    # compact-grower streaming block sizes (ops/grower_compact.py)
    part_block: int = 2048
    hist_block: int = 16384
    # fused per-split Mosaic kernel (ops/fused_split.py): 0 = off, else the
    # kernel's streaming block size (multiple of 32)
    fused_block: int = 0
    fused_interpret: bool = False   # Pallas interpret mode (CPU tests)
    # dual-residency segments (round 4). False = copy-back variant: all
    # segments stay in work, rights re-stream through scratch — slower, but
    # immune to the open dual+EFB TPU fault (ops/fused_split.py docstring)
    fused_dual: bool = True
    # timing bisect only (LGBM_TPU_FUSED_HIST_DEBUG=off|assembly|matmul):
    # disable all hist work / run channel assembly only / run one-hot
    # matmuls with constant channels — results are INVALID, timings
    # decompose the fused kernel's histogram cost
    fused_hist_debug: str = ""

    # EFB (io/efb.py): the scan axis extends past the stored columns with
    # one virtual feature per bundled original (0 = bundling off)
    efb_virtual: int = 0
    efb_bmax: int = 0

    # quantized-gradient integer histograms (compact grower): grad/hess
    # columns carry int8 discretizer codes, histograms accumulate
    # int8 x int8 -> int32 on the MXU and dequantize at the split scan
    # (reference: gradient_discretizer.cpp + cuda_histogram_constructor
    # .cu:249-524); the per-iteration scales ride as traced args
    quant_hist: bool = False
    # narrowed (16-bit) quantized accumulation (reference:
    # GetHistBitsInLeaf, gradient_discretizer.cpp): leaves whose code sums
    # fit the packing radix take the packed-pair engine — grad/hess and
    # inbag/raw pairs share one f32 channel each, HALF the contraction
    # work, bit-identical int32 sums (ops/histogram.py
    # _xla_histogram_narrow); bits renew per split as leaves shrink
    # (ops/renew.py hist_bits_in_leaf). XLA engine only — the MXU's int8
    # dot accumulates s32 natively, so Mosaic paths gain nothing
    quant_narrow: bool = False
    # static |code| bound for the narrowed engine's packing radix
    # (num_grad_quant_bins + 1; 127 = the raw int8 bound)
    quant_max: int = 127
    # 4-bit nibble-packed bin columns in the compact row records
    # (tpu_bin_pack4 training; RowLayout.packed4 is the operative static
    # key — this mirror keeps the knob visible on the params pytree)
    bin_pack4: bool = False
    # Mosaic one-hot register layout (tpu_hist_layout): "lane" = bins
    # along lanes (channel-major output, the batched-M block-diagonal
    # path), "sublane" = bins along sublanes for B <= 64
    # (ops/pallas_histogram.py _hist_kernel_sublane,
    # ops/fused_split.py hist_flush)
    hist_layout: str = "lane"
    # batched-M histogram depth (env/param tpu_hist_mbatch): K staged row
    # blocks per one-hot contraction fill M = 8K of the 128 MXU rows —
    # the fused kernel's pending ring, the Mosaic kernel's window
    # partition, and the XLA engine's chunk widening all key off this
    # (ops/fused_split.py hist_flush is the reference design). K = 1 is
    # the sync reference path; the ring multiplies histogram-side VMEM
    # residency by K (ops/fused_split.py fused_block_cap)
    hist_mbatch: int = 8
    # data-parallel histogram reduction: 0 = all-reduce (lax.psum) of the
    # full [F, B, 4] histogram; S > 0 = reduce-scatter over the feature
    # axis across S shards (lax.psum_scatter) + an all-gather of the tiny
    # per-shard best-split candidate — the reference's actual protocol
    # (ReduceScatter + SyncUpGlobalBestSplit,
    # data_parallel_tree_learner.cpp:223-300)
    hist_scatter: int = 0
    # bucketed step ladder (tpu_step_buckets): ``num_leaves`` holds the
    # power-of-two LEAF RUNG (leaf_rung) and ``max_depth`` the DEPTH
    # BUCKET (depth_rung: -1 unlimited / +1 bounded); the actual budgets
    # arrive as the traced scalars (leaf_budget, depth_budget), so one
    # program serves every (num_leaves, max_depth) in the rung
    step_buckets: bool = False
    # async histogram-collective overlap (tpu_hist_overlap): > 1 = build
    # the local histogram in that many feature groups and reduce each
    # group separately, issuing group g's psum_scatter/all-reduce while
    # group g+1 still accumulates (double-buffered hist slots) — comm
    # hides under the contraction, collective bytes unchanged
    hist_overlap: int = 0

    def split_params(self) -> SplitParams:
        return SplitParams(
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            min_gain_to_split=self.min_gain_to_split,
            max_delta_step=self.max_delta_step,
            max_cat_threshold=self.max_cat_threshold,
            cat_l2=self.cat_l2,
            cat_smooth=self.cat_smooth,
            max_cat_to_onehot=self.max_cat_to_onehot,
            min_data_per_group=self.min_data_per_group,
            enable_sorted_cat=self.any_cat,
            use_monotone=self.use_monotone,
            monotone_penalty=self.monotone_penalty,
            path_smooth=self.path_smooth,
            use_cegb=self.use_cegb,
            cegb_split_pen=self.cegb_split_pen,
            extra_trees=self.extra_trees,
        )

    @property
    def bitset_words(self) -> int:
        return -(-self.num_bins // 32)


class TreeArrays(NamedTuple):
    """Struct-of-arrays tree (reference: Tree, include/LightGBM/tree.h:26).

    Nodes are indexed 0..num_leaves-2 in creation order; child pointers >= 0
    reference internal nodes, negative values ~leaf (i.e. -(leaf_idx+1))
    reference leaves — same convention as the reference's Tree arrays.
    """
    split_feature: jax.Array   # [L-1] i32 (-1 = unused node)
    split_bin: jax.Array       # [L-1] i32 threshold bin (numerical: left is bin <= t)
    cat_bitset: jax.Array      # [L-1, W] u32 bin bitset for categorical splits
    split_gain: jax.Array      # [L-1] f32
    default_left: jax.Array    # [L-1] bool
    left_child: jax.Array      # [L-1] i32
    right_child: jax.Array     # [L-1] i32
    leaf_value: jax.Array      # [L] f32
    leaf_weight: jax.Array     # [L] f32 (sum of hessians)
    leaf_count: jax.Array      # [L] f32 (weighted row count)
    leaf_parent: jax.Array     # [L] i32 node whose child the leaf is
    leaf_depth: jax.Array      # [L] i32
    internal_value: jax.Array  # [L-1] f32 output the node would emit as a leaf
    internal_weight: jax.Array  # [L-1] f32 hessian sum at the node
    internal_count: jax.Array  # [L-1] f32 row count at the node
    num_leaves: jax.Array      # scalar i32: actual number of leaves
    num_nodes: jax.Array       # scalar i32: actual number of internal nodes


class GrowerState(NamedTuple):
    done: jax.Array
    num_nodes: jax.Array
    row_leaf: jax.Array
    # per-leaf histograms resident in HBM [L, F, B, K]
    leaf_hist: jax.Array
    # tree arrays under construction
    split_feature: jax.Array
    split_bin: jax.Array
    cat_bitset: jax.Array      # [L-1, W] u32
    split_gain: jax.Array
    default_left: jax.Array
    left_child: jax.Array
    right_child: jax.Array
    leaf_parent: jax.Array
    leaf_parent_side: jax.Array
    leaf_depth: jax.Array
    # per-internal-node aggregates (for model export / plotting)
    node_grad: jax.Array
    node_hess: jax.Array
    node_cnt: jax.Array
    # per-leaf aggregates
    leaf_grad: jax.Array
    leaf_hess: jax.Array
    leaf_cnt: jax.Array
    # lazy CEGB charged-rows bitmap [F, N] (dummy [1, 1] when off)
    cegb_charged: jax.Array
    # per-leaf cached best splits
    bs_gain: jax.Array
    bs_feature: jax.Array
    bs_bin: jax.Array
    bs_default_left: jax.Array
    bs_left_grad: jax.Array
    bs_left_hess: jax.Array
    bs_left_cnt: jax.Array
    bs_bitset: jax.Array       # [L, W] u32 cached categorical bitsets
    bs_cat_l2: jax.Array       # [L] bool: cached split uses lambda_l2+cat_l2
    # per-leaf outputs fixed at split time (reference stores left_output/
    # right_output in SplitInfo; sorted-categorical splits use l2+cat_l2)
    leaf_out: jax.Array        # [L] f32
    # monotone output bounds per leaf (reference: BasicConstraintEntry)
    leaf_cmin: jax.Array       # [L] f32
    leaf_cmax: jax.Array       # [L] f32
    # features used on the path to each leaf (interaction constraints)
    leaf_used: jax.Array       # [L, F] bool
    # output of the parent at leaf creation (path smoothing context)
    leaf_pout: jax.Array       # [L] f32
    # features already used by any split (CEGB coupled costs paid once)
    cegb_used: jax.Array       # [F] bool


def _leaf_best_split(hist3, pg, ph, pc, feat_info, feat_mask, depth,
                     params: GrowerParams, mono_types=None, cmin=None,
                     cmax=None, pout=0.0, cegb_pen=None, extra_key=None,
                     feature_contri=None, depth_budget=None):
    num_bins_arr, nan_bin_arr, has_nan_arr, is_cat_arr = feat_info
    with span("split_scan"):
        sp = best_split(
            hist3, pg, ph, pc,
            num_bins_arr, nan_bin_arr, has_nan_arr, is_cat_arr, feat_mask,
            params.split_params(), mono_types, cmin, cmax, pout, depth,
            cegb_pen, extra_key, feature_contri,
        )
    return sp._replace(gain=depth_gate(sp.gain, depth, params.max_depth,
                                       depth_budget))


def node_feature_mask(feat_mask, used, inter_sets, key, params):
    """Per-node allowed features: interaction constraints restrict to the
    union of constraint sets containing every feature already used on the
    path (reference: ColSampler::GetByNode, col_sampler.hpp), then
    feature_fraction_bynode Bernoulli-samples the survivors (documented
    deviation: the reference draws an exact-count sample)."""
    fm = feat_mask
    if params.use_interaction:
        subset = jnp.logical_not(
            jnp.any(used[None, :] & jnp.logical_not(inter_sets), axis=1))
        allowed = jnp.any(subset[:, None] & inter_sets, axis=0)
        fm = fm & allowed
    if params.bynode_fraction < 1.0:
        keep = jax.random.uniform(key, fm.shape) < params.bynode_fraction
        keep = jnp.where(jnp.any(keep & fm), keep, True)
        fm = fm & keep
    return fm


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree(
    binned: jax.Array,        # [N, F] uint8/uint16
    grad: jax.Array,          # [N] f32 (already multiplied by sample weights/mask)
    hess: jax.Array,          # [N] f32 (already multiplied by sample weights/mask)
    cnt_weight: jax.Array,    # [N] f32 in {0,1}: bagging mask (row counts)
    num_bins_arr: jax.Array,  # [F] i32
    nan_bin_arr: jax.Array,   # [F] i32
    has_nan_arr: jax.Array,   # [F] bool
    is_cat_arr: jax.Array,    # [F] bool
    feat_mask: jax.Array,     # [F] bool
    params: GrowerParams,
    mono_types: Optional[jax.Array] = None,   # [F] i8 (use_monotone)
    inter_sets: Optional[jax.Array] = None,   # [S, F] bool (use_interaction)
    bynode_key: Optional[jax.Array] = None,   # PRNG key (bynode_fraction<1)
    cegb_coupled: Optional[jax.Array] = None,  # [F] tradeoff*coupled costs
    cegb_used0: Optional[jax.Array] = None,    # [F] bool (persisted model-level)
    extra_key: Optional[jax.Array] = None,     # PRNG key (extra_trees)
    feature_contri: Optional[jax.Array] = None,  # [F] gain multipliers
    forced: Optional[tuple] = None,   # (leaf[J], feature[J], bin[J]) arrays
    cegb_lazy: Optional[jax.Array] = None,     # [F] tradeoff*lazy costs
    cegb_charged0: Optional[jax.Array] = None,  # [F, N] bool (persisted)
    leaf_budget: Optional[jax.Array] = None,   # i32 actual leaf budget
    depth_budget: Optional[jax.Array] = None,  # i32 actual depth bound
):
    """Grow one tree; returns (TreeArrays, row_leaf [N] i32), plus the
    updated [F, N] charged-rows bitmap when ``cegb_lazy`` is set (lazy
    feature penalties persist per (row, feature) across the whole model —
    reference: feature_used_in_data_, cost_effective_gradient_boosting
    .hpp:62,125).

    ``params.step_buckets``: ``params.num_leaves`` is the power-of-two
    rung and ``leaf_budget``/``depth_budget`` carry the ACTUAL budgets as
    traced scalars — rounds past the leaf budget are masked no-ops and
    the padded leaves stay zero-weight segments, so the grown tree is
    bit-identical to the exact-keyed program while the jit key stays on
    (rung, depth bucket, mode, dtype)."""
    n, f = binned.shape
    L = params.num_leaves
    if params.step_buckets and leaf_budget is None:
        raise ValueError("params.step_buckets needs the traced leaf_budget "
                         "(the rung is the jit key, not the leaf count)")
    if params.step_buckets and params.max_depth > 0 and depth_budget is None:
        raise ValueError("params.step_buckets with the bounded depth "
                         "bucket needs the traced depth_budget (max_depth "
                         "is the bucket sentinel, not the actual bound)")
    dbudget = depth_budget if (params.step_buckets
                               and params.max_depth > 0) else None
    use_lazy = cegb_lazy is not None
    if use_lazy and cegb_charged0 is None:
        cegb_charged0 = jnp.zeros((f, n), bool)
    B = params.num_bins
    ax = params.axis_name
    feat_info = (num_bins_arr, nan_bin_arr, has_nan_arr, is_cat_arr)

    grad = grad.astype(jnp.float32)
    hess = hess.astype(jnp.float32)
    cnt_weight = cnt_weight.astype(jnp.float32)
    # contiguous per-feature rows for the split partition (one dynamic row
    # slice per split instead of a strided column gather from [N, F])
    binned_t = binned.T

    # voting with 2k >= F elects every feature — the vote is a no-op, so
    # the grower must run the data-parallel program EXACTLY (same
    # histogram chunking, same parent-minus-smaller subtraction): the
    # fresh-both-children voting variant rounds its f32 sums differently
    # and the last-ulp gain noise flips split tie-breaks vs the data
    # learner (the pre-PR-8 tier-1 voting-parity failure)
    voting_live = (params.voting_k > 0 and params.voting_shards > 1
                   and min(2 * params.voting_k, f) < f)

    def hist3(mask):
        with span("hist_build"):
            chans = jnp.stack(
                [grad * mask, hess * mask, cnt_weight * mask], axis=1)
            if voting_live:
                from ..parallel.voting import voting_histogram
                return voting_histogram(
                    binned, chans, B, params.voting_shards,
                    params.voting_k, params.split_params(),
                    impl=params.hist_impl,
                    mbatch=params.hist_mbatch,
                    layout=params.hist_layout,
                    overlap=params.hist_overlap)
            return histogram(binned, chans, B, ax, impl=params.hist_impl,
                             mbatch=params.hist_mbatch,
                             layout=params.hist_layout,
                             overlap=params.hist_overlap)

    if mono_types is None:
        mono_types = jnp.zeros((f,), jnp.int8)
    if inter_sets is None:
        inter_sets = jnp.zeros((0, f), bool)
    if bynode_key is None:
        bynode_key = jax.random.PRNGKey(0)
    if cegb_coupled is None:
        cegb_coupled = jnp.zeros((f,), jnp.float32)
    if cegb_used0 is None:
        cegb_used0 = jnp.zeros((f,), bool)
    if extra_key is None:
        extra_key = jax.random.PRNGKey(6)
    big = jnp.float32(3.4e38)

    # batched best-split over the two fresh children (one fused scan);
    # cegb_pen is per-child [2, F] (lazy costs differ between children)
    def two_best_splits(h2, pg2, ph2, pc2, fm2, depth, cmin2, cmax2, pout2,
                        cegb_pen2, ek2):
        fn = lambda h, pg, ph, pc, fm, cmn, cmx, po, pen, ek: \
            _leaf_best_split(
                h, pg, ph, pc, feat_info, fm, depth, params, mono_types,
                cmn, cmx, po, pen, ek, feature_contri, dbudget)
        return jax.vmap(fn)(h2, pg2, ph2, pc2, fm2, cmin2, cmax2, pout2,
                            cegb_pen2, ek2)

    # ---- root ----
    root_g = grad.sum()
    root_h = hess.sum()
    root_c = cnt_weight.sum()
    if ax is not None:
        with span("collective_reduce"):
            root_g = lax.psum(root_g, ax)
            root_h = lax.psum(root_h, ax)
            root_c = lax.psum(root_c, ax)
    root_hist = hist3(jnp.ones_like(cnt_weight))
    root_fm = node_feature_mask(
        feat_mask, jnp.zeros((f,), bool), inter_sets,
        jax.random.fold_in(bynode_key, 0), params)
    # path smoothing at the root smooths toward the root's own output
    # (reference: GetParentOutput, serial_tree_learner.cpp:1005-1016)
    root_out = leaf_output(root_g, root_h, params.split_params())
    bag = (cnt_weight != 0.0).astype(jnp.float32)
    if use_lazy:
        # on-demand (lazy) feature costs: penalty * bagged rows of the leaf
        # not yet charged for the feature (reference:
        # CalculateOndemandCosts, cost_effective_gradient_boosting.hpp:139)
        u_root = jnp.logical_not(cegb_charged0).astype(jnp.float32) @ bag
        pen_root = (cegb_coupled * jnp.logical_not(cegb_used0)
                    + cegb_lazy * u_root)
    else:
        pen_root = cegb_coupled * jnp.logical_not(cegb_used0)
    sp0 = _leaf_best_split(
        root_hist, root_g, root_h, root_c, feat_info, root_fm,
        jnp.asarray(0, jnp.int32), params, mono_types,
        -big, big, root_out, pen_root,
        jax.random.fold_in(extra_key, 0), feature_contri, dbudget,
    )

    i32 = jnp.int32
    W = params.bitset_words
    leaf_hist0 = jnp.zeros((L, f, B, 3), jnp.float32).at[0].set(root_hist)
    st = GrowerState(
        done=jnp.asarray(False),
        cegb_charged=(cegb_charged0 if use_lazy
                      else jnp.zeros((1, 1), bool)),
        num_nodes=jnp.asarray(0, i32),
        row_leaf=jnp.zeros((n,), i32),
        leaf_hist=leaf_hist0,
        split_feature=jnp.full((L - 1,), -1, i32),
        split_bin=jnp.zeros((L - 1,), i32),
        cat_bitset=jnp.zeros((L - 1, W), jnp.uint32),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        default_left=jnp.zeros((L - 1,), bool),
        left_child=jnp.full((L - 1,), -1, i32),
        right_child=jnp.full((L - 1,), -1, i32),
        leaf_parent=jnp.full((L,), -1, i32),
        leaf_parent_side=jnp.zeros((L,), i32),
        leaf_depth=jnp.zeros((L,), i32),
        node_grad=jnp.zeros((L - 1,), jnp.float32),
        node_hess=jnp.zeros((L - 1,), jnp.float32),
        node_cnt=jnp.zeros((L - 1,), jnp.float32),
        leaf_grad=jnp.zeros((L,), jnp.float32).at[0].set(root_g),
        leaf_hess=jnp.zeros((L,), jnp.float32).at[0].set(root_h),
        leaf_cnt=jnp.zeros((L,), jnp.float32).at[0].set(root_c),
        bs_gain=jnp.full((L,), _NEG_INF, jnp.float32).at[0].set(sp0.gain),
        bs_feature=jnp.zeros((L,), i32).at[0].set(sp0.feature),
        bs_bin=jnp.zeros((L,), i32).at[0].set(sp0.bin),
        bs_default_left=jnp.zeros((L,), bool).at[0].set(sp0.default_left),
        bs_left_grad=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_grad),
        bs_left_hess=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_hess),
        bs_left_cnt=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_count),
        bs_bitset=jnp.zeros((L, W), jnp.uint32).at[0].set(sp0.cat_bitset),
        bs_cat_l2=jnp.zeros((L,), bool).at[0].set(sp0.is_cat_l2),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(root_out),
        leaf_cmin=jnp.full((L,), -3.4e38, jnp.float32),
        leaf_cmax=jnp.full((L,), 3.4e38, jnp.float32),
        leaf_used=jnp.zeros((L, f), bool),
        leaf_pout=jnp.zeros((L,), jnp.float32).at[0].set(root_out),
        cegb_used=cegb_used0,
    )

    def body(k, st: GrowerState) -> GrowerState:
        # ---- FindBestFromAllSplits (reference: cuda_best_split_finder.cu:2113) ----
        leaf_alive = jnp.arange(L) <= k
        gains = jnp.where(leaf_alive, st.bs_gain, _NEG_INF)
        best_leaf = jnp.argmax(gains).astype(i32)
        valid = gains[best_leaf] > 0.0
        if params.step_buckets:
            # rounds past the traced leaf budget are inert — the rung's
            # remaining iterations run the same program with zero trip
            # counts, exactly like a post-early-stop round
            valid = jnp.logical_and(valid, k < leaf_budget - 1)
        applied = jnp.logical_and(valid, jnp.logical_not(st.done))
        done = jnp.logical_or(st.done, jnp.logical_not(valid))

        node = k
        new_leaf = jnp.asarray(k + 1, i32)

        f_ = st.bs_feature[best_leaf]
        b_ = st.bs_bin[best_leaf]
        dl = st.bs_default_left[best_leaf]
        bits = st.bs_bitset[best_leaf]
        catl2 = st.bs_cat_l2[best_leaf]
        if forced is not None:
            # the first len(forced) splits are dictated by the user's JSON
            # tree (reference: SerialTreeLearner::ForceSplits,
            # serial_tree_learner.cpp:620 — forced splits apply before the
            # gain-driven growth). The target leaf ids were precomputed on
            # the host from the creation-order convention.
            fleaf, ffeat, fbin = forced
            j_forced = fleaf.shape[0]
            is_forced = k < j_forced
            if params.step_buckets:
                # forced splits must respect the traced budget too: the
                # rung loop runs rounds the exact-keyed num_leaves-1 loop
                # never had, and an ungated is_forced would re-enable
                # `applied` past leaf_budget (e.g. a forced schedule
                # parsed under a larger pre-reset_parameter num_leaves)
                is_forced = jnp.logical_and(is_forced, k < leaf_budget - 1)
            kf = jnp.minimum(k, j_forced - 1)
            best_leaf = jnp.where(is_forced, fleaf[kf], best_leaf)
            f_ = jnp.where(is_forced, ffeat[kf], f_)
            b_ = jnp.where(is_forced, fbin[kf], b_)
            dl = jnp.where(is_forced, False, dl)
            bits = jnp.where(is_forced, 0, bits)
            catl2 = jnp.where(is_forced, False, catl2)
            # sums for the forced (feature, bin): one feature row sliced
            # from the leaf's histogram, then a single-bin cumulative read
            frow = lax.dynamic_slice_in_dim(
                st.leaf_hist[best_leaf], f_, 1, axis=0)[0]   # [B, K]
            cum = jnp.cumsum(frow, axis=0)
            flg = cum[b_, 0]
            flh = cum[b_, 1]
            flc = cum[b_, 2]
            applied = jnp.logical_or(applied, is_forced)
            done = jnp.where(is_forced, False, done)

        # ---- record split; wire tree structure ----
        split_feature = st.split_feature.at[node].set(jnp.where(applied, f_, -1))
        split_bin = st.split_bin.at[node].set(jnp.where(applied, b_, 0))
        cat_bitset = st.cat_bitset.at[node].set(jnp.where(applied, bits, 0))
        gain_rec = st.bs_gain[best_leaf]
        if forced is not None:
            # the cached candidate gain belongs to a different (feature,
            # bin); record 0 for forced nodes (reference reports the forced
            # SplitInfo's own gain, which we do not evaluate)
            gain_rec = jnp.where(is_forced, 0.0, gain_rec)
        split_gain = st.split_gain.at[node].set(
            jnp.where(applied, gain_rec, 0.0))
        default_left = st.default_left.at[node].set(jnp.where(applied, dl, False))
        p = st.leaf_parent[best_leaf]
        side = st.leaf_parent_side[best_leaf]
        p_idx = jnp.maximum(p, 0)
        left_child = st.left_child.at[p_idx].set(
            jnp.where(applied & (p >= 0) & (side == 0), node, st.left_child[p_idx]))
        right_child = st.right_child.at[p_idx].set(
            jnp.where(applied & (p >= 0) & (side == 1), node, st.right_child[p_idx]))
        left_child = left_child.at[node].set(
            jnp.where(applied, -(best_leaf + 1), left_child[node]))
        right_child = right_child.at[node].set(
            jnp.where(applied, -(new_leaf + 1), right_child[node]))
        leaf_parent = st.leaf_parent.at[best_leaf].set(
            jnp.where(applied, node, st.leaf_parent[best_leaf]))
        leaf_parent = leaf_parent.at[new_leaf].set(
            jnp.where(applied, node, leaf_parent[new_leaf]))
        leaf_parent_side = st.leaf_parent_side.at[best_leaf].set(
            jnp.where(applied, 0, st.leaf_parent_side[best_leaf]))
        leaf_parent_side = leaf_parent_side.at[new_leaf].set(
            jnp.where(applied, 1, leaf_parent_side[new_leaf]))

        # ---- partition rows (reference: CUDADataPartition::SplitInner) ----
        with span("partition"):
            fcol = lax.dynamic_slice_in_dim(
                binned_t, f_, 1, axis=0)[0].astype(i32)
            nb = nan_bin_arr[f_]
            iscat = is_cat_arr[f_]
            go_left = go_left_pred(fcol, b_, dl, nb, iscat, bits)
            row_leaf = jnp.where(
                applied & (st.row_leaf == best_leaf)
                & jnp.logical_not(go_left),
                new_leaf,
                st.row_leaf,
            )

        # ---- per-leaf aggregates for the two children ----
        lg, lh, lc = (st.bs_left_grad[best_leaf], st.bs_left_hess[best_leaf],
                      st.bs_left_cnt[best_leaf])
        if forced is not None:
            lg = jnp.where(is_forced, flg, lg)
            lh = jnp.where(is_forced, flh, lh)
            lc = jnp.where(is_forced, flc, lc)
        pg, ph, pc = (st.leaf_grad[best_leaf], st.leaf_hess[best_leaf],
                      st.leaf_cnt[best_leaf])
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        node_grad = st.node_grad.at[node].set(jnp.where(applied, pg, 0.0))
        node_hess = st.node_hess.at[node].set(jnp.where(applied, ph, 0.0))
        node_cnt = st.node_cnt.at[node].set(jnp.where(applied, pc, 0.0))
        d_child = st.leaf_depth[best_leaf] + 1
        leaf_grad = st.leaf_grad.at[best_leaf].set(jnp.where(applied, lg, pg))
        leaf_grad = leaf_grad.at[new_leaf].set(
            jnp.where(applied, rg, leaf_grad[new_leaf]))
        leaf_hess = st.leaf_hess.at[best_leaf].set(jnp.where(applied, lh, ph))
        leaf_hess = leaf_hess.at[new_leaf].set(
            jnp.where(applied, rh, leaf_hess[new_leaf]))
        leaf_cnt = st.leaf_cnt.at[best_leaf].set(jnp.where(applied, lc, pc))
        leaf_cnt = leaf_cnt.at[new_leaf].set(
            jnp.where(applied, rc, leaf_cnt[new_leaf]))
        leaf_depth = st.leaf_depth.at[best_leaf].set(
            jnp.where(applied, d_child, st.leaf_depth[best_leaf]))
        leaf_depth = leaf_depth.at[new_leaf].set(
            jnp.where(applied, d_child, leaf_depth[new_leaf]))
        # child outputs fixed now, under the parent leaf's monotone bounds
        # and smoothing context (reference: SplitInfo left/right_output)
        sp_ = params.split_params()
        l2_used = params.lambda_l2 + params.cat_l2 * catl2.astype(jnp.float32)
        cminp = st.leaf_cmin[best_leaf]
        cmaxp = st.leaf_cmax[best_leaf]
        poutp = st.leaf_pout[best_leaf]
        lw = child_output(lg, lh, lc, sp_, l2_used, poutp, cminp, cmaxp)
        rw = child_output(rg, rh, rc, sp_, l2_used, poutp, cminp, cmaxp)
        leaf_out = st.leaf_out.at[best_leaf].set(
            jnp.where(applied, lw, st.leaf_out[best_leaf]))
        leaf_out = leaf_out.at[new_leaf].set(
            jnp.where(applied, rw, leaf_out[new_leaf]))
        leaf_pout = st.leaf_pout.at[best_leaf].set(
            jnp.where(applied, lw, poutp))
        leaf_pout = leaf_pout.at[new_leaf].set(
            jnp.where(applied, rw, leaf_pout[new_leaf]))

        # monotone bound propagation, basic method (reference:
        # BasicLeafConstraints::Update — children bounded by the midpoint)
        iscat_split = is_cat_arr[f_]
        if params.use_monotone:
            mt = mono_types[f_].astype(jnp.int32)
            mid = 0.5 * (lw + rw)
            act = applied & jnp.logical_not(iscat_split)
            cmax_l = jnp.where(act & (mt > 0), jnp.minimum(cmaxp, mid), cmaxp)
            cmin_l = jnp.where(act & (mt < 0), jnp.maximum(cminp, mid), cminp)
            cmin_r = jnp.where(act & (mt > 0), jnp.maximum(cminp, mid), cminp)
            cmax_r = jnp.where(act & (mt < 0), jnp.minimum(cmaxp, mid), cmaxp)
        else:
            cmax_l = cmax_r = cmaxp
            cmin_l = cmin_r = cminp
        leaf_cmin = st.leaf_cmin.at[best_leaf].set(
            jnp.where(applied, cmin_l, cminp))
        leaf_cmin = leaf_cmin.at[new_leaf].set(
            jnp.where(applied, cmin_r, leaf_cmin[new_leaf]))
        leaf_cmax = st.leaf_cmax.at[best_leaf].set(
            jnp.where(applied, cmax_l, cmaxp))
        leaf_cmax = leaf_cmax.at[new_leaf].set(
            jnp.where(applied, cmax_r, leaf_cmax[new_leaf]))

        used_child = st.leaf_used[best_leaf] | (jnp.arange(f) == f_)
        leaf_used = st.leaf_used.at[best_leaf].set(
            jnp.where(applied, used_child, st.leaf_used[best_leaf]))
        leaf_used = leaf_used.at[new_leaf].set(
            jnp.where(applied, used_child, leaf_used[new_leaf]))
        cegb_used = st.cegb_used | (applied & (jnp.arange(f) == f_))
        if use_lazy:
            # charge every bagged row of the parent for the split feature
            # (reference: UpdateLeafBestSplits runs BEFORE the partition,
            # serial_tree_learner.cpp:768 — the parent's full row set)
            in_parent = ((row_leaf == best_leaf) | (row_leaf == new_leaf)) \
                & (cnt_weight != 0.0)
            cegb_charged = st.cegb_charged.at[f_].set(
                st.cegb_charged[f_] | (applied & in_parent))
        else:
            cegb_charged = st.cegb_charged

        # ---- children histograms + best splits (skipped when done) ----
        bs_arrays = (st.leaf_hist, st.bs_gain, st.bs_feature, st.bs_bin,
                     st.bs_default_left, st.bs_left_grad, st.bs_left_hess,
                     st.bs_left_cnt, st.bs_bitset, st.bs_cat_l2)

        def compute_children(bs):
            (leaf_hist, bs_gain, bs_feature, bs_bin, bs_dl, bs_lg, bs_lh,
             bs_lc, bs_bits, bs_catl2) = bs
            if voting_live:
                # voting elects a DIFFERENT feature subset per histogram
                # (unvoted features are zeroed), so parent-minus-smaller
                # subtraction would mix inconsistent elected sets — build
                # both children fresh instead (the reference's voting
                # learner re-elects per FindBestSplits round too,
                # voting_parallel_tree_learner.cpp:151)
                hist_left = hist3((row_leaf == best_leaf).astype(jnp.float32))
                hist_right = hist3((row_leaf == new_leaf).astype(jnp.float32))
            else:
                # one masked pass over the SMALLER child only; the larger
                # child is parent − smaller (reference:
                # SubtractHistogramForLeaf, cuda_histogram_constructor.cu:723)
                parent_hist = leaf_hist[best_leaf]
                left_smaller = lc <= rc
                small_id = jnp.where(left_smaller, best_leaf, new_leaf)
                m = (row_leaf == small_id).astype(jnp.float32)
                hist_small = hist3(m)
                hist_large = parent_hist - hist_small
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
            leaf_hist = leaf_hist.at[best_leaf].set(hist_left)
            leaf_hist = leaf_hist.at[new_leaf].set(hist_right)

            h2 = jnp.stack([hist_left, hist_right])
            fm_l = node_feature_mask(
                feat_mask, used_child, inter_sets,
                jax.random.fold_in(bynode_key, 2 * k + 1), params)
            fm_r = node_feature_mask(
                feat_mask, used_child, inter_sets,
                jax.random.fold_in(bynode_key, 2 * k + 2), params)
            pen_base = cegb_coupled * jnp.logical_not(cegb_used)
            if use_lazy:
                unch = jnp.logical_not(cegb_charged).astype(jnp.float32)
                bagm = cnt_weight != 0.0
                u_l = unch @ ((row_leaf == best_leaf) & bagm) \
                    .astype(jnp.float32)
                u_r = unch @ ((row_leaf == new_leaf) & bagm) \
                    .astype(jnp.float32)
                pen2 = jnp.stack([pen_base + cegb_lazy * u_l,
                                  pen_base + cegb_lazy * u_r])
            else:
                pen2 = jnp.stack([pen_base, pen_base])
            sp = two_best_splits(
                h2, jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                jnp.stack([lc, rc]), jnp.stack([fm_l, fm_r]), d_child,
                jnp.stack([cmin_l, cmin_r]), jnp.stack([cmax_l, cmax_r]),
                jnp.stack([lw, rw]), pen2,
                jnp.stack([jax.random.fold_in(extra_key, 2 * k + 1),
                           jax.random.fold_in(extra_key, 2 * k + 2)]))
            bs_gain = bs_gain.at[best_leaf].set(sp.gain[0]).at[new_leaf].set(sp.gain[1])
            bs_feature = bs_feature.at[best_leaf].set(sp.feature[0]).at[new_leaf].set(sp.feature[1])
            bs_bin = bs_bin.at[best_leaf].set(sp.bin[0]).at[new_leaf].set(sp.bin[1])
            bs_dl = bs_dl.at[best_leaf].set(sp.default_left[0]).at[new_leaf].set(sp.default_left[1])
            bs_lg = bs_lg.at[best_leaf].set(sp.left_grad[0]).at[new_leaf].set(sp.left_grad[1])
            bs_lh = bs_lh.at[best_leaf].set(sp.left_hess[0]).at[new_leaf].set(sp.left_hess[1])
            bs_lc = bs_lc.at[best_leaf].set(sp.left_count[0]).at[new_leaf].set(sp.left_count[1])
            bs_bits = bs_bits.at[best_leaf].set(sp.cat_bitset[0]) \
                .at[new_leaf].set(sp.cat_bitset[1])
            bs_catl2 = bs_catl2.at[best_leaf].set(sp.is_cat_l2[0]) \
                .at[new_leaf].set(sp.is_cat_l2[1])
            return (leaf_hist, bs_gain, bs_feature, bs_bin, bs_dl, bs_lg,
                    bs_lh, bs_lc, bs_bits, bs_catl2)

        bs_arrays = lax.cond(applied, compute_children, lambda bs: bs, bs_arrays)
        (leaf_hist, bs_gain, bs_feature, bs_bin, bs_dl, bs_lg, bs_lh,
         bs_lc, bs_bits, bs_catl2) = bs_arrays

        return GrowerState(
            done=done,
            cegb_charged=cegb_charged,
            num_nodes=st.num_nodes + jnp.where(applied, 1, 0).astype(i32),
            row_leaf=row_leaf,
            leaf_hist=leaf_hist,
            split_feature=split_feature,
            split_bin=split_bin,
            cat_bitset=cat_bitset,
            split_gain=split_gain,
            default_left=default_left,
            left_child=left_child,
            right_child=right_child,
            leaf_parent=leaf_parent,
            leaf_parent_side=leaf_parent_side,
            leaf_depth=leaf_depth,
            node_grad=node_grad,
            node_hess=node_hess,
            node_cnt=node_cnt,
            leaf_grad=leaf_grad,
            leaf_hess=leaf_hess,
            leaf_cnt=leaf_cnt,
            bs_gain=bs_gain,
            bs_feature=bs_feature,
            bs_bin=bs_bin,
            bs_default_left=bs_dl,
            bs_left_grad=bs_lg,
            bs_left_hess=bs_lh,
            bs_left_cnt=bs_lc,
            bs_bitset=bs_bits,
            bs_cat_l2=bs_catl2,
            leaf_out=leaf_out,
            leaf_cmin=leaf_cmin,
            leaf_cmax=leaf_cmax,
            leaf_used=leaf_used,
            leaf_pout=leaf_pout,
            cegb_used=cegb_used,
        )

    st = lax.fori_loop(0, L - 1, body, st)

    leaf_value = st.leaf_out
    tree = TreeArrays(
        split_feature=st.split_feature,
        split_bin=st.split_bin,
        cat_bitset=st.cat_bitset,
        split_gain=st.split_gain,
        default_left=st.default_left,
        left_child=st.left_child,
        right_child=st.right_child,
        leaf_value=leaf_value,
        leaf_weight=st.leaf_hess,
        leaf_count=st.leaf_cnt,
        leaf_parent=st.leaf_parent,
        leaf_depth=st.leaf_depth,
        internal_value=leaf_output(st.node_grad, st.node_hess,
                                   params.split_params()),
        internal_weight=st.node_hess,
        internal_count=st.node_cnt,
        num_leaves=st.num_nodes + 1,
        num_nodes=st.num_nodes,
    )
    if use_lazy:
        return tree, st.row_leaf, st.cegb_charged
    return tree, st.row_leaf
