"""Device TreeSHAP: exact per-feature contributions as one XLA program.

The serving twin of ops/treeshap.py (the numpy reference of Lundberg et
al.'s exact TreeSHAP — the algorithm ``Tree::TreeSHAP`` implements in the
reference's src/io/tree.cpp, driven from ``GBDT::PredictContrib``). The
host walk is O(rows * trees * leaves * depth^2) Python recursion; here
the same arithmetic is reshaped for a batched accelerator:

  * the recursion is unrolled per LEAF: every root->leaf path is
    extracted once at stack time (``build_shap_paths``) into
    depth-bucketed arrays — the internal node ids along the path, the
    direction the path takes, and the per-path-step -> unique-feature
    slot mapping (the reference's duplicate-feature UNWIND merges
    repeated features on a path; the merge STRUCTURE and the merged
    cover fractions are row-independent, so they precompute);
  * per (row, leaf): the row's agreement with each path step comes from
    the SAME packed per-node records the depth-walk predict engine
    gathers (ops/predict._pack_node_records — go_left bit-parity with
    routing), merged per slot into the row-dependent ``one`` fractions;
    EXTEND then runs as a vectorized recurrence over the depth bucket
    and the per-slot UNWIND sums run as one masked scan — O(depth^2)
    like the reference, but over [tree-chunk, rows, depth] lanes with no
    data-dependent control flow;
  * trees run ``tbatch`` at a time under a chunk scan with per-chunk
    class scatter-add, exactly like ``predict_raw_batched``, so the
    compiled program is keyed on (row rung, tree bucket, depth bucket,
    num_class) — the coalescer's zero-recompile serving contract extends
    to the ``pred_contrib`` endpoint unchanged.

Numerics: pweights accumulate in float32 on device (the host reference
is float64); contributions match the numpy path within documented f32
tolerance and sum to the raw score (tests/test_device_serving.py pins
both properties, multiclass and windowed models included).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .packed import gather_bin
from .predict import (StackedTrees, _REC_BIN, _REC_CAT, _REC_COL, _REC_DL,
                      _REC_NAN, _pack_node_records)
from .treeshap import tree_expected_value


class ShapPaths(NamedTuple):
    """Per-leaf decision paths, depth-bucketed and tree-padded.

    ``D`` is the depth bucket, ``L`` the padded leaf width, ``T`` the
    tree bucket. Slot 0 of the unique-path axis is the root placeholder
    (zero fraction 1, one fraction 1 — its contribution weight is
    identically 0); padded steps point at slot 0 and padded slots keep
    (1, 1) fractions, so they are arithmetic no-ops.
    """

    node: jax.Array       # [T, L, D] i32 internal node per step, -1 pad
    went_left: jax.Array  # [T, L, D] bool — direction the PATH takes
    slot: jax.Array       # [T, L, D] i32 unique-feature slot (1-based)
    zfrac: jax.Array      # [T, L, D+1] f32 merged cover fractions, 1.0 pad
    feat: jax.Array       # [T, L, D+1] i32 feature id per slot (0 pad)
    ulen: jax.Array       # [T, L] i32 unique path length (0 = no path)
    ev: jax.Array         # [T] f32 cover-weighted expected value


def build_shap_paths(models: Sequence, max_leaves: int, depth_pad: int,
                     pad_to: Optional[int] = None) -> ShapPaths:
    """Extract every tree's per-leaf paths on the host (numpy, once per
    model window at stack time — the row-independent half of TreeSHAP).

    Cover fractions multiply in float64 and round once to f32, like the
    leaf values the predict stack carries. Padding trees (``pad_to`` >
    len(models)) and constant trees get ``ulen == 0`` everywhere: their
    leaves contribute nothing and only ``ev`` (0 for padding) reaches
    the bias slot."""
    t = len(models)
    t_pad = max(t, pad_to or t)
    L, D = max_leaves, depth_pad
    node = np.full((t_pad, L, D), -1, np.int32)
    went = np.zeros((t_pad, L, D), bool)
    slot = np.zeros((t_pad, L, D), np.int32)
    zfrac = np.ones((t_pad, L, D + 1), np.float64)
    feat = np.zeros((t_pad, L, D + 1), np.int32)
    ulen = np.zeros((t_pad, L), np.int32)
    ev = np.zeros(t_pad, np.float32)
    for ti, m in enumerate(models):
        ev[ti] = tree_expected_value(
            m.left_child, m.right_child, m.leaf_value, m.internal_count,
            m.leaf_count, m.num_nodes)
        if m.num_nodes == 0:
            continue

        def cover(nd: int) -> float:
            if nd < 0:
                return max(float(m.leaf_count[-(nd + 1)]), 1e-12)
            return max(float(m.internal_count[nd]), 1e-12)

        # iterative DFS carrying the (internal node, direction, child)
        # path; leaves fill their row with the first-occurrence slot
        # merge (extend order is immaterial in exact arithmetic — the
        # reference's unwind/re-extend moves merged features to the end,
        # a pure rounding-order difference)
        stack = [(0, [])]
        while stack:
            nd, path = stack.pop()
            if nd < 0:
                leaf = -(nd + 1)
                if len(path) > D:
                    raise ValueError(
                        f"path of {len(path)} steps exceeds the depth "
                        f"bucket {D}")
                slots = {}
                for s, (inode, wl, child) in enumerate(path):
                    node[ti, leaf, s] = inode
                    went[ti, leaf, s] = wl
                    f = int(m.split_feature[inode])
                    if f not in slots:
                        slots[f] = len(slots) + 1
                        feat[ti, leaf, slots[f]] = f
                    j = slots[f]
                    slot[ti, leaf, s] = j
                    zfrac[ti, leaf, j] *= cover(child) / cover(inode)
                ulen[ti, leaf] = len(slots)
                continue
            lc, rc = int(m.left_child[nd]), int(m.right_child[nd])
            stack.append((lc, path + [(nd, True, lc)]))
            stack.append((rc, path + [(nd, False, rc)]))
    return ShapPaths(
        jnp.asarray(node), jnp.asarray(went), jnp.asarray(slot),
        jnp.asarray(zfrac.astype(np.float32)), jnp.asarray(feat),
        jnp.asarray(ulen), jnp.asarray(ev))


def _chunked(arr: jax.Array, chunks: int) -> jax.Array:
    return arr.reshape(chunks, arr.shape[0] // chunks, *arr.shape[1:])


def _path_agreement(binned, rec_b, cat_b, node, went, slot, depth: int,
                    any_cat: bool, packed: bool) -> jax.Array:
    """Per-slot ``one`` fractions [Tb, N, D+1] in {0, 1}: a slot is 1
    when the row agrees with EVERY occurrence of its feature on the
    path (go_left bit-parity with the predict walk: same records, same
    predicate). Padded steps land on slot 0 with forced agreement."""
    nd = jnp.maximum(node, 0)                                  # [Tb, D]
    r = jnp.take_along_axis(rec_b, nd[:, :, None], axis=1)     # [Tb, D, 7]
    n = binned.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)[None, None, :]
    col = r[..., _REC_COL][:, :, None]
    fcol = gather_bin(binned, rows, col, packed)               # [Tb, D, N]
    go_left = (fcol <= r[..., _REC_BIN][:, :, None]) | \
        ((r[..., _REC_DL][:, :, None] != 0)
         & (fcol == r[..., _REC_NAN][:, :, None]))
    if any_cat:
        w = cat_b.shape[-1]
        idx = jnp.broadcast_to(nd[:, :, None], nd.shape + (w,))
        words = jnp.take_along_axis(cat_b, idx, axis=1)        # [Tb, D, W]
        word_id = (fcol // 32).astype(jnp.uint32)
        sel = jnp.zeros_like(fcol, dtype=jnp.uint32)
        for j in range(w):
            sel = jnp.where(word_id == j, words[..., j][:, :, None], sel)
        in_set = ((sel >> (fcol.astype(jnp.uint32) % 32)) & 1) != 0
        go_left = jnp.where(r[..., _REC_CAT][:, :, None] != 0, in_set,
                            go_left)
    agree = (go_left == went[:, :, None]) | (node[:, :, None] < 0)

    # a slot's one is the AND of its occurrences' agreements; padded steps
    # land on slot 0 with forced agreement, so slot 0 stays (1, 1)
    onehot_slot = (slot[:, :, None]
                   == jnp.arange(depth + 1, dtype=jnp.int32)[None, None, :])
    disagree = (~agree).astype(jnp.float32)                    # [Tb, D, N]
    cnt = jnp.einsum("tdn,tdj->tnj", disagree,
                     onehot_slot.astype(jnp.float32))
    return (cnt == 0).astype(jnp.float32)                      # [Tb, N, D+1]


def _extend_unwind(one, zfrac, ulen, depth: int) -> jax.Array:
    """The row-dependent EXTEND/UNWIND recurrences: per-slot UNWIND sums
    [Tb, B, D+1] from the agreement fractions ``one`` [Tb, B, D+1].

    ``B`` is any batch axis — rows in the serving kernel, enumerated
    agreement masks in the deploy-time table builder
    (:func:`build_shap_tables`): the arithmetic depends on the row ONLY
    through ``one``, which is what makes the tables row-independent."""
    tb, b = one.shape[0], one.shape[1]
    zero = zfrac[:, None, :]                                   # [Tb, 1, D+1]

    # -- EXTEND: vectorized pweight recurrence over slots 1..u -------------
    karr = jnp.arange(depth + 1, dtype=jnp.float32)
    p0 = jnp.zeros((tb, b, depth + 1), jnp.float32).at[..., 0].set(1.0)

    def ext_body(j, p):
        jf = j.astype(jnp.float32)
        z = jnp.take(zfrac, j, axis=1)[:, None, None]          # [Tb, 1, 1]
        o = jnp.take(one, j, axis=2)[..., None]                # [Tb, B, 1]
        pshift = jnp.pad(p, ((0, 0), (0, 0), (1, 0)))[..., :-1]
        newp = (z * p * (jf - karr) + o * pshift * karr) / (jf + 1.0)
        return jnp.where((j <= ulen)[:, None, None], newp, p)

    p = lax.fori_loop(1, depth + 1, ext_body, p0)

    # -- UNWIND sums for every slot (masked descent i = u-1 .. 0) ----------
    uf = ulen.astype(jnp.float32)[:, None, None]               # [Tb, 1, 1]
    pu = jnp.take_along_axis(p, ulen[:, None, None], axis=2)   # [Tb, B, 1]
    next_one = jnp.broadcast_to(pu, p.shape)
    total = jnp.zeros_like(p)

    def unwind_body(s, carry):
        total, next_one = carry
        i = ulen - 1 - s                                       # [Tb]
        valid = (i >= 0)[:, None, None]
        iq = jnp.maximum(i, 0)
        i_f = iq.astype(jnp.float32)[:, None, None]
        pi = jnp.take_along_axis(p, iq[:, None, None], axis=2)  # [Tb, B, 1]
        safe_one = jnp.where(one != 0, one, 1.0)
        tmp = next_one * (uf + 1.0) / ((i_f + 1.0) * safe_one)
        frac = zero * (uf - i_f) / (uf + 1.0)
        zero_term = pi / jnp.where(frac != 0, frac, 1.0)
        add = jnp.where(one != 0, tmp, zero_term)
        nn = jnp.where(one != 0, pi - tmp * frac, next_one)
        return (jnp.where(valid, total + add, total),
                jnp.where(valid, nn, next_one))

    total, _ = lax.fori_loop(0, depth, unwind_body, (total, next_one))
    return total


def _leaf_phi(binned, rec_b, cat_b, leaf, depth: int, any_cat: bool,
              packed: bool):
    """SHAP contributions of ONE leaf across a tree chunk: [Tb, N, D+1]
    per-slot weights ``w * (one - zero) * leaf_value`` plus the slot
    feature ids to scatter them with."""
    node, went, slot, zfrac, feat, ulen, lval = leaf
    one = _path_agreement(binned, rec_b, cat_b, node, went, slot, depth,
                          any_cat, packed)
    total = _extend_unwind(one, zfrac, ulen, depth)
    zero = zfrac[:, None, :]                                   # [Tb, 1, D+1]
    # padded slots carry (one, zero) == (1, 1) so their weight is exactly
    # 0; slot 0 likewise — no masking needed beyond the fractions
    return total * (one - zero) * lval[:, None, None], feat


@functools.partial(jax.jit, static_argnames=(
    "num_class", "depth", "tbatch", "any_cat", "packed", "num_features"))
def shap_batched(
    binned: jax.Array,         # [N, F] u8/u16, or [N, ceil(F/2)] u8 packed
    trees: StackedTrees,       # T padded to the tree bucket
    paths: ShapPaths,
    nan_bin_arr: jax.Array,    # [F] i32
    is_cat_arr: jax.Array,     # [F] bool
    num_model_per_iteration: jax.Array,  # scalar i32
    num_class: int = 1,
    depth: int = 8,            # depth bucket (paths are built at it)
    tbatch: int = 16,
    any_cat: bool = False,
    packed: bool = False,
    num_features: int = 0,
    col_of: Optional[jax.Array] = None,
) -> jax.Array:
    """SHAP contributions [num_class, N, F+1] (bias in the last column).

    Row rung, tree bucket, depth bucket and num_class are the only jit
    keys — identical to the predict engine's serving contract, so a
    warmed ``pred_contrib`` ladder serves mixed batch sizes with zero
    steady-state compiles.
    """
    from ..obs.spans import span
    with span("contrib"):
        n = binned.shape[0]
        t_total = trees.num_trees
        chunks = t_total // tbatch
        k_it = jnp.maximum(num_model_per_iteration, 1)
        rec = _pack_node_records(trees, nan_bin_arr, is_cat_arr, col_of)
        class_ids = (jnp.arange(t_total, dtype=jnp.int32) % k_it)
        xs = (_chunked(rec, chunks), _chunked(trees.cat_bitset, chunks),
              _chunked(trees.leaf_value, chunks),
              _chunked(paths.node, chunks), _chunked(paths.went_left, chunks),
              _chunked(paths.slot, chunks), _chunked(paths.zfrac, chunks),
              _chunked(paths.feat, chunks), _chunked(paths.ulen, chunks),
              _chunked(paths.ev, chunks), _chunked(class_ids, chunks))
        fdim = num_features + 1
        farange = jnp.arange(fdim, dtype=jnp.int32)

        def chunk_step(scores, x):
            (rec_b, cat_b, lv_b, node_b, went_b, slot_b, zfrac_b, feat_b,
             ulen_b, ev_b, cid_b) = x
            tb = rec_b.shape[0]

            def leaf_step(phi, leaf_x):
                wgt, feat = _leaf_phi(binned, rec_b, cat_b, leaf_x, depth,
                                      any_cat, packed)
                onehot_f = (feat[:, :, None] == farange[None, None, :]
                            ).astype(jnp.float32)              # [Tb,D+1,Fd]
                return phi + jnp.einsum("tnj,tjf->tnf", wgt, onehot_f), None

            # scan the leaf axis (leaf-major transposes of the path
            # arrays) so peak memory stays one leaf's working set
            leaf_xs = (
                node_b.transpose(1, 0, 2), went_b.transpose(1, 0, 2),
                slot_b.transpose(1, 0, 2), zfrac_b.transpose(1, 0, 2),
                feat_b.transpose(1, 0, 2), ulen_b.T, lv_b.T)
            phi0 = jnp.zeros((tb, n, fdim), jnp.float32)
            phi, _ = lax.scan(leaf_step, phi0, leaf_xs)
            # the tree's expected value lands in the bias slot once
            phi = phi.at[..., -1].add(ev_b[:, None])
            if num_class == 1:
                return scores + phi.sum(axis=0)[None], None
            return scores.at[cid_b].add(phi), None

        scores0 = jnp.zeros((num_class, n, fdim), jnp.float32)
        scores, _ = lax.scan(chunk_step, scores0, xs)
        return scores


class ShapTables(NamedTuple):
    """Precomputed per-leaf UNWIND tables (the deploy-time half of the
    tabled contrib kernel).

    The EXTEND/UNWIND arithmetic of :func:`_extend_unwind` depends on
    the row ONLY through the binary agreement pattern ``one`` over the
    leaf's <= ``mask_bits`` unique slots (slot 0 and padded slots are
    forced to 1). Enumerating all ``2^mask_bits`` patterns at deploy
    time collapses the per-row kernel to agreement bits + one table
    gather + the feature scatter: ``table[t, l, m]`` already carries
    ``unwind_total * (one - zero) * leaf_value`` per slot.
    """

    node: jax.Array       # [T, L, D] i32 internal node per step, -1 pad
    went_left: jax.Array  # [T, L, D] bool — direction the PATH takes
    slot: jax.Array       # [T, L, D] i32 unique-feature slot (1-based)
    feat: jax.Array       # [T, L, D+1] i32 feature id per slot (0 pad)
    table: jax.Array      # [T, L, 2^mask_bits, D+1] f32 final weights
    ev: jax.Array         # [T] f32 cover-weighted expected value

    @property
    def mask_bits(self) -> int:
        return max(int(self.table.shape[2]).bit_length() - 1, 0)


def shap_table_bytes(tree_bucket: int, max_leaves: int, mask_bits: int,
                     depth: int) -> int:
    """f32 footprint of a :class:`ShapTables.table` slab — the budget
    gate (``tpu_shap_table_mb``) checks this BEFORE building."""
    return tree_bucket * max_leaves * (1 << mask_bits) * (depth + 1) * 4


@functools.partial(jax.jit, static_argnames=("mask_bits", "depth"))
def build_shap_tables(paths: ShapPaths, leaf_value: jax.Array,
                      mask_bits: int, depth: int) -> ShapTables:
    """Enumerate every agreement mask through EXTEND/UNWIND once, at
    deploy time (row-independent — runs on model (hot-)swap, never on
    the serving path).

    ``mask_bits`` must cover the longest unique path
    (``paths.ulen.max()``); build peak memory is ~4x the final table, so
    the caller gates on :func:`shap_table_bytes` first. Bit ``j-1`` of a
    mask is slot ``j``'s agreement; slots past a leaf's ``ulen`` are
    forced to agree, matching what :func:`_path_agreement` yields for
    real rows (no step maps to a slot past ``ulen``), so every reachable
    mask row is exact — table-vs-loop parity is bit-level per leaf.
    """
    t, l, d1 = paths.zfrac.shape
    m = 1 << mask_bits
    zfrac = paths.zfrac.reshape(t * l, d1)
    ulen = paths.ulen.reshape(t * l)
    lval = leaf_value.astype(jnp.float32).reshape(t * l)
    j = jnp.arange(d1, dtype=jnp.int32)
    bits = (jnp.arange(m, dtype=jnp.int32)[:, None]
            >> jnp.maximum(j - 1, 0)[None, :]) & 1              # [M, D+1]
    forced = (j[None, None, :] == 0) | (j[None, None, :]
                                        > ulen[:, None, None])  # [TL,1,D+1]
    one = jnp.where(forced, 1.0, bits[None].astype(jnp.float32))
    total = _extend_unwind(one, zfrac, ulen, depth)             # [TL,M,D+1]
    wgt = total * (one - zfrac[:, None, :]) * lval[:, None, None]
    return ShapTables(
        node=paths.node, went_left=paths.went_left, slot=paths.slot,
        feat=paths.feat, table=wgt.reshape(t, l, m, d1), ev=paths.ev)


@functools.partial(jax.jit, static_argnames=(
    "num_class", "depth", "tbatch", "any_cat", "packed", "num_features"))
def shap_batched_tables(
    binned: jax.Array,         # [N, F] u8/u16, or [N, ceil(F/2)] u8 packed
    trees: StackedTrees,       # T padded to the tree bucket
    tables: ShapTables,
    nan_bin_arr: jax.Array,    # [F] i32
    is_cat_arr: jax.Array,     # [F] bool
    num_model_per_iteration: jax.Array,  # scalar i32
    num_class: int = 1,
    depth: int = 8,            # depth bucket (paths are built at it)
    tbatch: int = 16,
    any_cat: bool = False,
    packed: bool = False,
    num_features: int = 0,
    col_of: Optional[jax.Array] = None,
) -> jax.Array:
    """Tabled twin of :func:`shap_batched`: [num_class, N, F+1].

    Per (row, leaf) the EXTEND and UNWIND recurrences are replaced by a
    mask-integer reduction over the agreement bits and ONE gather from
    the precomputed table — same jit keys, same output (bit-identical to
    the loop kernel on every reachable mask, see
    :func:`build_shap_tables`)."""
    from ..obs.spans import span
    with span("contrib"):
        n = binned.shape[0]
        t_total = trees.num_trees
        chunks = t_total // tbatch
        k_it = jnp.maximum(num_model_per_iteration, 1)
        rec = _pack_node_records(trees, nan_bin_arr, is_cat_arr, col_of)
        class_ids = (jnp.arange(t_total, dtype=jnp.int32) % k_it)
        mask_bits = tables.mask_bits
        xs = (_chunked(rec, chunks), _chunked(trees.cat_bitset, chunks),
              _chunked(tables.node, chunks),
              _chunked(tables.went_left, chunks),
              _chunked(tables.slot, chunks), _chunked(tables.feat, chunks),
              _chunked(tables.table, chunks), _chunked(tables.ev, chunks),
              _chunked(class_ids, chunks))
        fdim = num_features + 1
        farange = jnp.arange(fdim, dtype=jnp.int32)
        pw2 = jnp.left_shift(
            jnp.int32(1), jnp.arange(mask_bits, dtype=jnp.int32))

        def chunk_step(scores, x):
            (rec_b, cat_b, node_b, went_b, slot_b, feat_b, tab_b, ev_b,
             cid_b) = x
            tb = rec_b.shape[0]

            def leaf_step(phi, leaf_x):
                node, went, slot, feat, tab = leaf_x    # tab [Tb, M, D+1]
                one = _path_agreement(binned, rec_b, cat_b, node, went,
                                      slot, depth, any_cat, packed)
                bits = (one[..., 1:mask_bits + 1] != 0).astype(jnp.int32)
                midx = jnp.sum(bits * pw2[None, None, :], axis=-1)  # [Tb,N]
                wgt = jnp.take_along_axis(
                    tab, jnp.broadcast_to(midx[:, :, None],
                                          (tb, n, tab.shape[2])), axis=1)
                onehot_f = (feat[:, :, None] == farange[None, None, :]
                            ).astype(jnp.float32)              # [Tb,D+1,Fd]
                return phi + jnp.einsum("tnj,tjf->tnf", wgt, onehot_f), None

            leaf_xs = (
                node_b.transpose(1, 0, 2), went_b.transpose(1, 0, 2),
                slot_b.transpose(1, 0, 2), feat_b.transpose(1, 0, 2),
                tab_b.transpose(1, 0, 2, 3))
            phi0 = jnp.zeros((tb, n, fdim), jnp.float32)
            phi, _ = lax.scan(leaf_step, phi0, leaf_xs)
            phi = phi.at[..., -1].add(ev_b[:, None])
            if num_class == 1:
                return scores + phi.sum(axis=0)[None], None
            return scores.at[cid_b].add(phi), None

        scores0 = jnp.zeros((num_class, n, fdim), jnp.float32)
        scores, _ = lax.scan(chunk_step, scores0, xs)
        return scores
