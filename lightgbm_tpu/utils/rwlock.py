"""Reader-writer lock for the public ``Booster``/``Dataset`` API.

The reference guards every C API entry point with a yamc shared mutex
(``API_BEGIN``/``UNIQUE_LOCK``, src/c_api.cpp:163): many concurrent
predictions, exclusive training/mutation. This repo has no C boundary —
the Python ``Booster`` drives the JAX GBDT directly — so the same
discipline lives here: public methods are decorated ``@read_locked`` or
``@write_locked`` against the instance's ``_api_lock`` (tpulint R007
statically enforces that no public method of a lock-declaring class
skips the decorator, and that mutating methods take the write side).

Semantics:
  * many concurrent readers, one exclusive writer, writer preference
    (a waiting writer blocks new readers, so a predict storm cannot
    starve training);
  * re-entrant per thread: read-inside-read, anything-inside-write, and
    write-inside-write all nest freely (``save_model`` calls
    ``model_to_string``; ``update`` may flush through other write
    methods);
  * read→write upgrade raises ``RuntimeError`` instead of deadlocking —
    a public read method must not call a public write method.

The decorators report every entry/exit to an optional *sanitizer*
(:func:`set_sanitizer`, armed by
``lightgbm_tpu.analysis.guards.api_race_sanitizer``) AFTER acquiring the
lock, so a correctly locked program shows zero overlap while a bypassed
or missing lock shows up as a detected race — the runtime half of R007.
No jax import here: the lock is plain threading and loads anywhere.
"""
from __future__ import annotations

import functools
import os
import sys
import threading
from typing import Optional

#: armed by guards.api_race_sanitizer(); must expose enter()/exit_()
_sanitizer = None


def set_sanitizer(san) -> None:
    global _sanitizer
    _sanitizer = san


def get_sanitizer():
    return _sanitizer


#: armed by guards.lock_witness(); must expose note_acquire(obj, name,
#: side) / note_release(obj) — called AFTER acquiring / BEFORE releasing
#: on outer (depth 0 <-> 1) transitions only, so re-entrant nesting never
#: shows up as a self-order
_witness = None


def set_witness(w) -> None:
    global _witness
    _witness = w


def get_witness():
    return _witness


def _creation_site() -> str:
    """``file.py:line`` of the caller that constructed the lock, used as
    the lock's name in the witness order graph (skips this module)."""
    try:
        f = sys._getframe(1)
    except ValueError:              # pragma: no cover - shallow stack
        return "<unknown>"
    own = __file__
    while f is not None and f.f_code.co_filename == own:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class RWLock:
    """Re-entrant reader-writer lock with writer preference.

    Copies and pickles as a FRESH lock: hold state is meaningless in a
    copy, and a raw ``threading.Condition`` in ``Booster``/``Dataset``
    would otherwise break ``copy.deepcopy`` of trained models.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0                     # active read holds (threads)
        self._writer: Optional[int] = None    # thread id holding write
        self._writer_depth = 0
        self._waiting_writers = 0
        self._local = threading.local()       # per-thread read depth
        self._name = f"RWLock@{_creation_site()}"

    def __deepcopy__(self, memo):
        return type(self)()

    def __reduce__(self):
        return (type(self), ())

    # -- per-thread state ---------------------------------------------------
    def _read_depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _set_read_depth(self, n: int) -> None:
        self._local.depth = n

    # -- read side ----------------------------------------------------------
    def acquire_read(self) -> None:
        me = threading.get_ident()
        outer = False
        with self._cond:
            if self._writer == me or self._read_depth() > 0:
                # nested read under our own write or read: free (already
                # counted in _readers when the outer read registered)
                self._set_read_depth(self._read_depth() + 1)
            else:
                while self._writer is not None or self._waiting_writers:
                    self._cond.wait()
                self._readers += 1
                self._set_read_depth(1)
                outer = True
        # witness note happens OUTSIDE the internal cond so the order
        # graph never sees <internal cond> -> <this lock>
        if outer and _witness is not None:
            _witness.note_acquire(self, self._name, "read")

    def release_read(self) -> None:
        me = threading.get_ident()
        outer = False
        with self._cond:
            depth = self._read_depth()
            if depth <= 0:
                raise RuntimeError("release_read without acquire_read")
            self._set_read_depth(depth - 1)
            if self._writer == me:
                return                        # read nested under our write
            if depth == 1:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()
                outer = True
        if outer and _witness is not None:
            _witness.note_release(self)

    # -- write side ---------------------------------------------------------
    def acquire_write(self) -> None:
        me = threading.get_ident()
        outer = False
        with self._cond:
            if self._writer == me:
                self._writer_depth += 1
            else:
                if self._read_depth() > 0:
                    raise RuntimeError(
                        "read->write lock upgrade: a public read-locked "
                        "method called a write-locked one; make the "
                        "caller write_locked")
                self._waiting_writers += 1
                try:
                    while self._writer is not None or self._readers:
                        self._cond.wait()
                finally:
                    self._waiting_writers -= 1
                self._writer = me
                self._writer_depth = 1
                outer = True
        if outer and _witness is not None:
            _witness.note_acquire(self, self._name, "write")

    def release_write(self) -> None:
        outer = False
        with self._cond:
            if self._writer != threading.get_ident():
                raise RuntimeError("release_write by a non-holder")
            if self._writer_depth == 1 and self._read_depth() > 0:
                # reads nested under this write never bumped _readers;
                # dropping the write first would make the later
                # release_read underflow the count and wedge every
                # future writer — fail loudly instead
                raise RuntimeError(
                    "release_write while reads acquired under the write "
                    "are still held — release order must be LIFO")
            self._writer_depth -= 1
            if self._writer_depth == 0:
                self._writer = None
                self._cond.notify_all()
                outer = True
        if outer and _witness is not None:
            _witness.note_release(self)

    # -- context-manager views ---------------------------------------------
    def read(self) -> "_Side":
        return _Side(self.acquire_read, self.release_read)

    def write(self) -> "_Side":
        return _Side(self.acquire_write, self.release_write)


class _Side:
    def __init__(self, acquire, release):
        self._acquire = acquire
        self._release = release

    def __enter__(self):
        self._acquire()
        return self

    def __exit__(self, *exc):
        self._release()
        return False


class NullLock:
    """Lock-shaped no-op — the seeded R007 bypass mutation for the
    sanitizer tests (swap a Booster's ``_api_lock`` for this and the
    detector must light up). Never used in shipped code paths."""

    def read(self):
        return _Side(lambda: None, lambda: None)

    def write(self):
        return _Side(lambda: None, lambda: None)


class Mutex:
    """Re-entrant mutex (``with mutex:``) that deep-copies/pickles as a
    fresh lock — for internal serialization members (``GBDT._trees_mu``)
    living on objects users may ``copy.deepcopy``."""

    def __init__(self):
        self._lock = threading.RLock()
        self._local = threading.local()       # per-thread hold depth
        self._name = f"Mutex@{_creation_site()}"

    def __enter__(self):
        self._lock.acquire()
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        if depth == 0 and _witness is not None:
            _witness.note_acquire(self, self._name, "excl")
        return self

    def __exit__(self, *exc):
        depth = getattr(self._local, "depth", 1)
        self._local.depth = depth - 1
        if depth == 1 and _witness is not None:
            _witness.note_release(self)
        self._lock.release()
        return False

    def __deepcopy__(self, memo):
        return type(self)()

    def __reduce__(self):
        return (type(self), ())


def _locked(kind: str, method):
    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        lock = self._api_lock
        side = lock.read() if kind == "read" else lock.write()
        with side:
            san = _sanitizer
            if san is None:
                return method(self, *args, **kwargs)
            token = san.enter(self, kind, method.__name__)
            try:
                return method(self, *args, **kwargs)
            finally:
                san.exit_(token)
    wrapper.__lock_kind__ = kind
    return wrapper


def read_locked(method):
    """Shared-lock a public API method (concurrent with other readers)."""
    return _locked("read", method)


def write_locked(method):
    """Exclusively lock a public API method that mutates shared state."""
    return _locked("write", method)
