"""Logging for lightgbm_tpu.

TPU-native re-design of the reference's ``Log`` singleton
(reference: include/LightGBM/utils/log.h:78-180): levels Fatal/Warning/Info/Debug,
redirectable callback (the reference's ``Log::ResetCallBack``; Python side routes
through ``register_logger`` in python-package/lightgbm/basic.py:232-301).
"""
from __future__ import annotations

import logging
import sys
from typing import Any, Callable, Optional

_logger: Any = logging.getLogger("lightgbm_tpu")
_logger.addHandler(logging.StreamHandler(sys.stdout))
_logger.setLevel(logging.INFO)

_info_method_name = "info"
_warning_method_name = "warning"

# verbosity: <0 = fatal only, 0 = warning+, 1 = info+, >1 = debug
_verbosity = 1


def register_logger(
    logger: Any,
    info_method_name: str = "info",
    warning_method_name: str = "warning",
) -> None:
    """Redirect lightgbm_tpu's logging to a custom logger object."""
    global _logger, _info_method_name, _warning_method_name
    if not callable(getattr(logger, info_method_name, None)) or not callable(
        getattr(logger, warning_method_name, None)
    ):
        raise TypeError("logger must provide callable info/warning methods")
    _logger = logger
    _info_method_name = info_method_name
    _warning_method_name = warning_method_name


def set_verbosity(verbosity: int) -> None:
    global _verbosity
    _verbosity = verbosity


def get_verbosity() -> int:
    return _verbosity


def debug(msg: str) -> None:
    if _verbosity >= 2:
        getattr(_logger, _info_method_name)(f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    if _verbosity >= 1:
        getattr(_logger, _info_method_name)(f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _verbosity >= 0:
        getattr(_logger, _warning_method_name)(f"[LightGBM-TPU] [Warning] {msg}")


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu (mirrors the reference's LightGBMError)."""


def fatal(msg: str) -> None:
    raise LightGBMError(msg)
