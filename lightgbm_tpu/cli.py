"""Config-file driven command line (the reference's ``lightgbm`` binary).

Mirrors the reference CLI (reference: src/main.cpp:13, Application::Run
src/application/application.cpp:168-285 — ``lightgbm config=train.conf``
plus key=value overrides; tasks train/predict/convert_model/refit from
include/LightGBM/config.h:34).

Usage:
    python -m lightgbm_tpu config=train.conf [key=value ...]
    python -m lightgbm_tpu task=predict data=test.csv input_model=model.txt
"""
from __future__ import annotations

import sys
from typing import Dict

import numpy as np


def parse_config_file(path: str) -> Dict[str, str]:
    """key=value lines, '#' comments (reference: Application::LoadParameters,
    application.cpp:50)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, _, value = line.partition("=")
            out[key.strip()] = value.strip()
    return out


def _load_dataset(params, data_path: str):
    import lightgbm_tpu as lgb
    from .io.loader import load_query_file, load_text_file, load_weight_file

    X, label, weight, group, names = load_text_file(
        data_path,
        has_header=str(params.get("header", "false")).lower()
        in ("true", "1"),
        label_column=params.get("label_column", "0"),
        weight_column=params.get("weight_column", ""),
        group_column=params.get("group_column", ""),
        parser_config_file=str(params.get("parser_config_file", "") or ""),
        ignore_column=params.get("ignore_column", ""),
        # memory-bounded two-pass loading (reference: two_round config,
        # dataset_loader.cpp:266) — X comes back as a TextFileSequence and
        # feeds the streaming construction path
        two_round=str(params.get("two_round", "false")).lower()
        in ("true", "1"),
    )
    if weight is None:
        weight = load_weight_file(data_path)
    if group is None:
        group = load_query_file(data_path)
    return lgb.Dataset(X, label=label, weight=weight, group=group,
                       feature_name=names or "auto",
                       free_raw_data=False), X


def run(argv=None) -> int:
    import lightgbm_tpu as lgb

    argv = list(sys.argv[1:] if argv is None else argv)
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            print(f"ignoring argument without '=': {arg}", file=sys.stderr)
            continue
        key, _, value = arg.partition("=")
        if key == "config":
            file_params = parse_config_file(value)
            # command line overrides the config file (application.cpp:56-66)
            file_params.update(params)
            params = file_params
        else:
            params[key] = value

    task = params.pop("task", "train")
    if task == "train":
        data = params.pop("data", None)
        if not data:
            print("task=train needs data=<file>", file=sys.stderr)
            return 1
        valid = params.pop("valid", params.pop("valid_data", ""))
        num_round = int(params.pop("num_iterations",
                                   params.pop("num_boost_round", 100)))
        output_model = params.get("output_model", "LightGBM_model.txt")
        ds, _ = _load_dataset(params, data)
        valid_sets = []
        valid_names = []
        for i, v in enumerate(p for p in valid.split(",") if p):
            vds, _ = _load_dataset(params, v)
            vds.reference = ds
            valid_sets.append(vds)
            valid_names.append(f"valid_{i}")
        bst = lgb.train(params, ds, num_round,
                        valid_sets=valid_sets or None,
                        valid_names=valid_names or None,
                        callbacks=[lgb.log_evaluation(1)] if valid_sets
                        else None)
        bst.save_model(output_model)
        print(f"model saved to {output_model}")
        return 0

    if task == "predict":
        data = params.pop("data", None)
        input_model = params.pop("input_model", None)
        if not data or not input_model:
            print("task=predict needs data=<file> input_model=<model>",
                  file=sys.stderr)
            return 1
        output_result = params.pop("output_result", "LightGBM_predict_result.txt")
        from .io.loader import load_text_file
        X, _, _, _, _ = load_text_file(
            data,
            has_header=str(params.get("header", "false")).lower()
            in ("true", "1"),
            label_column=params.get("label_column", "0"))
        bst = lgb.Booster(model_file=input_model)
        pred = bst.predict(
            X,
            raw_score=str(params.get("predict_raw_score", "false")).lower()
            in ("true", "1"),
            pred_leaf=str(params.get("predict_leaf_index", "false")).lower()
            in ("true", "1"))
        np.savetxt(output_result, np.asarray(pred), fmt="%.9g")
        print(f"predictions saved to {output_result}")
        return 0

    if task == "save_binary":
        # (reference: kSaveBinary, application.cpp — bins the train data and
        # writes <data>.bin for fast reloads)
        data = params.pop("data", None)
        if not data:
            print("task=save_binary needs data=<file>", file=sys.stderr)
            return 1
        ds, _ = _load_dataset(params, data)
        ds._update_params(params)
        ds.construct()
        out = params.pop("output_model", data + ".bin")
        ds._inner.save_binary(out)
        print(f"binary dataset saved to {out}")
        return 0

    if task == "refit":
        # (reference: KRefitTree, application.cpp:268 — re-learn leaf values
        # on new data with refit_decay_rate, tree structure unchanged)
        data = params.pop("data", None)
        input_model = params.pop("input_model", None)
        if not data or not input_model:
            print("task=refit needs data=<file> input_model=<model>",
                  file=sys.stderr)
            return 1
        from .io.loader import load_text_file
        X, label, _, _, _ = load_text_file(
            data,
            has_header=str(params.get("header", "false")).lower()
            in ("true", "1"),
            label_column=params.get("label_column", "0"))
        bst = lgb.Booster(model_file=input_model)
        decay = float(params.get("refit_decay_rate", 0.9))
        bst = bst.refit(X, label, decay_rate=decay)
        output_model = params.get("output_model", "LightGBM_model.txt")
        bst.save_model(output_model)
        print(f"refitted model saved to {output_model}")
        return 0

    if task == "convert_model":
        # (reference: kConvertModel, application.cpp:215 -> Tree::ToIfElse)
        input_model = params.pop("input_model", None)
        if not input_model:
            print("task=convert_model needs input_model=<model>",
                  file=sys.stderr)
            return 1
        lang = params.get("convert_model_language", "cpp")
        if lang not in ("cpp", "c++", ""):
            print(f"convert_model_language={lang} is not supported (cpp "
                  "only)", file=sys.stderr)
            return 1
        out = params.get("convert_model", "gbdt_prediction.cpp")
        from .model_io import LoadedGBDT
        with open(input_model) as fh:
            code = LoadedGBDT(fh.read()).to_if_else()
        with open(out, "w") as fh:
            fh.write(code)
        print(f"if-else model written to {out}")
        return 0

    print(f"unknown task: {task}", file=sys.stderr)
    return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(run())
