"""Evaluation metrics.

Mirror of the reference's metric layer (reference: include/LightGBM/metric.h,
factory Metric::CreateMetric src/metric/metric.cpp, families in
src/metric/{regression,binary,multiclass,rank,map,xentropy}_metric.hpp).

Like the reference — where AUC/NDCG stay on CPU even in CUDA mode
(src/metric/metric.cpp:39-56) — metrics are computed host-side in numpy from the
device score vector: they run once per ``metric_freq`` iterations and are never
on the training hot path.

Each metric exposes ``eval(raw_score, convert) -> float`` where ``convert`` is
the objective's ConvertOutput (objective_function.h:81) and ``higher_better``.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

_EPS = 1e-15


class Metric:
    name = "metric"
    higher_better = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weight = (
            np.asarray(metadata.weight, dtype=np.float64)
            if metadata.weight is not None else None
        )
        self.sum_weight = (
            float(self.weight.sum()) if self.weight is not None else float(num_data)
        )
        self.metadata = metadata

    def _avg(self, per_row: np.ndarray) -> float:
        if self.weight is not None:
            return float((per_row * self.weight).sum() / max(self.sum_weight, _EPS))
        return float(per_row.mean())

    def eval(self, raw_score: np.ndarray, convert: Optional[Callable]) -> float:
        raise NotImplementedError


# -- regression (reference: src/metric/regression_metric.hpp) ---------------
class _PointwiseRegression(Metric):
    def point_loss(self, pred, label):
        raise NotImplementedError

    def eval(self, raw_score, convert):
        pred = np.asarray(convert(raw_score)) if convert else np.asarray(raw_score)
        return self._avg(self.point_loss(pred.reshape(-1), self.label))


class L2Metric(_PointwiseRegression):
    name = "l2"

    def point_loss(self, pred, label):
        return (pred - label) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def eval(self, raw_score, convert):
        return float(np.sqrt(super().eval(raw_score, convert)))


class L1Metric(_PointwiseRegression):
    name = "l1"

    def point_loss(self, pred, label):
        return np.abs(pred - label)


class QuantileMetric(_PointwiseRegression):
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.get("alpha", 0.9))

    def point_loss(self, pred, label):
        d = label - pred
        return np.where(d >= 0, self.alpha * d, (self.alpha - 1.0) * d)


class HuberMetric(_PointwiseRegression):
    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.get("alpha", 0.9))

    def point_loss(self, pred, label):
        d = np.abs(pred - label)
        a = self.alpha
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegression):
    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.get("fair_c", 1.0))

    def point_loss(self, pred, label):
        x = np.abs(pred - label)
        c = self.c
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegression):
    name = "poisson"

    def point_loss(self, pred, label):
        eps = 1e-10
        return pred - label * np.log(np.maximum(pred, eps))


class MAPEMetric(_PointwiseRegression):
    name = "mape"

    def point_loss(self, pred, label):
        return np.abs((label - pred) / np.maximum(1.0, np.abs(label)))


class GammaMetric(_PointwiseRegression):
    name = "gamma"

    def point_loss(self, pred, label):
        eps = 1e-10
        psafe = np.maximum(pred, eps)
        return label / psafe + np.log(psafe)


class GammaDevianceMetric(_PointwiseRegression):
    name = "gamma_deviance"

    def point_loss(self, pred, label):
        eps = 1e-10
        f = label / np.maximum(pred, eps)
        return 2.0 * (f - np.log(np.maximum(f, eps)) - 1.0)


class TweedieMetric(_PointwiseRegression):
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.get("tweedie_variance_power", 1.5))

    def point_loss(self, pred, label):
        eps = 1e-10
        p = np.maximum(pred, eps)
        rho = self.rho
        a = label * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return -a + b


# -- binary (reference: src/metric/binary_metric.hpp) -----------------------
class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, raw_score, convert):
        p = np.asarray(convert(raw_score)).reshape(-1) if convert \
            else 1.0 / (1.0 + np.exp(-np.asarray(raw_score).reshape(-1)))
        p = np.clip(p, _EPS, 1.0 - _EPS)
        y = self.label
        return self._avg(-(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, raw_score, convert):
        p = np.asarray(convert(raw_score)).reshape(-1) if convert \
            else np.asarray(raw_score).reshape(-1)
        thresh = 0.5 if convert else 0.0
        pred = (p > thresh).astype(np.float64)
        return self._avg((pred != (self.label > 0)).astype(np.float64))


def _auc(label01: np.ndarray, score: np.ndarray, weight) -> float:
    """Weighted ROC-AUC via rank statistic (reference: binary_metric.hpp AUCMetric)."""
    order = np.argsort(score, kind="mergesort")
    s = score[order]
    y = label01[order]
    w = weight[order] if weight is not None else np.ones_like(s)
    # tie-aware trapezoid accumulation
    pos_w = w * (y > 0)
    neg_w = w * (y <= 0)
    total_pos = pos_w.sum()
    total_neg = neg_w.sum()
    if total_pos == 0 or total_neg == 0:
        return 1.0
    # group by unique score
    _, starts = np.unique(s, return_index=True)
    pos_per = np.add.reduceat(pos_w, starts)
    neg_per = np.add.reduceat(neg_w, starts)
    cum_neg_before = np.concatenate([[0.0], np.cumsum(neg_per)[:-1]])
    auc = float((pos_per * (cum_neg_before + 0.5 * neg_per)).sum())
    return auc / float(total_pos * total_neg)


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, raw_score, convert):
        return _auc(
            (self.label > 0).astype(np.float64),
            np.asarray(raw_score).reshape(-1).astype(np.float64),
            self.weight,
        )


class AveragePrecisionMetric(Metric):
    """(reference: binary_metric.hpp AveragePrecisionMetric)"""
    name = "average_precision"
    higher_better = True

    def eval(self, raw_score, convert):
        score = np.asarray(raw_score).reshape(-1).astype(np.float64)
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-score, kind="mergesort")
        y, w = y[order], w[order]
        tp = np.cumsum(w * y)
        fp = np.cumsum(w * (1 - y))
        total_pos = tp[-1]
        if total_pos == 0:
            return 1.0
        precision = tp / np.maximum(tp + fp, _EPS)
        recall_delta = np.diff(np.concatenate([[0.0], tp])) / total_pos
        return float((precision * recall_delta).sum())


# -- multiclass (reference: src/metric/multiclass_metric.hpp) ---------------
class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.get("num_class", 1))

    def eval(self, raw_score, convert):
        # raw_score: [K, N]
        raw = np.asarray(raw_score)
        if convert:
            p = np.asarray(convert(raw.T))                 # [N, K] probs
        else:
            e = np.exp(raw - raw.max(axis=0, keepdims=True))
            p = (e / e.sum(axis=0, keepdims=True)).T
        idx = self.label.astype(np.int64)
        pt = np.clip(p[np.arange(len(idx)), idx], _EPS, None)
        return self._avg(-np.log(pt))


class MultiErrorMetric(Metric):
    name = "multi_error"

    def __init__(self, config):
        super().__init__(config)
        self.top_k = int(config.get("multi_error_top_k", 1))

    def eval(self, raw_score, convert):
        raw = np.asarray(raw_score)                        # [K, N]
        idx = self.label.astype(np.int64)
        if self.top_k <= 1:
            err = (raw.argmax(axis=0) != idx).astype(np.float64)
        else:
            true_score = raw[idx, np.arange(raw.shape[1])]
            rank = (raw > true_score[None, :]).sum(axis=0)
            err = (rank >= self.top_k).astype(np.float64)
        return self._avg(err)


class AucMuMetric(Metric):
    """Multiclass AUC-mu (reference: multiclass_metric.hpp auc_mu branch):
    average pairwise-class AUC of the score difference direction."""
    name = "auc_mu"
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.get("num_class", 1))
        k = self.num_class
        w = config.get("auc_mu_weights")
        if w is not None:
            if isinstance(w, str):
                # config files / CLI deliver the matrix as a comma string
                w = [float(t) for t in w.split(",") if t.strip()]
            arr = np.asarray(list(w), np.float64).reshape(-1)
            if arr.size != k * k:
                raise ValueError(
                    f"auc_mu_weights must have num_class^2 = {k * k} "
                    f"entries, got {arr.size}")
            self.W = arr.reshape(k, k).copy()
        else:
            self.W = np.ones((k, k), np.float64)
        # the diagonal is always zero (reference: Config::GetAucMuWeights,
        # src/io/config.cpp:224)
        np.fill_diagonal(self.W, 0.0)

    def eval(self, raw_score, convert):
        raw = np.asarray(raw_score)                        # [K, N]
        idx = self.label.astype(np.int64)
        k = self.num_class
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                sel = (idx == a) | (idx == b)
                if sel.sum() == 0 or (idx[sel] == a).all() or (idx[sel] == b).all():
                    continue
                # partition-weighted separating direction (reference:
                # multiclass_metric.hpp:250-265; Kleiman & Page AUC-mu):
                # v = W[a] - W[b], decision value (v[a]-v[b]) * (v . scores)
                v = self.W[a] - self.W[b]
                t1 = v[a] - v[b]
                s = t1 * (v @ raw[:, sel])
                y = (idx[sel] == a).astype(np.float64)
                w = self.weight[sel] if self.weight is not None else None
                aucs.append(_auc(y, s, w))
        return float(np.mean(aucs)) if aucs else 1.0


# -- ranking (reference: src/metric/rank_metric.hpp NDCG via dcg_calculator.cpp,
#    src/metric/map_metric.hpp) ----------------------------------------------
class NDCGMetric(Metric):
    name = "ndcg"
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        ks = config.get("eval_at", None) or [1, 2, 3, 4, 5]
        self.eval_at = [int(k) for k in ks]
        self.label_gain = config.get("label_gain", None)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("ndcg metric requires query groups")
        self.qb = np.asarray(metadata.query_boundaries)
        max_label = int(self.label.max()) if len(self.label) else 0
        if self.label_gain is None:
            self.gains = (2.0 ** np.arange(max(max_label + 1, 2))) - 1.0
        else:
            self.gains = np.asarray(self.label_gain, dtype=np.float64)

    def eval(self, raw_score, convert):
        return self.eval_all(raw_score)[0]

    def eval_all(self, raw_score) -> List[float]:
        score = np.asarray(raw_score).reshape(-1).astype(np.float64)
        lbl = self.label.astype(np.int64)
        out = []
        for k in self.eval_at:
            vals = []
            for i in range(len(self.qb) - 1):
                s, e = self.qb[i], self.qb[i + 1]
                g = self.gains[lbl[s:e]]
                kk = min(k, e - s)
                order = np.argsort(-score[s:e], kind="mergesort")
                disc = 1.0 / np.log2(np.arange(kk) + 2.0)
                dcg = float((g[order[:kk]] * disc).sum())
                ideal = float((np.sort(g)[::-1][:kk] * disc).sum())
                vals.append(dcg / ideal if ideal > 0 else 1.0)
            out.append(float(np.mean(vals)) if vals else 1.0)
        return out


class MapMetric(Metric):
    name = "map"
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        ks = config.get("eval_at", None) or [1, 2, 3, 4, 5]
        self.eval_at = [int(k) for k in ks]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("map metric requires query groups")
        self.qb = np.asarray(metadata.query_boundaries)

    def eval(self, raw_score, convert):
        return self.eval_all(raw_score)[0]

    def eval_all(self, raw_score) -> List[float]:
        score = np.asarray(raw_score).reshape(-1).astype(np.float64)
        rel = (self.label > 0).astype(np.float64)
        out = []
        for k in self.eval_at:
            vals = []
            for i in range(len(self.qb) - 1):
                s, e = self.qb[i], self.qb[i + 1]
                order = np.argsort(-score[s:e], kind="mergesort")
                r = rel[s:e][order][:k]
                if r.sum() == 0:
                    vals.append(0.0)
                    continue
                prec = np.cumsum(r) / (np.arange(len(r)) + 1.0)
                vals.append(float((prec * r).sum() / min(rel[s:e].sum(), k)))
            out.append(float(np.mean(vals)) if vals else 1.0)
        return out


# -- cross-entropy (reference: src/metric/xentropy_metric.hpp) --------------
class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, raw_score, convert):
        p = np.asarray(convert(raw_score)).reshape(-1) if convert \
            else 1.0 / (1.0 + np.exp(-np.asarray(raw_score).reshape(-1)))
        p = np.clip(p, _EPS, 1.0 - _EPS)
        y = self.label
        return self._avg(-(y * np.log(p) + (1 - y) * np.log(1 - p)))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, raw_score, convert):
        raw = np.asarray(raw_score).reshape(-1)
        hhat = np.log1p(np.exp(raw))
        y = self.label
        return self._avg(hhat - y * np.log(np.maximum(1.0 - np.exp(-hhat), _EPS)))


class KLDivMetric(Metric):
    """(reference: xentropy_metric.hpp KullbackLeiblerDivergence)"""
    name = "kldiv"

    def eval(self, raw_score, convert):
        p = np.asarray(convert(raw_score)).reshape(-1) if convert \
            else 1.0 / (1.0 + np.exp(-np.asarray(raw_score).reshape(-1)))
        p = np.clip(p, _EPS, 1.0 - _EPS)
        y = np.clip(self.label, 0.0, 1.0)
        ent = np.where(
            (y > 0) & (y < 1),
            y * np.log(np.maximum(y, _EPS)) + (1 - y) * np.log(np.maximum(1 - y, _EPS)),
            0.0,
        )
        ce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return self._avg(ent + ce)


_METRICS = {
    "l2": L2Metric, "mse": L2Metric, "mean_squared_error": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric, "l2_root": RMSEMetric,
    "l1": L1Metric, "mae": L1Metric, "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric, "rank_xendcg": NDCGMetric,
    "xendcg": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric, "kullback_leibler": KLDivMetric,
}


def create_metric(name: str, config) -> Optional[Metric]:
    key = str(name).lower()
    if key in ("", "none", "null", "na", "custom"):
        return None
    if key not in _METRICS:
        raise ValueError(f"Unknown metric: {name}")
    return _METRICS[key](config)


def create_metrics(names: Sequence[str], config) -> List[Metric]:
    out = []
    seen = set()
    for n in names:
        m = create_metric(n, config)
        if m is not None and m.name not in seen:
            out.append(m)
            seen.add(m.name)
    return out
