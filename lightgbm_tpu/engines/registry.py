"""Engine registry: the ONE owner of histogram-engine selection.

Through round 11 the engine knob space — {fused, pallas, xla-einsum} x
batched-M depth x block size x {lane, sublane} layout x learner mode —
was resolved by five ``_pick_*`` helpers spread through
``boosting/gbdt.py``, plus env overrides (``LGBM_TPU_FUSED_BS``,
``LGBM_TPU_HIST_MBATCH``) and per-op defaults. This module collapses
all of it behind one table (:data:`ENTRIES`) and one callsite
(:func:`resolve`), the way the reference resolves col-wise vs row-wise
histogram dispatch from ONE decision point at ``InitTrain``
(``dataset.h:727``) — and, like the reference, the decision can be
*measured* instead of guessed: the startup microbench autotuner
(``engines/autotune.py``, ``tpu_autotune``) times the eligible entries
on a slice of the real binned data and records the winner per
shape-class.

Resolve order, per knob (the contract every test in
tests/test_registry.py pins)::

    user explicit > env override > autotune cache > heuristic default

Registry entries carry their HLO-contract id: ``scripts/
verify_contracts.py`` enumerates contracts per entry (the entry id is
in the contract filename), so a new engine cannot land without either
a checked-in contract or a justified ``contract_exempt`` (TPU-only
Mosaic kernels, which the CPU contract harness cannot lower — their
parity is pinned by the cross-engine bit-identity tests instead).

tpulint R004 enforces the ownership: ``GrowerParams(hist_*=...)`` or a
direct engine-callable choice outside this package is a finding; the
one sanctioned escape hatch is ``ops/histogram.py::_resolve_impl``
(allowlist-anchored), the trace-time dispatch that keeps the measured
per-width heuristic when the registry hands ``"auto"`` through
(``tpu_autotune=off`` / no cache).

Module level is jax-free; functions that need a backend import jax
lazily.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from ..utils import log

#: platforms with a real Mosaic/TPU backend (matches ops/fused_split.py
#: fused_available and ops/pallas_histogram.py pallas_available)
TPU_PLATFORMS = ("tpu", "axon")

#: batched-M depths the autotuner sweeps (ops/fused_split.py hist_flush:
#: M = 8K MXU rows, K <= 16). The default (8) leads so a tie resolves to
#: today's behavior, not to an arbitrary cell.
MBATCH_CANDIDATES = (8, 16, 1)


class DatasetShape(NamedTuple):
    """The static dataset facts engine selection keys on."""
    rows: int
    features: int
    num_bins: int
    mode: str = "serial"          # serial | data | voting | feature
    quant: bool = False           # use_quantized_grad (int8 channels)
    pack4: bool = False           # tpu_bin_pack4 (nibble-packed bins)


class EngineEntry(NamedTuple):
    """One histogram engine the registry can select.

    ``contracts`` names the ``analysis/contracts/<mode>.json`` files
    that pin this entry's steady-state step program (at least one file
    name must contain the entry id); ``contract_exempt`` is the
    mandatory justification when no CPU contract can exist (TPU-only
    Mosaic kernels). ``sweepable`` entries are timed standalone by the
    autotuner; the fused kernel is selected structurally (it replaces
    the partition+histogram streams and its binding constraint is the
    scoped-VMEM validator, :func:`clamp_fused_block`) but INHERITS the
    winning layout/mbatch — those knobs thread into its ``hist_flush``.
    """
    id: str
    impl: str                     # hist_impl fed to ops/histogram dispatch
    layout: str                   # lane | sublane
    fused: bool
    description: str
    contracts: Tuple[str, ...] = ()
    contract_exempt: str = ""
    max_bins: int = 256           # eligibility bound on the bin width
    requires_tpu: bool = False
    sweepable: bool = True
    #: mesh shapes (spmd_check keys: "1", "8", "4x2") every contract of
    #: this entry must carry a verified `memory` block for — the
    #: per-entry slice of the pod flight check (analysis/spmd_check.py);
    #: hlo_check.registry_contract_findings enumerates the coverage
    meshes: Tuple[str, ...] = ("1",)


ENTRIES: Tuple[EngineEntry, ...] = (
    EngineEntry(
        "xla_lane", "xla", "lane", False,
        "chunked one-hot einsum (fp32-HIGHEST / int8 -> s32), lane "
        "layout — runs on every backend",
        contracts=("xla_lane",)),
    EngineEntry(
        "pallas_lane", "pallas", "lane", False,
        "standalone Mosaic one-hot kernel, bins along lanes "
        "(ops/pallas_histogram.py)",
        contract_exempt="Mosaic kernels cannot lower on the CPU "
                        "contract harness; cross-engine bit-identity "
                        "is pinned by tests/test_ops.py and "
                        "tests/test_hist_mbatch.py",
        requires_tpu=True),
    EngineEntry(
        "pallas_sublane", "pallas", "sublane", False,
        "standalone Mosaic kernel, bins along sublanes (B <= 64: the "
        "one-hot compare fills the register tile)",
        contract_exempt="Mosaic kernels cannot lower on the CPU "
                        "contract harness; layout bit-identity is "
                        "pinned by tests/test_pack4_train.py",
        max_bins=64, requires_tpu=True),
    EngineEntry(
        "fused_lane", "auto", "lane", True,
        "fused partition+histogram Mosaic kernel (ops/fused_split.py), "
        "lane-layout hist_flush",
        contract_exempt="Mosaic kernels cannot lower on the CPU "
                        "contract harness; parity is pinned by "
                        "tests/test_fused.py leaf-count identity",
        requires_tpu=True, sweepable=False),
    EngineEntry(
        "fused_sublane", "auto", "sublane", True,
        "fused Mosaic kernel with the bins-on-sublanes hist_flush "
        "(B <= 64)",
        contract_exempt="Mosaic kernels cannot lower on the CPU "
                        "contract harness; layout bit-identity is "
                        "pinned by tests/test_pack4_train.py",
        max_bins=64, requires_tpu=True, sweepable=False),
)


#: serving-only inference engines (ROADMAP 4): same registry contract
#: as the histogram entries — an HLO contract id in the filename or a
#: justified exemption (tpulint R004 enforces it), selected through the
#: same resolve order by :func:`resolve_serving_engine`.
SERVING_ENTRIES: Tuple[EngineEntry, ...] = (
    EngineEntry(
        "serve_walk", "walk", "lane", False,
        "depth-batched pointer walk (ops/predict.py "
        "predict_raw_batched): one packed node-record gather over "
        "[Tb, L-1] per depth step",
        contracts=("serve_walk",), sweepable=True),
    EngineEntry(
        "serve_level", "level", "lane", False,
        "level-order heap relayout (predict_raw_level): depth step d "
        "reads the contiguous [Tb, 2^d] per-level slab; buckets deeper "
        "than tpu_level_depth_cap keep the walk",
        contracts=("serve_level",), sweepable=True),
    EngineEntry(
        "serve_qleaf", "qleaf", "lane", False,
        "quantized leaf slab (tpu_leaf_quant=int8|f16) over the "
        "resolved walk/level router: narrow leaf gather + per-tree "
        "dequant scale, with a recorded max-score-error bound",
        contract_exempt="shares the serve_walk/serve_level step "
                        "program shape (only the leaf-slab dtype "
                        "narrows); score deviation is pinned by the "
                        "RECORDED bound and "
                        "tests/test_level_engine.py",
        sweepable=True),
)

#: tpu_predict_engine spellings the serving resolver accepts
SERVING_ENGINE_VALUES = ("batched", "walk", "level", "scan", "auto")


class Candidate(NamedTuple):
    """One autotune sweep cell: an engine entry at a batched-M depth."""
    entry: EngineEntry
    mbatch: int

    @property
    def key(self) -> str:
        return f"{self.entry.id}-k{self.mbatch}"


class Resolution(NamedTuple):
    """The registry's answer: every engine knob, with provenance.

    ``sources`` maps knob -> one of ``user`` / ``env`` / ``autotune`` /
    ``default`` so logs and tests can see WHICH rung of the resolve
    order produced each value.
    """
    entry_id: str
    fused_block: int
    hist_impl: str
    hist_mbatch: int
    hist_layout: str
    hist_overlap: int
    step_buckets: bool
    sources: Dict[str, str]
    shape_class: Optional[str] = None
    autotuned: bool = False
    # the raw autotune winner this resolution applied (None = none):
    # reset_parameter re-resolves against THIS, not a cache re-read —
    # the in-run engine choice must survive an unwritable cache and
    # must never flip because the file changed under a live run
    decision: Optional[Dict[str, Any]] = None


# ---------------------------------------------------------------------------
# shape classes
# ---------------------------------------------------------------------------
def _rung(x: int) -> int:
    """Power-of-two rung (>= 1) — shape classes bucket like the step
    ladder does, so near-identical datasets share one decision."""
    return 1 << max(0, (max(1, int(x)) - 1).bit_length())


def shape_class(shape: DatasetShape) -> str:
    """Canonical shape-class key: learner mode + row/feature rungs +
    exact bin width + dtype/layout markers. The autotune cache and
    BENCH_SHAPES["autotune"] both key on it."""
    tags = ""
    if shape.quant:
        tags += "-quant"
    if shape.pack4:
        tags += "-pack4"
    return (f"{shape.mode}-r{_rung(shape.rows)}-f{_rung(shape.features)}"
            f"-b{int(shape.num_bins)}{tags}")


def current_platform() -> str:
    """The active jax backend platform ("cpu" when no backend exists —
    the jax-free CLI paths pass an explicit platform instead)."""
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover - backend-less host
        return "cpu"


def _entry_available(entry: EngineEntry, platform: str) -> bool:
    if entry.requires_tpu and platform not in TPU_PLATFORMS:
        return False
    if entry.fused and platform in TPU_PLATFORMS:
        from ..ops.fused_split import fused_available
        return fused_available()
    return True


def eligible_entries(shape: DatasetShape, platform: str
                     ) -> List[EngineEntry]:
    """Entries that can serve ``shape`` on ``platform``."""
    return [e for e in ENTRIES
            if shape.num_bins <= e.max_bins
            and _entry_available(e, platform)]


def sweep_candidates(shape: DatasetShape, platform: str
                     ) -> List[Candidate]:
    """The autotune sweep grid: sweepable eligible entries x mbatch."""
    out: List[Candidate] = []
    for entry in eligible_entries(shape, platform):
        if not entry.sweepable:
            continue
        for k in MBATCH_CANDIDATES:
            out.append(Candidate(entry, k))
    return out


# ---------------------------------------------------------------------------
# cfg access (Config objects AND plain dicts — the gbdt delegates and
# their tests pass both)
# ---------------------------------------------------------------------------
def _get(cfg, name: str, default: Any = None) -> Any:
    if hasattr(cfg, "get"):
        v = cfg.get(name, default)
        return default if v is None else v
    return default


def _explicit(cfg, name: str) -> bool:
    """Did the USER set this knob (resolve-order rung 1)?"""
    if hasattr(cfg, "is_explicit"):
        return bool(cfg.is_explicit(name))
    try:
        return name in cfg
    except TypeError:  # pragma: no cover - exotic cfg objects
        return False


# ---------------------------------------------------------------------------
# per-knob resolvers (validation/warning behavior of the former gbdt
# _pick_* helpers, now registry-owned; gbdt keeps thin delegates)
# ---------------------------------------------------------------------------
def validated_mbatch_env(value: str) -> int:
    """Round and re-guard an ``LGBM_TPU_HIST_MBATCH`` override (1-16)."""
    k = int(value)
    if not 1 <= k <= 16:
        clamped = max(1, min(k, 16))
        log.warning(f"LGBM_TPU_HIST_MBATCH={value} outside [1, 16] "
                    f"(8K must fit the 128 MXU rows); clamped to {clamped}")
        k = clamped
    return k


def validated_fused_block_env(value: str, num_cols: int,
                              vmem_cap_bs: int) -> int:
    """Round and re-guard an ``LGBM_TPU_FUSED_BS`` override.

    The override exists for perf experiments, but it must not be able
    to recreate the hazards the automatic derivation prevents: the
    kernel requires a 32-multiple block size (Mosaic DMA alignment,
    ops/fused_split.py), and its scoped-VMEM buffers scale with
    ``block_size * num_cols`` — so the value is rounded down to a
    32-multiple and clamped to the same scoped-VMEM-derived cap the
    automatic path uses (``vmem_cap_bs``)."""
    bs = max(32, (int(value) // 32) * 32)
    if bs != int(value):
        log.warning(f"LGBM_TPU_FUSED_BS={value} is not a 32-multiple; "
                    f"rounded to {bs}")
    if bs > vmem_cap_bs:
        log.warning(
            f"LGBM_TPU_FUSED_BS={value} exceeds the scoped-VMEM cap for "
            f"{num_cols}-byte row records (max {vmem_cap_bs}); clamped — "
            "an unchecked override would recreate the VMEM blowup the "
            "guard prevents")
        bs = vmem_cap_bs
    return bs


def resolve_mbatch(cfg, decision: Optional[Dict[str, Any]] = None,
                   sources: Optional[Dict[str, str]] = None) -> int:
    """``tpu_hist_mbatch``: K row blocks per one-hot contraction,
    M = 8K MXU rows. user > env (LGBM_TPU_HIST_MBATCH) > autotune >
    default 8; always clamped to [1, 16]."""
    src = "default"
    k = int(_get(cfg, "tpu_hist_mbatch", 8) or 8)
    if _explicit(cfg, "tpu_hist_mbatch"):
        src = "user"
    elif os.environ.get("LGBM_TPU_HIST_MBATCH", ""):
        k = validated_mbatch_env(os.environ["LGBM_TPU_HIST_MBATCH"])
        src = "env"
    elif decision and decision.get("hist_mbatch"):
        k = int(decision["hist_mbatch"])
        src = "autotune"
    if sources is not None:
        sources["hist_mbatch"] = src
    return max(1, min(k, 16))


def resolve_layout(cfg, num_bins: int,
                   decision: Optional[Dict[str, Any]] = None,
                   platform: Optional[str] = None,
                   sources: Optional[Dict[str, str]] = None) -> str:
    """``tpu_hist_layout``: the Mosaic one-hot register layout.

    "sublane" lays bins along sublanes (B <= 64 only — wider bin counts
    leave no room to group features into the 128 MXU rows). ``auto``
    is honest where a measurement exists: an autotune-cache winner for
    this shape-class selects the layout it measured fastest (the PR 6
    sweep showed sublane competitive at B <= 64); without a cache the
    conservative lane default holds."""
    mode = str(_get(cfg, "tpu_hist_layout", "auto") or "auto").lower()
    src = "user" if mode not in ("", "auto") else "default"
    if mode in ("", "auto"):
        mode = "lane"
        if decision and decision.get("hist_layout"):
            cand = str(decision["hist_layout"])
            if cand == "sublane" and (num_bins <= 0 or num_bins > 64):
                pass      # stale cache vs a wider re-bin: keep lane
            elif cand == "sublane" and (platform or current_platform()) \
                    not in TPU_PLATFORMS:
                pass      # Mosaic layout needs a TPU backend
            elif cand in ("lane", "sublane"):
                mode, src = cand, "autotune"
    elif mode not in ("lane", "sublane"):
        log.warning(f"tpu_hist_layout={mode!r} is not one of "
                    "auto|lane|sublane; using the lane layout (auto "
                    "stays on the conservative lane default until an "
                    "autotune cache records a sublane win for this "
                    "shape-class — tpu_autotune=first_run)")
        if sources is not None:
            sources["hist_layout"] = "default"
        return "lane"
    if mode == "sublane" and num_bins > 64:
        # num_bins <= 0 means "width unknown" (no train-set context,
        # e.g. reset_parameter on a loaded booster) — the bound is
        # enforced where a real width exists, not against a guess
        log.warning(
            f"tpu_hist_layout=sublane needs num_bins <= 64 (got "
            f"{num_bins}): bins lie along sublanes and wider counts "
            "cannot group features into the 128 MXU rows; using lane")
        if sources is not None:
            sources["hist_layout"] = "default"
        return "lane"
    if sources is not None:
        sources["hist_layout"] = src
    return mode


def resolve_impl(cfg, decision: Optional[Dict[str, Any]] = None,
                 sources: Optional[Dict[str, str]] = None) -> str:
    """``tpu_hist_impl``: the standalone histogram engine. user >
    autotune > "auto" (the trace-time per-width heuristic in
    ops/histogram.py _resolve_impl — the ``tpu_autotune=off`` escape
    hatch)."""
    src = "default"
    impl = str(_get(cfg, "tpu_hist_impl", "auto") or "auto").lower()
    if _explicit(cfg, "tpu_hist_impl") and impl != "auto":
        if impl not in ("xla", "pallas"):
            log.warning(f"tpu_hist_impl={impl!r} is not one of "
                        "auto|xla|pallas; using auto")
            impl = "auto"
        else:
            src = "user"
    elif decision and decision.get("hist_impl") in ("xla", "pallas"):
        impl, src = str(decision["hist_impl"]), "autotune"
    else:
        impl = "auto"
    if sources is not None:
        sources["hist_impl"] = src
    return impl


def resolve_fused_block(cfg, platform: Optional[str] = None,
                        sources: Optional[Dict[str, str]] = None) -> int:
    """``tpu_fused``: the fused per-split Mosaic kernel block size
    (0 = off). auto = on whenever a real TPU backend is present; the
    fused kernel is selected structurally, not by the microbench (see
    EngineEntry.sweepable), but its hist_flush inherits the autotuned
    layout/mbatch. The record-width scoped-VMEM clamp re-runs at
    :func:`clamp_fused_block` once the row layout is known."""
    from ..ops.fused_split import fused_available
    mode = str(_get(cfg, "tpu_fused", "auto") or "auto").lower()
    src = "user" if _explicit(cfg, "tpu_fused") else "default"
    if sources is not None:
        sources["fused_block"] = src
    if mode in ("off", "0", "false"):
        return 0
    if bool(_get(cfg, "tpu_fused_interpret", False)):
        # CI-only: run the Mosaic kernel in Pallas interpret mode on CPU
        bs = int(_get(cfg, "tpu_fused_block", 512) or 512)
        return max(32, (bs // 32) * 32)
    available = (fused_available() if platform is None
                 else platform in TPU_PLATFORMS and fused_available())
    if mode == "on" and not available:
        log.warning("tpu_fused=on requires a TPU backend (Mosaic); "
                    "falling back to the XLA compact path")
        if sources is not None:
            sources["fused_block"] = "default"
        return 0
    if mode == "on" or (mode == "auto" and available):
        bs = int(_get(cfg, "tpu_fused_block", 512) or 512)
        return max(32, (bs // 32) * 32)
    return 0


def resolve_step_buckets(cfg,
                         sources: Optional[Dict[str, str]] = None) -> bool:
    """``tpu_step_buckets``: the bucketed grower-step ladder.

    On (the default), the step program's jit key carries the
    power-of-two leaf RUNG and the {unlimited, bounded} depth bucket
    instead of the exact (num_leaves, max_depth) pair — the actual
    budgets ride as traced scalars, so every configuration in a rung
    shares one compiled program. ``off`` is the exact-keyed escape
    hatch for parity benching."""
    mode = str(_get(cfg, "tpu_step_buckets", "auto") or "auto").lower()
    if sources is not None:
        sources["step_buckets"] = \
            "user" if _explicit(cfg, "tpu_step_buckets") else "default"
    if mode in ("off", "0", "false"):
        return False
    if mode not in ("", "auto", "on", "1", "true"):
        log.warning(f"tpu_step_buckets={mode!r} is not one of "
                    "auto|on|off; the ladder stays on")
    return True


def resolve_overlap(cfg,
                    sources: Optional[Dict[str, str]] = None) -> int:
    """``tpu_hist_overlap``: async histogram-collective overlap.

    ``on`` builds each leaf histogram in 2 feature groups with one
    psum_scatter/all-reduce per group, issued while the next group
    still accumulates — collective latency hides under the MXU
    contraction at unchanged byte totals. Only meaningful on the
    distributed learners. ``auto`` stays off until a real-TPU sweep
    says otherwise (the autotuner does not sweep it: overlap needs live
    collectives, which a single-chip microbench cannot time)."""
    mode = str(_get(cfg, "tpu_hist_overlap", "auto") or "auto").lower()
    if sources is not None:
        sources["hist_overlap"] = \
            "user" if _explicit(cfg, "tpu_hist_overlap") else "default"
    if mode in ("on", "1", "true"):
        return 2
    if mode not in ("", "auto", "off", "0", "false"):
        log.warning(f"tpu_hist_overlap={mode!r} is not one of "
                    "auto|on|off; overlap stays off")
    return 0


def clamp_fused_block(block: int, num_cols: int, mbatch: int,
                      hist_layout: str, num_bins: int, num_features: int,
                      env_override: str = "") -> int:
    """The record-width scoped-VMEM clamp (registry-owned since round
    12; previously inlined in gbdt._setup_compact_state).

    The kernel's streaming buffers scale with ``block_size * num_cols``
    and the batched-M pending ring with ``mbatch * block_size`` (bins +
    transposed channels + the flush's one-hot and block-diagonal
    transients — both register layouts charged, ops/fused_split.py
    fused_ring_bytes); the histogram accumulator needs
    ``f_pad * stride * 32`` bytes regardless of block size, so a shape
    whose accumulator alone blows the ~16MB scoped limit falls back to
    the XLA walk (returns 0). ``env_override`` (LGBM_TPU_FUSED_BS) is
    rounded + re-guarded, never trusted raw."""
    if not block:
        return 0
    from ..ops.fused_split import _hist_packing, fused_block_cap
    vmem_cap_bs = fused_block_cap(num_cols, mbatch,
                                  hist_layout=hist_layout)
    bs = min(block, vmem_cap_bs)
    if env_override:
        # perf experiments; rounded + re-guarded, never trusted raw
        bs = validated_fused_block_env(env_override, num_cols, vmem_cap_bs)
    stride, f_pad, _ = _hist_packing(num_features, num_bins)
    f_hist_bytes = f_pad * stride * 32
    if f_hist_bytes > 6 << 20:
        log.warning("fused kernel disabled: histogram accumulator "
                    f"needs {f_hist_bytes >> 20}MB VMEM; using the "
                    "XLA compact walk")
        return 0
    return bs


# ---------------------------------------------------------------------------
# THE resolve callsite
# ---------------------------------------------------------------------------
def resolve(cfg, shape: Optional[DatasetShape] = None,
            sample_provider=None, platform: Optional[str] = None,
            allow_sweep: bool = True,
            prior: Optional[Resolution] = None) -> Resolution:
    """Resolve every engine knob for one training run.

    ``shape`` keys the autotune cache (None = no shape context, e.g. a
    booster constructed without a train set: heuristic defaults only).
    ``sample_provider(n)`` returns up to ``n`` rows of the REAL binned
    matrix for the microbench; ``allow_sweep=False`` never runs a new
    sweep. ``prior`` (reset_parameter) is the run's previous
    Resolution: its in-memory decision is reused VERBATIM — no cache
    re-read, no file I/O in the training loop, and the engine a run
    measured at startup can neither vanish (unwritable cache) nor flip
    (cache rewritten underneath a live run) on a mid-run re-resolve.
    """
    platform = platform or current_platform()
    sources: Dict[str, str] = {}
    decision = None
    swept = False
    sclass = shape_class(shape) if shape is not None else None
    if prior is not None:
        decision = prior.decision
    elif shape is not None:
        from . import autotune
        decision, swept = autotune.decision_for(
            cfg, shape, platform, sample_provider=sample_provider,
            allow_sweep=allow_sweep)
    # 0 = bin width unknown (no train-set context): the sublane bound
    # cannot be checked, so it is not enforced against a made-up width
    num_bins = int(shape.num_bins) if shape is not None else 0
    mbatch = resolve_mbatch(cfg, decision, sources)
    layout = resolve_layout(cfg, num_bins, decision, platform, sources)
    impl = resolve_impl(cfg, decision, sources)
    fused_block = resolve_fused_block(cfg, platform, sources)
    step_buckets = resolve_step_buckets(cfg, sources)
    overlap = resolve_overlap(cfg, sources)
    if fused_block:
        entry_id = "fused_sublane" if layout == "sublane" else "fused_lane"
    elif decision and decision.get("entry"):
        entry_id = str(decision["entry"])
    elif impl == "pallas":
        entry_id = ("pallas_sublane" if layout == "sublane"
                    else "pallas_lane")
    else:
        entry_id = "xla_lane"
    res = Resolution(
        entry_id=entry_id, fused_block=fused_block, hist_impl=impl,
        hist_mbatch=mbatch, hist_layout=layout, hist_overlap=overlap,
        step_buckets=step_buckets, sources=sources, shape_class=sclass,
        autotuned=bool(decision), decision=decision)
    if decision and prior is None:
        log.info(
            f"engine registry: shape-class {sclass} -> {entry_id} "
            f"(layout={layout}, mbatch={mbatch}, impl={impl}; "
            f"{'measured now' if swept else 'autotune cache'})")
    return res


# ---------------------------------------------------------------------------
# serving-engine resolution (ROADMAP 4)
# ---------------------------------------------------------------------------
class ServingResolution(NamedTuple):
    """The registry's serving answer: which per-row router runs.

    ``engine`` is the resolved router (``walk`` | ``level``);
    ``entry_id`` the registry entry it maps to (``serve_qleaf`` when a
    quantized leaf slab rides the router); ``source`` the resolve-order
    rung that produced it (user / env / autotune / default).
    """
    engine: str
    entry_id: str
    source: str
    shape_class: Optional[str] = None
    decision: Optional[Dict[str, Any]] = None


def serving_shape_class(tree_bucket: int, depth: int, num_class: int,
                        quant: str = "off") -> str:
    """Autotune cache key for one serving shape: tree bucket + depth +
    class count (+ quant mode), the jit-key axes a frozen model's
    serving programs are compiled on. Distinct from the training shape
    classes by the ``serve-`` prefix."""
    tag = "" if quant in ("", "off", None) else f"-q{quant}"
    return f"serve-t{int(tree_bucket)}-d{int(depth)}-k{int(num_class)}{tag}"


def _serving_entry_id(engine: str, quant: str) -> str:
    if quant not in ("", "off", None):
        return "serve_qleaf"
    return f"serve_{engine}"


def resolve_serving_engine(cfg, depth: int, level_cap: int,
                           tree_bucket: int = 0, num_class: int = 1,
                           quant: str = "off",
                           platform: Optional[str] = None,
                           racer=None) -> ServingResolution:
    """Resolve ``tpu_predict_engine`` to a serving router.

    The same per-knob order as :func:`resolve`::

        user explicit > env LGBM_TPU_PREDICT_ENGINE > autotune cache
        > heuristic default

    ``level`` demotes to ``walk`` (with a warning) when the stack is
    deeper than ``level_cap`` — the per-level slab is O(2^depth) per
    tree, so deep/ragged buckets keep the walk. ``auto`` consults the
    autotune cache (shape class :func:`serving_shape_class`) and, when
    armed with a ``racer``, times the candidate engines on the real
    stacked trees (engines/autotune.serving_decision_for); unarmed it
    falls to the depth heuristic. ``scan`` never reaches here (callers
    branch to the reference path first).
    """
    platform = platform or current_platform()
    sclass = serving_shape_class(tree_bucket, depth, num_class, quant)

    def norm(value: str, source: str) -> Optional[ServingResolution]:
        if value in ("batched", "walk"):
            return ServingResolution("walk", _serving_entry_id(
                "walk", quant), source, sclass)
        if value == "level":
            if depth > level_cap:
                log.warning(
                    f"tpu_predict_engine=level: stacked depth {depth} "
                    f"exceeds tpu_level_depth_cap={level_cap}; the "
                    "bucket keeps the pointer walk")
                return ServingResolution("walk", _serving_entry_id(
                    "walk", quant), source, sclass)
            return ServingResolution("level", _serving_entry_id(
                "level", quant), source, sclass)
        if value not in ("", "auto"):
            log.warning(f"tpu_predict_engine={value!r} is not one of "
                        f"{'|'.join(SERVING_ENGINE_VALUES)}; using the "
                        "depth-batched walk")
            return ServingResolution("walk", _serving_entry_id(
                "walk", quant), source, sclass)
        return None

    raw = str(_get(cfg, "tpu_predict_engine", "batched")
              or "batched").lower()
    if _explicit(cfg, "tpu_predict_engine"):
        res = norm(raw, "user")
        if res is not None:
            return res
    env = os.environ.get("LGBM_TPU_PREDICT_ENGINE", "").strip().lower()
    if env:
        res = norm(env, "env")
        if res is not None:
            return res
    if raw != "auto":
        # unset knob keeps its heuristic default spelling ("batched")
        res = norm(raw, "default")
        if res is not None:
            return res
    # auto: measured decision when armed, depth heuristic otherwise
    from . import autotune
    decision, _swept = autotune.serving_decision_for(
        cfg, sclass, platform, runners_provider=racer)
    eng = (decision or {}).get("serve_engine")
    if eng in ("walk", "level"):
        if eng == "level" and depth > level_cap:
            eng = "walk"
        return ServingResolution(eng, _serving_entry_id(eng, quant),
                                 "autotune", sclass, decision)
    eng = "level" if depth <= level_cap else "walk"
    return ServingResolution(eng, _serving_entry_id(eng, quant),
                             "default", sclass)
