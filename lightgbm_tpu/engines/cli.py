"""``scripts/autotune`` — run the engine microbench sweep offline.

A thin operational wrapper over the autotuner (engines/autotune.py):
bin a CSV (or build a synthetic shape proxy), run the SAME candidate
sweep ``_setup_train`` would run, print the decision table, and —
with ``--cache`` — persist the winner so later training runs (and
multi-process pods, which never sweep locally) resolve their engines
with zero startup microbenches.

Examples::

    scripts/autotune train.csv --label-col 0 --cache ~/.cache/lightgbm_tpu/autotune.json
    scripts/autotune --rows 1e6 --features 28 --max-bin 255   # shape proxy
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import autotune, registry


def _load_binned_csv(path: str, label_col: int, max_bin: int):
    """Bin a CSV through the real Dataset pipeline — the sweep then
    times the engines on the ACTUAL bin distribution, not a proxy."""
    import numpy as np

    from .. import basic
    raw = np.genfromtxt(path, delimiter=",", dtype=np.float64)
    if raw.ndim != 2:
        raise SystemExit(f"{path}: expected a 2-D CSV matrix")
    y = raw[:, label_col]
    X = np.delete(raw, label_col, axis=1)
    ds = basic.Dataset(X, label=y, params={"max_bin": max_bin})
    ds.construct()
    inner = ds._inner
    return inner.binned, int(inner.max_num_bins)


def _synthetic_binned(rows: int, features: int, max_bin: int, seed: int):
    """Uniform-random bin codes of the requested shape — a proxy for
    engine timing (the one-hot contraction's cost is shape-, not
    value-, dependent), clearly labeled as such in the output."""
    import numpy as np
    rng = np.random.RandomState(seed)
    b = max_bin + 1
    dt = np.uint8 if b <= 256 else np.int32
    return rng.randint(0, b, (rows, features)).astype(dt), b


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="autotune", description=__doc__.splitlines()[0])
    ap.add_argument("data", nargs="?", default=None,
                    help="training CSV (binned through the real "
                         "pipeline); omit to sweep a synthetic "
                         "--rows x --features shape proxy")
    ap.add_argument("--label-col", type=int, default=0,
                    help="label column index in the CSV (default 0)")
    ap.add_argument("--rows", type=float, default=1e5,
                    help="synthetic rows (no CSV; default 1e5)")
    ap.add_argument("--features", type=int, default=28,
                    help="synthetic feature count (default 28)")
    ap.add_argument("--max-bin", type=int, default=255,
                    help="bin width (CSV binning AND synthetic codes)")
    ap.add_argument("--mode", default="serial",
                    choices=("serial", "data", "voting", "feature"),
                    help="learner mode the decision is keyed under")
    ap.add_argument("--reps", type=int, default=autotune.SWEEP_REPS,
                    help="timed repetitions per candidate")
    ap.add_argument("--sample-rows", type=int,
                    default=autotune.SWEEP_SAMPLE_ROWS,
                    help="rows sampled for the microbench")
    ap.add_argument("--cache", default="",
                    help="persist the decision to this autotune cache "
                         "(the tpu_autotune_cache trainers read); "
                         "print-only when omitted")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    if args.data:
        binned, num_bins = _load_binned_csv(args.data, args.label_col,
                                            args.max_bin)
        source = args.data
    else:
        binned, num_bins = _synthetic_binned(
            int(args.rows), args.features, args.max_bin, args.seed)
        source = (f"synthetic proxy [{int(args.rows)} x "
                  f"{args.features}] (shape-, not value-, dependent)")
    rows, features = binned.shape
    platform = registry.current_platform()
    shape = registry.DatasetShape(rows=rows, features=features,
                                  num_bins=num_bins, mode=args.mode)
    sclass = registry.shape_class(shape)
    candidates = registry.sweep_candidates(shape, platform)
    if not candidates:
        print(f"no sweepable engine candidates for {sclass} on "
              f"{platform}", file=sys.stderr)
        return 2
    print(f"# source: {source}", file=sys.stderr)
    print(f"# platform={platform} shape_class={sclass} "
          f"candidates={len(candidates)}", file=sys.stderr)
    n = min(rows, args.sample_rows)
    stride = max(1, rows // n)
    sample = binned[::stride][:n]
    winner, table = autotune.run_sweep(sample, num_bins, candidates,
                                       reps=args.reps)
    width = max(len(r["candidate"]) for r in table)
    for r in table:
        if "ms" in r:
            line = (f"{r['candidate']:<{width}}  {r['ms']:>10.4f} ms  "
                    f"{r['rows_per_sec']:>12,} rows/s")
        else:
            line = f"{r['candidate']:<{width}}  ERROR: {r['error']}"
        print(line)
    if winner is None:
        print("every candidate failed — no decision", file=sys.stderr)
        return 1
    print(f"winner: {json.dumps(winner)}")
    if args.cache:
        block = autotune.decision_block(winner, table, platform, sclass,
                                        n, args.reps)
        autotune.store_decision(args.cache,
                                autotune.cache_key(platform, sclass),
                                block)
        print(f"decision persisted to {args.cache} "
              f"[{autotune.cache_key(platform, sclass)}]",
              file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via scripts/
    sys.exit(main())
