"""Engine registry + startup microbench autotuner.

ONE owner for histogram-engine selection (registry.py) and the
measured per-shape decision plane on top of it (autotune.py) —
ROADMAP item 1: the {fused, pallas, xla-einsum} x mbatch x block size
x layout knob space collapses behind ``registry.resolve``, and the
choices flip from heuristic guesses to startup measurements.

Module level stays jax-free (like ``obs``): ``scripts/tpulint``'s
stub-package trick and the offline ``scripts/autotune`` CLI both import
pieces of this package before a backend exists; everything that needs
jax imports it lazily inside the function that runs on-device work.
"""
from . import registry  # noqa: F401  (jax-free)
