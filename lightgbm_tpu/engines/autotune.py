"""Startup microbench autotuner: measured per-shape engine selection.

``tpu_autotune=off|first_run|always`` (default ``first_run``): at
``_setup_train`` the registry's eligible sweep candidates
(engines/registry.py, {xla, pallas} x {lane, sublane} x batched-M
depth) are each timed on a small strided sample of the REAL binned
matrix — a few histogram builds per candidate, ``block_until_ready``,
under the ``autotune`` obs span and compile phase — and the winner
becomes the shape-class's decision. The decision PERSISTS to a JSON
cache (``tpu_autotune_cache``, atomic write-temp-rename like
obs/ledger.py), so a repeat run with the same shape-class and backend
resolves with ZERO microbenches and zero extra compiles; bench.py
copies the recorded sweep tables into ``BENCH_SHAPES.json["autotune"]``
(``BENCH_AUTOTUNE=1``) and ``scripts/autotune`` runs the same sweep
offline.

Arming rules (the part that keeps tier-1 and every CPU run inert by
default):

* ``off`` — never; the registry resolves pure heuristics (the escape
  hatch the parity tests diff against).
* ``first_run`` (default) — armed when the user set ``tpu_autotune``
  explicitly, OR implicitly on a real TPU backend for shapes of at
  least :data:`MIN_AUTOTUNE_ROWS` rows (tiny shapes gain nothing and
  the default must not tax small jobs or the CPU test suite). A cache
  hit skips the sweep.
* ``always`` — re-sweep even over a cache hit (perf investigations).

Multi-process runs never sweep locally: per-rank timings would elect
different winners and desync every collective — they read the shared
cache (same decision on every rank) or fall back to heuristics with a
warning pointing at ``scripts/autotune``.

The sweep runs strictly BEFORE the steady-state window: its compiles
land in the ``autotune`` phase (guards.compile_phase) and the
0-recompile/0-d2h steady-state guard holds with autotune armed
(tests/test_registry.py).
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log
from . import registry

#: cache schema version (consumers key on it before trusting fields)
CACHE_VERSION = 1

#: rows the microbench samples from the real binned matrix (strided)
SWEEP_SAMPLE_ROWS = 1 << 14

#: timed repetitions per candidate (after one warm/compile call)
SWEEP_REPS = 3

#: implicit-arming row floor: below this the engine choice is noise and
#: the DEFAULT first_run mode stays inert (explicit tpu_autotune
#: settings arm at any size — tests and perf experiments opt in)
MIN_AUTOTUNE_ROWS = 1 << 16

MODES = ("off", "first_run", "always")

#: module-level sweep counter — tests pin "exactly one microbench on a
#: fresh cache, zero on the warm rerun" against it
SWEEPS_RUN = 0


def resolve_mode(cfg) -> str:
    """Validate ``tpu_autotune``; unknown values warn and fall back to
    the ``first_run`` default."""
    mode = str(registry._get(cfg, "tpu_autotune", "first_run")
               or "first_run").lower()
    if mode in ("0", "false"):
        mode = "off"
    if mode not in MODES:
        log.warning(f"tpu_autotune={mode!r} is not one of "
                    f"{'|'.join(MODES)}; using first_run")
        return "first_run"
    return mode


def cache_path(cfg) -> str:
    """``tpu_autotune_cache``, or the per-user default location."""
    path = str(registry._get(cfg, "tpu_autotune_cache", "") or "")
    if path:
        return path
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "lightgbm_tpu", "autotune.json")


def cache_key(platform: str, sclass: str) -> str:
    return f"{platform}/{sclass}"


def load_cache(path: str) -> Dict[str, Any]:
    """Tolerant cache read: a missing, torn, or wrong-version file is an
    EMPTY cache (the sweep re-runs and rewrites it), never an error."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError):
        log.warning(f"tpu_autotune_cache {path} is unreadable/corrupt; "
                    "treating it as empty (the microbench will re-run "
                    "and rewrite it)")
        return {}
    if not isinstance(data, dict) \
            or data.get("version") != CACHE_VERSION \
            or not isinstance(data.get("entries"), dict):
        log.warning(f"tpu_autotune_cache {path} has an unknown schema; "
                    "treating it as empty")
        return {}
    return data


def store_decision(path: str, key: str, block: Dict[str, Any]) -> None:
    """Merge one shape-class decision into the cache file atomically
    (write-temp-rename, the obs/ledger.py discipline — a killed run
    must never leave a torn cache)."""
    data = load_cache(path)
    if not data:
        data = {"version": CACHE_VERSION, "entries": {}}
    data["entries"][key] = block
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        # BaseException, not OSError: a serializer TypeError or a
        # SimulatedKill mid-dump must not orphan the temp file (R012)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def sweep_tables(path: str) -> Dict[str, Any]:
    """Every recorded decision block, keyed by ``platform/shape-class``
    — what bench.py copies into BENCH_SHAPES.json["autotune"]."""
    return dict(load_cache(path).get("entries", {}))


def _time_candidate(fn, *args, reps: int = SWEEP_REPS) -> float:
    """One warm call (compile + cache fill), then the mean of ``reps``
    back-to-back dispatches with one trailing sync — the bench.py
    _timed_mean discipline. Module-level so the fast-lane tests stub it
    (the REAL timed sweep lives in the slow lane)."""
    fn(*args).block_until_ready()
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / max(1, reps)


def run_sweep(sample, num_bins: int,
              candidates: List[registry.Candidate],
              reps: int = SWEEP_REPS, quant: bool = False,
              pack4: bool = False
              ) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
    """Time every candidate on ``sample`` (host [n, F] bin codes);
    returns ``(winner_knobs_or_None, table)``. Runs under the
    ``autotune`` span and compile phase so device traces and compile
    counters attribute the startup work honestly.

    ``quant``/``pack4`` make the measurement match the engine path the
    shape-class actually trains on: quant classes time int8 code
    channels through the int8 -> int32 contraction (fp32 relative
    speeds do not transfer — that difference is the quant path's whole
    premise), pack4 classes time nibble-packed blocks through the
    in-loop unpack."""
    global SWEEPS_RUN
    import numpy as np

    import jax

    from ..analysis.guards import compile_phase
    from ..obs.spans import span
    from ..ops.histogram import histogram_block

    SWEEPS_RUN += 1
    sample = np.ascontiguousarray(sample)
    n = int(sample.shape[0])
    rng = np.random.RandomState(0)
    table: List[Dict[str, Any]] = []
    packed_features = 0
    if pack4:
        from ..io.dataset import pack4_matrix
        packed_features = int(sample.shape[1])
        sample = pack4_matrix(sample)
    with span("autotune"), compile_phase("autotune"):
        import jax.numpy as jnp
        binned = jnp.asarray(sample)
        if quant:
            codes = rng.randint(-8, 9, (n, 4)).astype(np.int8)
            codes[:, 1] = rng.randint(0, 9, n)      # hess codes >= 0
            codes[:, 2:] = 1                        # count channels
            channels = jnp.asarray(codes)
        else:
            channels = jnp.asarray(rng.randn(n, 4).astype(np.float32))

        def build(cand):
            def hist(b, c):
                with span("autotune"):
                    return histogram_block(
                        b, c, num_bins=num_bins, impl=cand.entry.impl,
                        mbatch=cand.mbatch, layout=cand.entry.layout,
                        packed4_features=packed_features)
            return jax.jit(hist)

        for cand in candidates:
            row: Dict[str, Any] = {
                "candidate": cand.key, "entry": cand.entry.id,
                "hist_impl": cand.entry.impl,
                "hist_layout": cand.entry.layout,
                "hist_mbatch": cand.mbatch,
            }
            try:
                dt = _time_candidate(build(cand), binned, channels,
                                     reps=reps)
            except Exception as err:  # noqa: BLE001 - record, move on
                row["error"] = str(err).splitlines()[0][:200]
                table.append(row)
                continue
            row["ms"] = round(dt * 1e3, 4)
            row["rows_per_sec"] = round(n / max(dt, 1e-12))
            table.append(row)
    timed = [r for r in table if "ms" in r]
    if not timed:
        return None, table
    best = min(timed, key=lambda r: r["ms"])
    winner = {"entry": best["entry"], "hist_impl": best["hist_impl"],
              "hist_layout": best["hist_layout"],
              "hist_mbatch": best["hist_mbatch"]}
    return winner, table


def _multiproc() -> bool:
    try:
        import jax
        return jax.process_count() > 1
    except Exception:  # pragma: no cover - backend-less host
        return False


def _all_swept_knobs_pinned(cfg) -> bool:
    """User/env own every knob the sweep can decide — the microbench
    could not influence anything, so startup pays nothing for it."""
    mbatch = registry._explicit(cfg, "tpu_hist_mbatch") \
        or bool(os.environ.get("LGBM_TPU_HIST_MBATCH", ""))
    layout = registry._explicit(cfg, "tpu_hist_layout") and \
        str(registry._get(cfg, "tpu_hist_layout", "auto")
            or "auto").lower() not in ("", "auto")
    impl = registry._explicit(cfg, "tpu_hist_impl") and \
        str(registry._get(cfg, "tpu_hist_impl", "auto")
            or "auto").lower() not in ("", "auto")
    return mbatch and layout and impl


def decision_block(winner, table, platform: str, sclass: str,
                   rows_sampled: int, reps: int) -> Dict[str, Any]:
    """The cache-entry schema — ONE construction site shared by
    :func:`decision_for` and the offline CLI (engines/cli.py), so a
    schema change cannot fork between the two writers."""
    return {"winner": winner, "table": table, "platform": platform,
            "shape_class": sclass, "rows_sampled": int(rows_sampled),
            "reps": int(reps),
            "recorded": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime())}


def decision_for(cfg, shape: registry.DatasetShape, platform: str,
                 sample_provider=None, allow_sweep: bool = True
                 ) -> Tuple[Optional[Dict[str, Any]], bool]:
    """The autotuner's half of ``registry.resolve``: ``(winner_knobs or
    None, swept_now)``. Explicit user knobs never reach here per-knob —
    the resolve order applies the decision only below user/env."""
    mode = resolve_mode(cfg)
    if mode == "off" or shape is None:
        return None, False
    armed = registry._explicit(cfg, "tpu_autotune") or (
        platform in registry.TPU_PLATFORMS
        and shape.rows >= MIN_AUTOTUNE_ROWS)
    if not armed:
        return None, False
    if _all_swept_knobs_pinned(cfg):
        return None, False
    path = cache_path(cfg)
    key = cache_key(platform, registry.shape_class(shape))
    cached = load_cache(path).get("entries", {}).get(key)
    if cached is not None and mode != "always":
        return cached.get("winner"), False
    if not allow_sweep or sample_provider is None:
        return (cached or {}).get("winner"), False
    if _multiproc():
        log.warning(
            "tpu_autotune: multi-process run with no cached decision "
            f"for {key} — per-rank microbenches would elect divergent "
            "winners and desync the collectives, so the heuristic "
            "defaults apply; record a decision offline with "
            "scripts/autotune (or a single-host run) into "
            f"{path} first")
        return (cached or {}).get("winner"), False
    candidates = registry.sweep_candidates(shape, platform)
    if not candidates:
        return None, False
    n = min(int(shape.rows), SWEEP_SAMPLE_ROWS)
    sample = sample_provider(n)
    winner, table = run_sweep(
        sample, int(shape.num_bins), candidates,
        quant=shape.quant,
        # pack4 nibble-packs only where every stored column fits a
        # nibble; the common padded width is the available proxy here
        pack4=shape.pack4 and int(shape.num_bins) <= 16)
    if winner is None:
        log.warning("tpu_autotune: every sweep candidate failed; "
                    "keeping the heuristic defaults")
        return None, True
    block = decision_block(winner, table, platform,
                           registry.shape_class(shape),
                           sample.shape[0], SWEEP_REPS)
    try:
        store_decision(path, key, block)
    except OSError as err:
        log.warning(f"tpu_autotune: cannot persist the decision to "
                    f"{path} ({err}); this run still uses the measured "
                    "winner, the next run will re-bench")
    return winner, True


def serving_decision_for(cfg, sclass: str, platform: Optional[str] = None,
                         runners_provider=None, allow_sweep: bool = True
                         ) -> Tuple[Optional[Dict[str, Any]], bool]:
    """The autotuner's serving half (registry.resolve_serving_engine's
    ``auto`` rung): ``(winner or None, raced_now)``.

    ``runners_provider()`` returns ``({engine_id: zero-arg dispatch},
    rows)`` — each dispatch runs the REAL stacked trees over a small
    rung (gbdt._serving_race_runners), so the race measures the actual
    serving programs, not a synthetic proxy. Decisions persist to the
    same atomic autotune cache under the ``serve-*`` shape class; the
    arming rules mirror :func:`decision_for` (explicit ``tpu_autotune``
    arms everywhere, TPU platforms arm implicitly, multi-process never
    races locally)."""
    global SWEEPS_RUN
    mode = resolve_mode(cfg)
    if mode == "off":
        return None, False
    platform = platform or registry.current_platform()
    armed = registry._explicit(cfg, "tpu_autotune") \
        or platform in registry.TPU_PLATFORMS
    if not armed:
        return None, False
    path = cache_path(cfg)
    key = cache_key(platform, sclass)
    cached = load_cache(path).get("entries", {}).get(key)
    if cached is not None and mode != "always":
        return cached.get("winner"), False
    if not allow_sweep or runners_provider is None or _multiproc():
        return (cached or {}).get("winner"), False
    runners, rows = runners_provider()
    if not runners:
        return None, False
    from ..analysis.guards import compile_phase
    from ..obs.spans import span
    SWEEPS_RUN += 1
    table: List[Dict[str, Any]] = []
    with span("autotune"), compile_phase("autotune"):
        for eng, fn in runners.items():
            row: Dict[str, Any] = {"candidate": f"serve_{eng}",
                                   "serve_engine": eng}
            try:
                dt = _time_candidate(fn)
            except Exception as err:  # noqa: BLE001 - record, move on
                row["error"] = str(err).splitlines()[0][:200]
                table.append(row)
                continue
            row["ms"] = round(dt * 1e3, 4)
            row["rows_per_sec"] = round(rows / max(dt, 1e-12))
            table.append(row)
    timed = [r for r in table if "ms" in r]
    if not timed:
        log.warning("tpu_autotune: every serving-engine candidate "
                    "failed; keeping the depth heuristic")
        return None, True
    best = min(timed, key=lambda r: r["ms"])
    winner = {"serve_engine": best["serve_engine"]}
    block = decision_block(winner, table, platform, sclass, rows,
                           SWEEP_REPS)
    try:
        store_decision(path, key, block)
    except OSError as err:
        log.warning(f"tpu_autotune: cannot persist the serving "
                    f"decision to {path} ({err}); this run still uses "
                    "the measured winner, the next run will re-race")
    return winner, True
