"""Atomic training checkpoints: write-temp-fsync-rename + checksum + rotation.

The reference treats continuation as first-class — ``snapshot_freq`` model
text dumps mid-train (reference: GBDT::Train, gbdt.cpp:250-254) and
``init_model`` warm starts — but a model-text snapshot alone cannot resume
bit-identically: it loses the optimizer-side state (cached scores, RNG
streams, bagging state, early-stopping bests). A lightgbm_tpu snapshot is
the COMPLETE training state (boosting/gbdt.py capture_training_state), so
``lgb.train`` with ``tpu_checkpoint_dir`` resumes a killed run to the
bit-identical model an uninterrupted run would have produced.

Durability contract:

* **Atomic**: payload goes to a temp file in the same directory, is
  fsync-ed, then ``os.replace``-d into place and the directory entry
  fsync-ed — a crash mid-write can never leave a half-written file under
  the snapshot name (the temp name is ignored by the reader).
* **Self-validating**: a fixed magic + length + SHA-256 digest header; a
  torn, truncated, or bit-flipped file raises :class:`SnapshotCorrupt`
  and :func:`load_latest` falls back to the previous valid snapshot.
* **Bounded**: ``keep``-last-k rotation deletes older snapshots after a
  successful write (never before).

Snapshots pickle host numpy state; like any pickle they are only safe to
load from a directory you trust (your own checkpoint dir — same trust
boundary as the reference's model files).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import re
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log

MAGIC = b"LGBMTPUCKPT1"
_HEADER_LEN = len(MAGIC) + 8 + 32
_NAME_RE = re.compile(r"^snapshot_iter_(\d+)\.ckpt$")


class SnapshotCorrupt(ValueError):
    """A snapshot file failed magic/length/checksum/unpickle validation."""


def snapshot_path(directory: str, iteration: int) -> str:
    return os.path.join(directory, f"snapshot_iter_{iteration:09d}.ckpt")


def list_snapshots(directory: str) -> List[Tuple[int, str]]:
    """(iteration, path) pairs present in ``directory``, oldest first."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _NAME_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def _fsync_dir(directory: str) -> None:
    """Durably record the rename in the directory entry (POSIX: the
    rename itself is atomic but not durable until the directory syncs)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:  # pragma: no cover - e.g. non-POSIX directory handles
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def write_snapshot(directory: str, iteration: int, state: Dict[str, Any],
                   keep: int = 3) -> str:
    """Atomically persist ``state`` as the snapshot for ``iteration``.

    Returns the final path. Rotation (keep-last-``keep``) runs only after
    the new snapshot is durably in place; ``keep <= 0`` keeps everything.
    """
    from ..obs import flight
    from ..obs.spans import span
    os.makedirs(directory, exist_ok=True)
    with span("checkpoint_write"):
        payload = pickle.dumps(state, protocol=4)
        digest = hashlib.sha256(payload).digest()
        final = snapshot_path(directory, iteration)
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=".snapshot_tmp_")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(MAGIC)
                fh.write(len(payload).to_bytes(8, "big"))
                fh.write(digest)
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(directory)
    if keep > 0:
        for _, old in list_snapshots(directory)[:-keep]:
            try:
                os.unlink(old)
            except OSError:  # pragma: no cover - already gone
                pass
    flight.note("snapshot", path=final, iteration=iteration,
                bytes=len(payload))
    # chaos hook: corrupt@snapshot=N damages the file that just landed,
    # exercising the checksum fallback path deterministically
    from ..analysis.faultinject import active_plan
    active_plan().fire("snapshot", path=final)
    return final


def read_snapshot(path: str) -> Dict[str, Any]:
    """Load and validate one snapshot; raises :class:`SnapshotCorrupt`."""
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError as err:
        raise SnapshotCorrupt(f"{path}: unreadable ({err})")
    if len(blob) < _HEADER_LEN or not blob.startswith(MAGIC):
        raise SnapshotCorrupt(f"{path}: bad magic / truncated header")
    n = int.from_bytes(blob[len(MAGIC):len(MAGIC) + 8], "big")
    digest = blob[len(MAGIC) + 8:_HEADER_LEN]
    payload = blob[_HEADER_LEN:]
    if len(payload) != n:
        raise SnapshotCorrupt(
            f"{path}: payload length {len(payload)} != recorded {n} "
            "(torn write)")
    if hashlib.sha256(payload).digest() != digest:
        raise SnapshotCorrupt(f"{path}: checksum mismatch")
    try:
        return pickle.loads(payload)
    except Exception as err:
        raise SnapshotCorrupt(f"{path}: undecodable payload ({err})")


def load_latest(directory: str) -> Optional[Dict[str, Any]]:
    """The newest VALID snapshot's state, or None.

    Corrupted/truncated snapshots are detected by checksum, warned about,
    and skipped back to the previous valid one — the resume analogue of
    the writer's atomicity guarantee."""
    for iteration, path in reversed(list_snapshots(directory)):
        try:
            state = read_snapshot(path)
        except SnapshotCorrupt as err:
            log.warning(f"skipping corrupted snapshot: {err}")
            from ..obs import flight
            flight.note("snapshot_corrupt", path=path, error=str(err)[:200])
            continue
        state.setdefault("iteration", iteration)
        return state
    return None
