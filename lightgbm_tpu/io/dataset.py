"""Binned dataset construction for lightgbm_tpu.

TPU-native re-design of the reference's ``Dataset`` / ``DatasetLoader`` /
``Metadata`` (reference: include/LightGBM/dataset.h:48,487,
src/io/dataset_loader.cpp — ``ConstructFromSampleData`` dataset_loader.cpp:593,
src/io/metadata.cpp).

Differences from the reference, by TPU design:
  * no FeatureGroup / EFB / sparse bins — the binned matrix is a single dense
    ``[N, F]`` uint8/uint16 array living in HBM, padded to a common per-feature
    bin count ``max_num_bins`` (dense layout is what the histogram matmul wants;
    EFB's memory win matters much less when bins are 1 byte and HBM is tens of GB);
  * construction is vectorized numpy on host, then one device_put.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..utils import log
from .binning import (
    MISSING_NAN,
    BinMapper,
    find_bin_categorical,
    find_bin_numerical,
)


def _to_2d_float(data: Any) -> np.ndarray:
    """Coerce input features to a float64 2-D numpy array (host side).

    scipy CSR/CSC matrices densify here: the TPU bin storage is a dense
    [N, F] uint8 matrix by design (io/dataset.py module doc — HBM-friendly
    MXU layout), so sparse inputs are a host-side ingestion format, not a
    device format (reference accepts CSR/CSC the same way through
    LGBM_DatasetCreateFromCSR/CSC, src/c_api.cpp)."""
    if hasattr(data, "tocsr") and hasattr(data, "toarray"):  # scipy.sparse
        arr = data.toarray()
    elif type(data).__module__.startswith("pyarrow"):
        # Arrow Table/RecordBatch ingestion (reference:
        # LGBM_DatasetCreateFromArrow, include/LightGBM/arrow.h)
        arr = np.column_stack([
            np.asarray(data.column(i)) for i in range(data.num_columns)])
    elif hasattr(data, "values") and hasattr(data, "columns"):  # pandas
        arr = data.values
    else:
        arr = data
    arr = np.asarray(arr)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.ndim != 2:
        raise ValueError(f"features must be 2-D, got shape {arr.shape}")
    if arr.dtype == np.float32:
        # keep float32: promoting a 10M x 4228 matrix to float64 doubles
        # peak host memory for nothing — every bound comparison in the
        # binning path upcasts exactly, so bins are bit-identical
        # (io/binning.py bin_columns)
        return arr
    return arr.astype(np.float64, copy=False)


def _feature_names_of(data: Any, num_features: int) -> List[str]:
    if hasattr(data, "column_names"):  # pyarrow Table / RecordBatch
        return [str(c) for c in data.column_names]
    if hasattr(data, "columns"):
        return [str(c) for c in data.columns]
    return [f"Column_{i}" for i in range(num_features)]


class Metadata:
    """Label / weight / query-group / init_score container
    (reference: Metadata, include/LightGBM/dataset.h:48)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None
        self.group: Optional[np.ndarray] = None          # per-group sizes
        self.query_boundaries: Optional[np.ndarray] = None  # cumulative [num_groups+1]
        self.position: Optional[np.ndarray] = None

    def set_label(self, label: Any) -> None:
        arr = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            raise ValueError(f"label length {len(arr)} != num_data {self.num_data}")
        self.label = arr

    def set_weight(self, weight: Any) -> None:
        if weight is None:
            self.weight = None
            return
        arr = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            raise ValueError(f"weight length {len(arr)} != num_data {self.num_data}")
        self.weight = arr

    def set_init_score(self, init_score: Any) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.asarray(init_score, dtype=np.float64)
        self.init_score = arr

    def set_group(self, group: Any) -> None:
        if group is None:
            self.group = None
            self.query_boundaries = None
            return
        arr = np.asarray(group, dtype=np.int64).reshape(-1)
        if arr.sum() != self.num_data:
            raise ValueError(
                f"sum of group sizes ({arr.sum()}) != num_data ({self.num_data})"
            )
        self.group = arr
        self.query_boundaries = np.concatenate([[0], np.cumsum(arr)]).astype(np.int64)

    def set_position(self, position: Any) -> None:
        if position is None:
            self.position = None
            return
        self.position = np.asarray(position, dtype=np.int64).reshape(-1)

    @property
    def num_queries(self) -> int:
        return 0 if self.group is None else len(self.group)


class BinnedDataset:
    """The constructed (binned) training dataset.

    reference analogue: ``Dataset`` (include/LightGBM/dataset.h:487). Holds the
    dense binned matrix, per-feature BinMappers, and Metadata.
    """

    def __init__(self):
        # [N, n_columns] uint8/uint16; n_columns == F unless EFB bundled
        self.binned: Optional[np.ndarray] = None
        self.bundle_info = None                    # io/efb.py BundleInfo
        self.mappers: List[BinMapper] = []
        self.feature_names: List[str] = []
        self.metadata: Optional[Metadata] = None
        self.max_num_bins: int = 1                 # B: common padded bin count
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.used_features: List[int] = []         # non-trivial feature indices
        self.categorical_features: List[int] = []
        self.raw_data: Optional[np.ndarray] = None  # kept only if needed (linear trees)
        # cached reference bin-occupancy (serving drift monitors); built
        # lazily so construct pays nothing when drift is off
        self._ref_dist: Optional[tuple] = None

    # -- binary serialization (reference: Dataset::SaveBinaryFile,
    # src/io/dataset.cpp / DatasetLoader::LoadFromBinFile :417) -------------
    def save_binary(self, path: str) -> None:
        """Save the constructed dataset (bins + mappers + metadata) so later
        runs skip text parsing and re-binning.

        Mappers serialize as JSON inside the npz (never pickle: loading a
        dataset file must not execute code — the reference's binary format is
        plain structs, dataset_loader.cpp:417)."""
        import json
        mapper_blobs = [{
            "num_bins": int(m.num_bins),
            "is_categorical": bool(m.is_categorical),
            "missing_type": int(m.missing_type),
            # non-finite bounds (the last bound is always +inf) go as strings
            # so the blob stays strict RFC-8259 JSON for external consumers
            "bin_upper_bounds": [float(x) if np.isfinite(x) else str(float(x))
                                 for x in m.bin_upper_bounds],
            "cat_to_bin": {str(k): int(v) for k, v in m.cat_to_bin.items()},
            "bin_to_cat": [int(x) for x in m.bin_to_cat],
            "default_bin": int(m.default_bin),
            # same finite-check encoding as bin_upper_bounds: +/-inf feature
            # values flow into min/max and would make json.dumps raise
            "min_value": (float(m.min_value) if np.isfinite(m.min_value)
                          else str(float(m.min_value))),
            "max_value": (float(m.max_value) if np.isfinite(m.max_value)
                          else str(float(m.max_value))),
        } for m in self.mappers]
        md = self.metadata
        # np.savez appends '.npz' to bare paths; write via a handle so the
        # requested filename (e.g. train.bin) is used verbatim
        fh = open(path, "wb")
        np.savez_compressed(
            fh,
            magic=np.frombuffer(b"lgbtpu.bin.v2\x00\x00\x00", np.uint8),
            binned=self.binned,
            feature_names=np.asarray(self.feature_names),
            max_num_bins=self.max_num_bins,
            num_data=self.num_data,
            num_total_features=self.num_total_features,
            used_features=np.asarray(self.used_features, np.int64),
            categorical_features=np.asarray(self.categorical_features,
                                            np.int64),
            bundle_col_of=(np.asarray(self.bundle_info.col_of, np.int64)
                           if self.bundle_info is not None
                           else np.zeros(0, np.int64)),
            bundle_offset_of=(np.asarray(self.bundle_info.offset_of, np.int64)
                              if self.bundle_info is not None
                              else np.zeros(0, np.int64)),
            bundle_col_bins=(np.asarray(self.bundle_info.num_column_bins,
                                        np.int64)
                             if self.bundle_info is not None
                             else np.zeros(0, np.int64)),
            mappers=np.frombuffer(
                json.dumps(mapper_blobs, allow_nan=False).encode(), np.uint8),
            label=md.label if md.label is not None else np.zeros(0),
            weight=md.weight if md.weight is not None else np.zeros(0),
            init_score=(md.init_score if md.init_score is not None
                        else np.zeros(0)),
            group=md.group if md.group is not None else np.zeros(0, np.int64),
            position=(md.position if md.position is not None
                      else np.zeros(0)),
        )
        fh.close()

    @staticmethod
    def load_binary(path: str) -> "BinnedDataset":
        import json
        from .binning import BinMapper
        z = np.load(path, allow_pickle=False)
        if bytes(z["magic"].tobytes())[:13] != b"lgbtpu.bin.v2":
            raise ValueError(
                f"{path} is not a lightgbm_tpu binary dataset (v2); "
                "re-save with save_binary()")
        ds = BinnedDataset()
        ds.binned = z["binned"]
        ds.feature_names = [str(x) for x in z["feature_names"]]
        ds.max_num_bins = int(z["max_num_bins"])
        ds.num_data = int(z["num_data"])
        ds.num_total_features = int(z["num_total_features"])
        ds.used_features = [int(i) for i in z["used_features"]]
        ds.categorical_features = [int(i) for i in z["categorical_features"]]
        if "bundle_col_of" in z and z["bundle_col_of"].size:
            from .efb import BundleInfo
            col_of = z["bundle_col_of"].astype(np.int32)
            off_of = z["bundle_offset_of"].astype(np.int32)
            ds.bundle_info = BundleInfo(
                col_of=col_of, offset_of=off_of,
                num_column_bins=z["bundle_col_bins"].astype(np.int32),
                n_columns=int(z["bundle_col_bins"].size),
                n_bundled=int((off_of >= 0).sum()))
        blobs = json.loads(z["mappers"].tobytes().decode())
        for blob in blobs:
            blob["bin_upper_bounds"] = np.asarray(
                [float(v) for v in blob["bin_upper_bounds"]], np.float64)
            blob["cat_to_bin"] = {int(k): int(v)
                                  for k, v in blob["cat_to_bin"].items()}
            blob["bin_to_cat"] = np.asarray(blob["bin_to_cat"], np.int64)
            blob["min_value"] = float(blob["min_value"])
            blob["max_value"] = float(blob["max_value"])
        ds.mappers = [BinMapper(**blob) for blob in blobs]
        md = Metadata(ds.num_data)
        for name in ("label", "weight", "init_score", "position"):
            arr = z[name]
            if arr.size:
                setattr(md, name, arr)
        if z["group"].size:
            md.set_group(z["group"])
        ds.metadata = md
        return ds

    # -- construction -------------------------------------------------------
    @staticmethod
    def construct(
        data: Any,
        *,
        max_bin: int = 255,
        min_data_in_bin: int = 3,
        bin_construct_sample_cnt: int = 200000,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        categorical_feature: Optional[Sequence[Union[int, str]]] = None,
        feature_names: Optional[Sequence[str]] = None,
        data_random_seed: int = 1,
        reference: Optional["BinnedDataset"] = None,
        keep_raw: bool = False,
        forcedbins_filename: str = "",
        max_bin_by_feature: Optional[Sequence[int]] = None,
        enable_bundle: bool = True,
        max_conflict_rate: float = 1e-4,
    ) -> "BinnedDataset":
        arr = _to_2d_float(data)
        n, f = arr.shape
        ds = BinnedDataset()
        ds.num_data = n
        ds.num_total_features = f
        ds.feature_names = (
            list(feature_names) if feature_names is not None else _feature_names_of(data, f)
        )
        if len(ds.feature_names) != f:
            raise ValueError("feature_names length mismatch")

        if reference is not None:
            # valid set: reuse the reference's bin mappers
            # (reference: Dataset::CreateValid, dataset.h:703)
            if f != reference.num_total_features:
                raise ValueError(
                    f"validation data has {f} features, training data had "
                    f"{reference.num_total_features}"
                )
            ds.mappers = reference.mappers
            ds.max_num_bins = reference.max_num_bins
            ds.used_features = reference.used_features
            ds.categorical_features = reference.categorical_features
        else:
            cat_idx = _resolve_categorical(categorical_feature, ds.feature_names)
            ds.categorical_features = sorted(cat_idx)
            # sample rows for bin construction (reference: bin_construct_sample_cnt)
            if n > bin_construct_sample_cnt:
                rng = np.random.RandomState(data_random_seed)
                sample_idx = rng.choice(n, size=bin_construct_sample_cnt, replace=False)
                sample = arr[np.sort(sample_idx)]
            else:
                sample = arr
            # multi-host: every process contributes its sample and all build
            # identical mappers from the pooled global distribution
            # (reference: ConstructBinMappersFromTextData,
            # src/io/dataset_loader.cpp:1070)
            from ..parallel.multihost import pool_bin_sample
            sample = pool_bin_sample(sample)
            total_sample_cnt = len(sample)
            _fit_mappers(ds, sample, f, cat_idx, max_bin, min_data_in_bin,
                         use_missing, zero_as_missing, forcedbins_filename,
                         max_bin_by_feature)

        # bin all columns — batched over row chunks and column groups
        # (io/binning.py bin_columns, the construct hot path)
        dtype = np.uint8 if ds.max_num_bins <= 256 else np.uint16
        from .binning import bin_columns
        binned = bin_columns(ds.mappers, arr, dtype)
        # Exclusive Feature Bundling: pack mutually-exclusive sparse features
        # into shared columns (reference: FeatureGroup / Dataset::Construct
        # FindGroups, include/LightGBM/feature_group.h). The growers then see
        # n_columns ( << F on one-hot-wide data) storage columns.
        if reference is not None:
            info = reference.bundle_info
            if info is not None:
                binned = _apply_bundles(binned, info, ds, max_conflict_rate)
        elif enable_bundle and ds.max_num_bins <= 256:
            srows = min(n, 50_000)
            info = _plan_efb(ds, binned[:srows], max_bin, max_conflict_rate)
            if info is not None:
                ds.bundle_info = info
                binned = _apply_bundles(binned, info, ds, max_conflict_rate)
                log.info(
                    f"EFB: bundled {info.n_bundled} of {f} features into "
                    f"{info.n_columns} stored columns")
        ds.binned = binned
        ds.metadata = Metadata(n)
        if keep_raw:
            # linear-tree least squares runs on raw values; keep those in
            # float64 regardless of the float32 binning fast path
            ds.raw_data = arr.astype(np.float64, copy=False)
        return ds

    @staticmethod
    def construct_from_sequences(
        seqs: List[Any],
        *,
        max_bin: int = 255,
        min_data_in_bin: int = 3,
        bin_construct_sample_cnt: int = 200000,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        categorical_feature: Optional[Sequence[Union[int, str]]] = None,
        feature_names: Optional[Sequence[str]] = None,
        data_random_seed: int = 1,
        reference: Optional["BinnedDataset"] = None,
        forcedbins_filename: str = "",
        max_bin_by_feature: Optional[Sequence[int]] = None,
        enable_bundle: bool = True,
        max_conflict_rate: float = 1e-4,
    ) -> "BinnedDataset":
        """Streaming construction from Sequence objects (random row access
        + batched range reads): the raw [N, F] float matrix is NEVER
        materialized — peak host memory is the packed bin matrix plus one
        batch (reference: Sequence-based construction,
        python-package/lightgbm/basic.py Sequence +
        Dataset::PushOneRow/FinishLoad, include/LightGBM/dataset.h:583)."""
        lens = [len(s) for s in seqs]
        n = int(sum(lens))
        if n == 0:
            raise ValueError("empty Sequence data")
        probe = next(s for s, m in zip(seqs, lens) if m > 0)
        first = np.asarray(probe[0], np.float64).reshape(-1)
        f = first.shape[0]
        ds = BinnedDataset()
        ds.num_data = n
        ds.num_total_features = f
        ds.feature_names = (list(feature_names) if feature_names is not None
                            else [f"Column_{j}" for j in range(f)])
        if len(ds.feature_names) != f:
            raise ValueError("feature_names length mismatch")

        offsets = np.cumsum([0] + lens)
        if reference is not None:
            if f != reference.num_total_features:
                raise ValueError(
                    f"validation data has {f} features, training data had "
                    f"{reference.num_total_features}")
            ds.mappers = reference.mappers
            ds.max_num_bins = reference.max_num_bins
            ds.used_features = reference.used_features
            ds.categorical_features = reference.categorical_features
            info = reference.bundle_info
        else:
            cat_idx = _resolve_categorical(categorical_feature,
                                           ds.feature_names)
            ds.categorical_features = sorted(cat_idx)
            s_cnt = min(n, bin_construct_sample_cnt)
            rng = np.random.RandomState(data_random_seed)
            idx = np.sort(rng.choice(n, size=s_cnt, replace=False)) \
                if s_cnt < n else np.arange(n)
            sample = np.empty((s_cnt, f), np.float64)
            si = np.searchsorted(offsets, idx, side="right") - 1
            pos = 0
            for sq_i, sq in enumerate(seqs):
                local = (idx[si == sq_i] - offsets[sq_i]).astype(np.int64)
                if not len(local):
                    continue
                m = len(sq)
                if len(local) * 3 >= m:
                    # dense sample: batched slice reads + subset (one
                    # storage round trip per batch, not per row)
                    bs0 = int(getattr(sq, "batch_size", 4096) or 4096)
                    for a in range(0, m, bs0):
                        sel = local[(local >= a) & (local < a + bs0)]
                        if not len(sel):
                            continue
                        batch = np.asarray(sq[a:min(a + bs0, m)],
                                           np.float64).reshape(-1, f)
                        take = batch[sel - a]
                        sample[pos:pos + len(take)] = take
                        pos += len(take)
                else:
                    for i in local:
                        sample[pos] = np.asarray(
                            sq[int(i)], np.float64).reshape(-1)
                        pos += 1
            from ..parallel.multihost import pool_bin_sample
            sample = pool_bin_sample(sample)
            _fit_mappers(ds, sample, f, cat_idx, max_bin, min_data_in_bin,
                         use_missing, zero_as_missing, forcedbins_filename,
                         max_bin_by_feature)
            info = None
            if enable_bundle and ds.max_num_bins <= 256:
                # cap the planning sample like the in-memory path: the
                # planner's occupancy matrix scales with sample rows
                sb = _bin_chunk(ds.mappers, sample[:50_000], np.uint8)
                info = _plan_efb(ds, sb, max_bin, max_conflict_rate)

        dtype = np.uint8 if ds.max_num_bins <= 256 else np.uint16
        dbins_all = np.array([m.default_bin for m in ds.mappers], np.int32)

        def stream(binfo):
            from .efb import bundle_chunk
            cols = binfo.n_columns if binfo is not None else f
            out = np.zeros((n, cols), dtype)
            conflicts = 0
            pos = 0
            for sq in seqs:
                bs = int(getattr(sq, "batch_size", 4096) or 4096)
                m = len(sq)
                for a in range(0, m, bs):
                    raw = np.asarray(sq[a:min(a + bs, m)], np.float64)
                    if raw.ndim == 1:
                        raw = raw.reshape(1, -1)
                    if raw.shape[1] != f:
                        raise ValueError(
                            f"Sequence batch has {raw.shape[1]} features, "
                            f"expected {f}")
                    if raw.shape[0] != min(a + bs, m) - a:
                        raise ValueError(
                            "Sequence slice returned "
                            f"{raw.shape[0]} rows for a "
                            f"{min(a + bs, m) - a}-row range")
                    chunk = _bin_chunk(ds.mappers, raw, dtype)
                    k = chunk.shape[0]
                    if binfo is not None:
                        enc, cf = bundle_chunk(chunk, binfo, dbins_all)
                        conflicts += cf
                        out[pos:pos + k] = enc
                    else:
                        out[pos:pos + k] = chunk
                    pos += k
            if pos != n:
                raise ValueError(
                    f"Sequences yielded {pos} rows, __len__ promised {n}")
            return out, conflicts

        out, conflicts = stream(info)
        if info is not None and reference is None:
            from .efb import conflict_allowance
            if conflicts > conflict_allowance(info, n, max_conflict_rate):
                log.warning("EFB: feature conflict outside the planning "
                            "sample; keeping the dense matrix")
                info = None
                out, _ = stream(None)
            else:
                log.info(
                    f"EFB: bundled {info.n_bundled} of {f} features into "
                    f"{info.n_columns} stored columns (streaming)")
        ds.bundle_info = info
        ds.binned = out
        ds.metadata = Metadata(n)
        return ds

    # -- views for the tree learner ----------------------------------------
    def reference_bin_distribution(self):
        """Normalized per-ORIGINAL-feature bin occupancy of this
        dataset's rows: ``(probs [F, B] float32, num_bins [F] int32)``.

        The drift monitor's reference (ISSUE 14): live serving traffic
        is binned in original feature space with these exact mappers, so
        the per-feature occupancy of the training data is the
        distribution a served window's occupancy is compared against
        (PSI/KL). Computed from the stored bin matrix — EFB bundle
        columns decode through their reserved offset ranges
        (io/binning.bin_occupancy) — and cached: the registry
        materializes it during the deploy warm phase so the monitor
        ships WITH the model and the swap flip never stalls on a data
        pass."""
        if self._ref_dist is not None:
            return self._ref_dist
        if self.binned is None:
            raise ValueError("dataset is not constructed")
        from .binning import bin_occupancy
        counts, nb = bin_occupancy(self.binned, self.mappers,
                                   self.bundle_info)
        probs = (counts / np.maximum(counts.sum(axis=1, keepdims=True), 1)
                 ).astype(np.float32)
        self._ref_dist = (probs, nb)
        return self._ref_dist

    @property
    def num_features(self) -> int:
        return self.num_total_features

    def feature_num_bins(self) -> np.ndarray:
        return np.array([m.num_bins for m in self.mappers], dtype=np.int32)

    def feature_nan_bins(self) -> np.ndarray:
        """Per feature: the bin NaN maps to (for default-direction handling)."""
        return np.array(
            [m.nan_bin if not m.is_trivial else 0 for m in self.mappers],
            dtype=np.int32,
        )

    def feature_is_categorical(self) -> np.ndarray:
        return np.array([m.is_categorical for m in self.mappers], dtype=bool)


def _plan_efb(ds, sample_binned, max_bin, max_conflict_rate):
    """Plan Exclusive Feature Bundling from a binned sample; returns
    BundleInfo or None (shared by the in-memory and streaming paths)."""
    from .efb import build_bundle_info, plan_bundles
    dbins = np.array([m.default_bin for m in ds.mappers], np.int32)
    nbins = np.array([m.num_bins for m in ds.mappers], np.int32)
    ok = np.array(
        [(not m.is_categorical) and m.missing_type != MISSING_NAN
         and not m.is_trivial for m in ds.mappers], bool)
    bundles = plan_bundles(sample_binned, nbins, dbins, ok, max_bin=max_bin,
                           max_conflict_rate=max_conflict_rate)
    if not bundles:
        return None
    return build_bundle_info(bundles, nbins, ds.num_total_features)


def _bin_chunk(mappers, arr: np.ndarray, dtype) -> np.ndarray:
    """Bin a raw [K, F] float chunk with fitted mappers."""
    from .binning import bin_columns
    return bin_columns(mappers, arr, dtype)


def _fit_mappers(ds, sample, f, cat_idx, max_bin, min_data_in_bin,
                 use_missing, zero_as_missing, forcedbins_filename,
                 max_bin_by_feature):
    """Fit per-feature BinMappers from a sample (shared by the in-memory
    and streaming construction paths)."""
    total_sample_cnt = len(sample)
    # user-forced bin boundaries, JSON list of {"feature": i,
    # "bin_upper_bound": [...]} (reference: forcedbins_filename,
    # DatasetLoader::GetForcedBins dataset_loader.cpp:1493)
    forced: Dict[int, np.ndarray] = {}
    if forcedbins_filename:
        import json as _json
        with open(forcedbins_filename) as fh:
            for entry in _json.load(fh):
                forced[int(entry["feature"])] = np.asarray(
                    entry["bin_upper_bound"], np.float64)
    if max_bin_by_feature is not None and len(max_bin_by_feature) != f:
        raise ValueError("max_bin_by_feature needs one entry per feature")
    mappers: List[BinMapper] = []
    for j in range(f):
        col = sample[:, j]
        mb = (int(max_bin_by_feature[j])
              if max_bin_by_feature is not None else max_bin)
        if j in cat_idx:
            m = find_bin_categorical(col, mb, min_data_in_bin)
        else:
            m = find_bin_numerical(
                col,
                total_sample_cnt,
                mb,
                min_data_in_bin,
                use_missing=use_missing,
                zero_as_missing=zero_as_missing,
                forced_bounds=forced.get(j),
            )
        mappers.append(m)
    ds.mappers = mappers
    ds.used_features = [j for j, m in enumerate(mappers) if not m.is_trivial]
    if not ds.used_features:
        log.warning("all features are constant; no informative splits "
                    "possible")
    # pad the bin axis to a shape-stable max_bin+1 so the jitted tree
    # grower's compile key doesn't depend on the realized bin counts
    ds.max_num_bins = max(max_bin + 1, 2)


def _apply_bundles(binned, info, ds, max_conflict_rate=1e-4):
    from .efb import bundle_matrix
    dbins = np.array([m.default_bin for m in ds.mappers], np.int32)
    out = bundle_matrix(binned, info, dbins, max_conflict_rate)
    if out is None:
        log.warning("EFB: feature conflict outside the planning sample; "
                    "keeping the dense matrix")
        ds.bundle_info = None
        return binned
    ds.bundle_info = info
    return out


# -- 4-bit dense bin packing (reference: the 4-bit mode of the dense bin
# store, src/io/dense_bin.hpp DenseBin<true>: two bins per byte) -----------
def pack4_eligible(mappers) -> bool:
    """True when every feature's realized bin count fits a nibble, so the
    bin matrix can store two columns per byte (``tpu_bin_pack4``). The
    check is per-ORIGINAL-feature: prediction inputs are binned in
    original feature space, so EFB bundling of the training matrix does
    not affect eligibility. (Training eligibility is the STORED-column
    twin — :func:`pack4_train_eligible`.)"""
    return bool(mappers) and all(m.num_bins <= 16 for m in mappers)


def pack4_train_eligible(stored_num_bins, hist_bins: int) -> bool:
    """Training-side pack4 eligibility (``tpu_bin_pack4`` on the compact
    grower): every STORED column's realized bin count must fit a nibble —
    under EFB that is the bundle-column width, which can exceed the
    members' own bins — and the shape-stable histogram width
    (``max_bin + 1``) must too, because the one-hot compare and the
    routing predicate read nibble values 0..15."""
    nb = np.asarray(stored_num_bins)
    return bool(nb.size) and int(nb.max()) <= 16 and int(hist_bins) <= 16


def pack4_matrix(binned: np.ndarray) -> np.ndarray:
    """[N, F] u8 (all values < 16) -> [N, ceil(F/2)] u8 nibble-packed.

    Column ``2j`` lands in the low nibble of packed column ``j``,
    ``2j+1`` in the high nibble — the layout ops/packed.py unpack4 and
    the predict walk's nibble gather invert. Halves the HBM footprint of
    a served request matrix."""
    if binned.dtype != np.uint8:
        raise ValueError("pack4_matrix needs a uint8 bin matrix")
    if binned.shape[1] % 2:
        binned = np.pad(binned, ((0, 0), (0, 1)))
    return (binned[:, 0::2] | (binned[:, 1::2] << 4)).astype(np.uint8)


def unpack4_matrix(packed: np.ndarray, num_features: int) -> np.ndarray:
    """Host inverse of ``pack4_matrix`` (round-trip tested)."""
    lo = packed & 0x0F
    hi = (packed >> 4) & 0x0F
    out = np.empty((packed.shape[0], packed.shape[1] * 2), np.uint8)
    out[:, 0::2] = lo
    out[:, 1::2] = hi
    return out[:, :num_features]


def _resolve_categorical(
    categorical_feature: Optional[Sequence[Union[int, str]]],
    feature_names: List[str],
) -> set:
    out: set = set()
    if categorical_feature is None or categorical_feature == "auto" or categorical_feature == "":
        return out
    if isinstance(categorical_feature, str):
        categorical_feature = [c.strip() for c in categorical_feature.split(",") if c.strip()]
    for c in categorical_feature:
        if isinstance(c, (int, np.integer)):
            out.add(int(c))
        elif isinstance(c, str):
            if c.startswith("name:"):
                c = c[5:]
            if c in feature_names:
                out.add(feature_names.index(c))
            else:
                try:
                    out.add(int(c))
                except ValueError:
                    log.warning(f"Unknown categorical feature: {c}")
    return out
