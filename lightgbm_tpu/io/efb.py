"""Exclusive Feature Bundling (EFB).

TPU-native re-design of the reference's feature bundling
(reference: FeatureGroup / multi-value bins, include/LightGBM/feature_group.h
and Dataset::Construct's greedy conflict-graph packing, src/io/dataset.cpp —
`FindGroups` / `FastFeatureBundling`). Wide sparse datasets (one-hot blocks
like Allstate's F=4228) have mutually-exclusive features; bundling packs them
into shared columns so histogram work and the [N, F] device matrix scale with
the number of BUNDLES, not raw features.

Encoding (per bundle column): value 0 = every member feature at its default
bin; member feature j with bin b != default stores ``offset_j + 1 + b``.
Offsets reserve each member's FULL bin range (no skip-compaction), so the
bundle-space routing predicate of a split on member j at threshold t is two
range checks:

    in_range = offset_j < v <= offset_j + num_bins_j
    go_left  = (in_range and v - offset_j - 1 <= t) or
               (not in_range and default_bin_j <= t)

Unlike the reference we keep the whole pipeline in bundle space: per-leaf
histogram caches are [n_columns, B] (53x smaller at Allstate shape), the
best-split scan handles member features with tiny gathered sub-scans
(ops/split.py best_bundled_split), and only the model's tree arrays carry
original feature ids / thresholds (so model text and raw-data prediction are
bundling-agnostic).

Bundled features are restricted to numerical, no-NaN (missing none/zero)
mappers; everything else passes through as its own column. Packing allows a
bounded conflict count per bundle (reference: total_sample_cnt/10000,
src/io/dataset.cpp:115) — conflicting rows keep the first-placed member's
value; max_conflict_rate=0 recovers exact conflict-free bundling.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from ..utils import log


class BundleInfo(NamedTuple):
    """Static bundle layout (host-side; device arrays built by the GBDT)."""
    # per ORIGINAL feature
    col_of: np.ndarray        # [F] i32: column in the stored matrix
    offset_of: np.ndarray     # [F] i32: bin offset within the column
    #                           (-1 = passthrough, column stores raw bins)
    # per stored column
    num_column_bins: np.ndarray   # [C] i32 total bins of each stored column
    n_columns: int
    n_bundled: int            # original features living in shared columns

    @property
    def any_bundled(self) -> bool:
        return self.n_bundled > 0


def plan_bundles(
    sample_binned: np.ndarray,      # [S, F] sample rows, already binned
    num_bins: np.ndarray,           # [F] per-feature bin counts
    default_bins: np.ndarray,       # [F] per-feature default (zero) bin
    bundleable: np.ndarray,         # [F] bool: numerical, no-NaN, non-cat
    max_bin: int = 255,
    max_conflict_rate: float = 1e-4,
    min_features: int = 256,
) -> Optional[List[List[int]]]:
    """Greedy bounded-conflict packing of sparse features into bundles.

    Reference: Dataset::Construct FindGroups — greedy graph coloring over
    the feature conflict graph with a per-group conflict budget of
    ``total_sample_cnt / 10000`` and a per-feature cap of half its nonzeros
    (src/io/dataset.cpp:115,163). max_conflict_rate = 0 recovers the exact
    (lossless) conflict-free packing.

    Returns bundles as lists of original feature ids (only multi-member
    bundles), or None when bundling is not worthwhile.
    """
    s, f = sample_binned.shape
    if f < min_features or s == 0:
        return None
    nonzero = sample_binned != default_bins[None, :]      # [S, F]
    counts = nonzero.sum(axis=0)
    density = counts / max(s, 1)
    # candidates: sparse enough that exclusivity is plausible
    cand = np.nonzero(bundleable & (density <= 0.5))[0]
    if len(cand) < min_features:
        return None
    # greedy first-fit by descending nonzero count (reference sorts the same
    # way); conflicts checked against the bundle's combined occupancy
    order = cand[np.argsort(-counts[cand], kind="stable")]
    budget = max_bin  # u8 storage: one column holds at most max_bin+1 values
    conflict_budget = int(s * max_conflict_rate)
    # feature-major f32 copy: the conflict counts against ALL open bundles
    # batch into one BLAS matvec per feature (the bundle-by-bundle bool-AND
    # loop was O(F^2 * S) python-side and dominated wide-data construct)
    nzT = np.ascontiguousarray(nonzero.T[order])               # [J, S] bool
    bundles: List[List[int]] = []
    nb_alloc = 256
    # stop OPENING bundles once the occupancy matrix would pass ~512MB
    # (features past the cap stay unbundled; already-planned bundles keep
    # accepting members)
    nb_cap = max(64, (512 << 20) // (4 * s))
    occ = np.zeros((nb_alloc, s), np.float32)       # [NB, S] occupancy
    used_bins = np.zeros(nb_alloc, np.int64)
    conflicts_used = np.zeros(nb_alloc, np.int64)
    for ji, j in enumerate(order):
        nb = int(num_bins[j])
        nz_j = int(counts[j])
        nbundles = len(bundles)
        placed = False
        if nbundles:
            conflict = occ[:nbundles] @ nzT[ji].astype(np.float32)  # [NB]
            ok = (used_bins[:nbundles] + nb <= budget) & (
                conflict <= np.minimum(
                    conflict_budget - conflicts_used[:nbundles], nz_j // 2))
            hits = np.nonzero(ok)[0]
            if len(hits):
                # first-fit, like the reference's FindGroups scan order
                bi = int(hits[0])
                bundles[bi].append(int(j))
                np.maximum(occ[bi], nzT[ji], out=occ[bi])
                used_bins[bi] += nb
                conflicts_used[bi] += int(conflict[bi])
                placed = True
        if not placed:
            if nbundles >= nb_cap:
                continue
            if nbundles == nb_alloc:
                nb_alloc *= 2
                occ = np.concatenate(
                    [occ, np.zeros((nb_alloc - nbundles, s), np.float32)])
                used_bins = np.concatenate(
                    [used_bins, np.zeros(nbundles, np.int64)])
                conflicts_used = np.concatenate(
                    [conflicts_used, np.zeros(nbundles, np.int64)])
            bundles.append([int(j)])
            occ[nbundles] = nzT[ji]
            used_bins[nbundles] = nb
    bundles = [b for b in bundles if len(b) > 1]
    n_bundled = sum(len(b) for b in bundles)
    if n_bundled < min_features:
        return None
    return bundles


def build_bundle_info(bundles: List[List[int]], num_bins: np.ndarray,
                      f: int) -> BundleInfo:
    """Column layout: passthrough features keep their own columns (in
    original order), bundles follow."""
    in_bundle = np.zeros(f, bool)
    for b in bundles:
        for j in b:
            in_bundle[j] = True
    col_of = np.full(f, -1, np.int32)
    offset_of = np.full(f, -1, np.int32)
    col_bins: List[int] = []
    c = 0
    for j in range(f):
        if not in_bundle[j]:
            col_of[j] = c
            col_bins.append(int(num_bins[j]))
            c += 1
    for b in bundles:
        off = 0
        for j in b:
            col_of[j] = c
            offset_of[j] = off
            off += int(num_bins[j])
        col_bins.append(off + 1)          # +1: the all-default value 0
        c += 1
    return BundleInfo(
        col_of=col_of, offset_of=offset_of,
        num_column_bins=np.asarray(col_bins, np.int32),
        n_columns=c, n_bundled=int(in_bundle.sum()))


def unbundle(bundled: np.ndarray, info: BundleInfo, default_bins: np.ndarray,
             num_bins: np.ndarray) -> np.ndarray:
    """Inverse of bundle_matrix: reconstruct the dense [N, F] binned
    matrix. The graceful fallback when a bundled dataset meets a learner
    configuration the bundle-space growers don't support. Exact for
    conflict-free plans; under bounded-conflict bundling, rows that lost a
    member's bin to a conflict come back at that member's default bin (the
    same information loss the reference accepts)."""
    n = bundled.shape[0]
    f = len(info.col_of)
    out = np.zeros((n, f), bundled.dtype)
    for j in range(f):
        c = info.col_of[j]
        o = int(info.offset_of[j])
        if o < 0:
            out[:, j] = bundled[:, c]
        else:
            v = bundled[:, c].astype(np.int64)
            col = np.full(n, default_bins[j], np.int64)
            in_r = (v > o) & (v <= o + int(num_bins[j]))
            col[in_r] = v[in_r] - o - 1
            out[:, j] = col.astype(bundled.dtype)
    return out


def bundle_chunk(binned: np.ndarray, info: BundleInfo,
                 default_bins: np.ndarray):
    """Re-encode one [K, F] binned chunk into ([K, n_columns] u8,
    conflict count). Row-local, so streaming construction applies it
    chunk by chunk (reference: PushOneRow per-group push,
    include/LightGBM/feature_group.h).

    Features encode in PLACEMENT order (ascending offset within each
    column) so a conflicting row keeps the FIRST-PLACED member's value,
    matching the planner's conflict accounting and the reference's drop
    order. The whole encode is batched (the construct hot path — the
    scalar loop paid ~6 full-column passes per member feature, which at
    Allstate shape is thousands of passes): passthrough columns move in
    one gather, and bundled members resolve first-writer-wins with a
    segmented ``np.minimum.reduceat`` over the placement-ordered member
    axis — the winner per (row, bundle) is the lowest-ranked member whose
    bin is off-default, exactly the scalar loop's first write."""
    n = binned.shape[0]
    out = np.zeros((n, info.n_columns), np.uint8)
    col_of = np.asarray(info.col_of)
    off_of = np.asarray(info.offset_of)
    pass_j = np.nonzero(off_of < 0)[0]
    if len(pass_j):
        out[:, col_of[pass_j]] = binned[:, pass_j]
    order = np.lexsort((off_of, col_of))
    bund = order[off_of[order] >= 0]          # placement-ordered members
    j_cnt = len(bund)
    if not j_cnt:
        return out, 0
    dflt = default_bins[bund].astype(np.int16)
    offs = off_of[bund].astype(np.int16)
    # contiguous member segments per bundle column (lexsort groups them)
    bcols = col_of[bund]
    seg_starts = np.flatnonzero(np.r_[True, bcols[1:] != bcols[:-1]])
    seg_cols = bcols[seg_starts]
    rank = np.arange(j_cnt, dtype=np.int32)
    conflicts = 0
    # row chunks bound the [R, J] intermediates (~32MB a piece)
    chunk = max(1024, (1 << 25) // j_cnt)
    for r0 in range(0, n, chunk):
        r1 = min(n, r0 + chunk)
        b = binned[r0:r1][:, bund].astype(np.int16)    # [R, J] gather
        enc = offs[None, :] + 1 + b
        emax = int(enc.max(initial=0))
        if emax > 255:
            raise ValueError("bundle exceeded u8 bin budget")
        nz = b != dflt[None, :]
        key = np.where(nz, rank[None, :], j_cnt)
        win = np.minimum.reduceat(key, seg_starts, axis=1)  # [R, n_bcols]
        has = win < j_cnt
        val = np.take_along_axis(enc, np.where(has, win, 0), axis=1)
        out[r0:r1, seg_cols] = np.where(has, val, 0).astype(np.uint8)
        conflicts += int(nz.sum()) - int(has.sum())
    return out, conflicts


def conflict_allowance(info: BundleInfo, n: int,
                       max_conflict_rate: float) -> int:
    """Full-data conflict budget: the planner allowed max_conflict_rate *
    sample rows PER bundle, so grant the same rate over n rows (x4 slack
    for sampling noise). Rate 0 is the lossless contract — ANY conflict
    must fall back to dense."""
    if max_conflict_rate <= 0:
        return 0
    n_bundle_cols = len(
        {int(c) for c, o in zip(info.col_of, info.offset_of) if o >= 0})
    return max(int(4 * max_conflict_rate * n * max(n_bundle_cols, 1)), 16)


def bundle_matrix(binned: np.ndarray, info: BundleInfo,
                  default_bins: np.ndarray,
                  max_conflict_rate: float = 1e-4) -> Optional[np.ndarray]:
    """Re-encode the dense [N, F] binned matrix into [N, n_columns], or None
    when far more conflicts appear than planned (caller keeps dense).

    Conflicting rows (two members nonzero) keep the FIRST-placed member's
    value — the planning order, matching the reference's bounded-conflict
    semantics (a conflicting row simply loses the later feature's bin,
    src/io/dataset.cpp FindGroups). With a conflict-free plan this is exact.

    (When constructing from raw columns the caller can stream feature by
    feature instead of materializing [N, F] first; this dense variant serves
    the in-memory path.)"""
    n = binned.shape[0]
    out, conflicts = bundle_chunk(binned, info, default_bins)
    allowed = conflict_allowance(info, n, max_conflict_rate)
    if conflicts > allowed:
        return None
    if conflicts:
        log.info(f"EFB: {conflicts} conflicting rows on the full data "
                 f"(allowed {allowed})")
    return out
