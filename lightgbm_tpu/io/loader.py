"""Text-file dataset loading: CSV / TSV / LibSVM.

TPU-native counterpart of the reference's DatasetLoader + Parser
(reference: src/io/dataset_loader.cpp LoadFromFile :203, format
auto-detection src/io/parser.cpp — CSV/TSV/LibSVM with an optional header,
label/weight/group columns by index or name). Parsing is host-side numpy;
the result feeds the same BinnedDataset construction as array inputs.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def _detect_format(path: str, line: str) -> str:
    lower = path.lower()
    for ext, fmt in ((".csv", "csv"), (".tsv", "tsv"), (".svm", "libsvm"),
                     (".libsvm", "libsvm")):
        if lower.endswith(ext):
            return fmt
    # auto-detect like the reference Parser::CreateParser: LibSVM tokens
    # look like idx:value
    tokens = line.replace("\t", " ").split()
    if any(":" in t for t in tokens[1:3]):
        return "libsvm"
    return "tsv" if "\t" in line else "csv"


def _parse_column_spec(spec: str, names) -> Optional[int]:
    """'0' or 'name:label_col' column addressing (reference:
    config.h label_column docs)."""
    if spec in ("", None):
        return None
    spec = str(spec)
    if spec.startswith("name:"):
        return list(names).index(spec[5:])
    return int(spec)


def load_text_file(
    path: str,
    has_header: bool = False,
    label_column: str = "0",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray], Optional[list]]:
    """Returns (X, label, weight, group_sizes, feature_names)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path) as f:
        first = f.readline()
    fmt = _detect_format(path, first if not has_header else "")

    if fmt == "libsvm":
        return _load_libsvm(path, has_header)

    delim = "\t" if fmt == "tsv" else ","
    names = None
    skip = 0
    if has_header:
        names = [c.strip() for c in first.strip().split(delim)]
        skip = 1
    raw = np.genfromtxt(path, delimiter=delim, skip_header=skip,
                        dtype=np.float64)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)

    def col_of(spec):
        return _parse_column_spec(spec, names or [])

    label_idx = col_of(label_column)
    weight_idx = col_of(weight_column)
    group_idx = col_of(group_column)
    ignore = set()
    if ignore_column:
        for part in str(ignore_column).split(","):
            idx = col_of(part)
            if idx is not None:
                ignore.add(idx)
    special = {i for i in (label_idx, weight_idx, group_idx)
               if i is not None} | ignore
    feat_cols = [i for i in range(raw.shape[1]) if i not in special]
    X = raw[:, feat_cols]
    label = raw[:, label_idx] if label_idx is not None else None
    weight = raw[:, weight_idx] if weight_idx is not None else None
    group_sizes = None
    if group_idx is not None:
        gid = raw[:, group_idx]
        # consecutive identical group ids -> sizes (reference query files)
        change = np.flatnonzero(np.diff(gid)) + 1
        bounds = np.concatenate([[0], change, [len(gid)]])
        group_sizes = np.diff(bounds)
    feat_names = ([names[i] for i in feat_cols] if names else None)
    return X, label, weight, group_sizes, feat_names


def _load_libsvm(path: str, has_header: bool):
    labels = []
    rows = []
    max_idx = -1
    with open(path) as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                i, _, v = tok.partition(":")
                i = int(i)
                feats[i] = float(v)
                max_idx = max(max_idx, i)
            rows.append(feats)
    x = np.zeros((len(rows), max_idx + 1))
    for r, feats in enumerate(rows):
        for i, v in feats.items():
            x[r, i] = v
    return x, np.asarray(labels), None, None, None


def load_query_file(path: str) -> Optional[np.ndarray]:
    """``<data>.query`` / ``.group`` sidecar with one group size per line
    (reference: Metadata::LoadQueryBoundaries)."""
    for suffix in (".query", ".group"):
        p = path + suffix
        if os.path.exists(p):
            return np.loadtxt(p, dtype=np.int64).reshape(-1)
    return None


def load_weight_file(path: str) -> Optional[np.ndarray]:
    p = path + ".weight"
    if os.path.exists(p):
        return np.loadtxt(p, dtype=np.float64).reshape(-1)
    return None
