"""Text-file dataset loading: CSV / TSV / LibSVM.

TPU-native counterpart of the reference's DatasetLoader + Parser
(reference: src/io/dataset_loader.cpp LoadFromFile :203, format
auto-detection src/io/parser.cpp — CSV/TSV/LibSVM with an optional header,
label/weight/group columns by index or name). Parsing is host-side numpy;
the result feeds the same BinnedDataset construction as array inputs.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def _detect_format(path: str, line: str) -> str:
    lower = path.lower()
    for ext, fmt in ((".csv", "csv"), (".tsv", "tsv"), (".svm", "libsvm"),
                     (".libsvm", "libsvm")):
        if lower.endswith(ext):
            return fmt
    # auto-detect like the reference Parser::CreateParser: LibSVM tokens
    # look like idx:value
    tokens = line.replace("\t", " ").split()
    if any(":" in t for t in tokens[1:3]):
        return "libsvm"
    return "tsv" if "\t" in line else "csv"


def _parse_column_spec(spec: str, names) -> Optional[int]:
    """'0' or 'name:label_col' column addressing (reference:
    config.h label_column docs)."""
    if spec in ("", None):
        return None
    spec = str(spec)
    if spec.startswith("name:"):
        return list(names).index(spec[5:])
    return int(spec)


# -- custom parser plugins (reference: pluggable ParserFactory via
# parser_config_file, src/io/parser.cpp Parser::CreateParser) ----------------
# The reference loads native parser plugins from a shared library named in a
# JSON config file; here plugins are PYTHON callables registered by name —
# the TPU build has no C ABI to load from, and a callable covers the same
# role (turn one text line into (features, label)).
_PARSER_REGISTRY = {}


def register_parser(name: str, fn) -> None:
    """Register a custom line parser: ``fn(line: str) -> (values, label)``
    where ``values`` is a float sequence. Select it with
    ``parser_config_file`` pointing at JSON ``{"className": "<name>"}``
    (the reference's key for its plugin class)."""
    _PARSER_REGISTRY[str(name)] = fn


def _load_with_plugin(path: str, has_header: bool, parser_config_file: str,
                      weight_column: str = "", group_column: str = "",
                      ignore_column: str = ""):
    import json
    with open(parser_config_file) as fh:
        cfg = json.load(fh)
    name = str(cfg.get("className", cfg.get("parser", "")))
    if name not in _PARSER_REGISTRY:
        raise ValueError(
            f"parser_config_file names parser {name!r} but no such parser "
            "is registered; call lightgbm_tpu.register_parser(name, fn)")
    fn = _PARSER_REGISTRY[name]
    xs, ys = [], []
    with open(path) as fh:
        if has_header:
            fh.readline()
        for line in fh:
            line = line.strip()
            if not line:
                continue
            vals, label = fn(line)
            xs.append(np.asarray(vals, np.float64))
            ys.append(np.nan if label is None else float(label))
    X = np.vstack(xs)
    y = np.asarray(ys, np.float64)
    if np.isnan(y).all():
        y = None
    # weight/group/ignore column specs index the PARSED value columns (the
    # reference's plugin parser feeds the normal column pipeline)
    weight = group = None
    drop = []

    def idx_of(spec):
        spec = str(spec).strip()
        if spec == "":
            return None
        if not spec.isdigit():
            # custom parsers produce unnamed columns; name-based (and
            # negative) specs cannot resolve here
            raise ValueError(
                f"column spec {spec!r} is not supported with a custom "
                "parser; use a non-negative 0-based column index")
        return int(spec)

    wi = idx_of(weight_column)
    gi = idx_of(group_column)
    if wi is not None:
        weight = X[:, wi]
        drop.append(wi)
    if gi is not None:
        gid = X[:, gi].astype(np.int64)
        # contiguous query-id column -> group sizes
        change = np.nonzero(np.diff(gid))[0]
        bounds = np.concatenate([[0], change + 1, [len(gid)]])
        group = np.diff(bounds).astype(np.int64)
        drop.append(gi)
    for spec in str(ignore_column).split(","):
        j = idx_of(spec)
        if j is not None:
            drop.append(j)
    if drop:
        keep = [j for j in range(X.shape[1]) if j not in set(drop)]
        X = X[:, keep]
    return X, y, weight, group, None


def load_text_file(
    path: str,
    has_header: bool = False,
    label_column: str = "0",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    parser_config_file: str = "",
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray], Optional[list]]:
    """Returns (X, label, weight, group_sizes, feature_names)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if parser_config_file:
        return _load_with_plugin(path, has_header, parser_config_file,
                                 weight_column, group_column, ignore_column)
    with open(path) as f:
        first = f.readline()
    fmt = _detect_format(path, first if not has_header else "")

    if fmt == "libsvm":
        return _load_libsvm(path, has_header)

    delim = "\t" if fmt == "tsv" else ","
    names = None
    skip = 0
    if has_header:
        names = [c.strip() for c in first.strip().split(delim)]
        skip = 1
    raw = np.genfromtxt(path, delimiter=delim, skip_header=skip,
                        dtype=np.float64)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)

    def col_of(spec):
        return _parse_column_spec(spec, names or [])

    label_idx = col_of(label_column)
    weight_idx = col_of(weight_column)
    group_idx = col_of(group_column)
    ignore = set()
    if ignore_column:
        for part in str(ignore_column).split(","):
            idx = col_of(part)
            if idx is not None:
                ignore.add(idx)
    special = {i for i in (label_idx, weight_idx, group_idx)
               if i is not None} | ignore
    feat_cols = [i for i in range(raw.shape[1]) if i not in special]
    X = raw[:, feat_cols]
    label = raw[:, label_idx] if label_idx is not None else None
    weight = raw[:, weight_idx] if weight_idx is not None else None
    group_sizes = None
    if group_idx is not None:
        gid = raw[:, group_idx]
        # consecutive identical group ids -> sizes (reference query files)
        change = np.flatnonzero(np.diff(gid)) + 1
        bounds = np.concatenate([[0], change, [len(gid)]])
        group_sizes = np.diff(bounds)
    feat_names = ([names[i] for i in feat_cols] if names else None)
    return X, label, weight, group_sizes, feat_names


def _load_libsvm(path: str, has_header: bool):
    labels = []
    rows = []
    max_idx = -1
    with open(path) as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                i, _, v = tok.partition(":")
                i = int(i)
                feats[i] = float(v)
                max_idx = max(max_idx, i)
            rows.append(feats)
    x = np.zeros((len(rows), max_idx + 1))
    for r, feats in enumerate(rows):
        for i, v in feats.items():
            x[r, i] = v
    return x, np.asarray(labels), None, None, None


def load_query_file(path: str) -> Optional[np.ndarray]:
    """``<data>.query`` / ``.group`` sidecar with one group size per line
    (reference: Metadata::LoadQueryBoundaries)."""
    for suffix in (".query", ".group"):
        p = path + suffix
        if os.path.exists(p):
            return np.loadtxt(p, dtype=np.int64).reshape(-1)
    return None


def load_weight_file(path: str) -> Optional[np.ndarray]:
    p = path + ".weight"
    if os.path.exists(p):
        return np.loadtxt(p, dtype=np.float64).reshape(-1)
    return None
