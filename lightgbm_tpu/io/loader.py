"""Text-file dataset loading: CSV / TSV / LibSVM.

TPU-native counterpart of the reference's DatasetLoader + Parser
(reference: src/io/dataset_loader.cpp LoadFromFile :203, format
auto-detection src/io/parser.cpp — CSV/TSV/LibSVM with an optional header,
label/weight/group columns by index or name). Parsing is host-side numpy;
the result feeds the same BinnedDataset construction as array inputs.

``two_round=True`` selects the reference's memory-bounded two-pass mode
(reference: two_round config, DatasetLoader::LoadFromFile's
SampleTextDataFromFile + second parse pass, dataset_loader.cpp:266-330):
the first round scans the file once, recording per-row byte offsets and
the tiny per-row metadata columns (label/weight/group); the second round
is on-demand — a ``Sequence`` over the recorded offsets feeds the
streaming ``BinnedDataset.construct_from_sequences`` path, so the dense
``[N, F]`` float64 matrix is never materialized (peak memory = packed bin
matrix + one parse batch + 8 bytes/row of offsets and 8 bytes/row per
requested metadata column — compact ``array`` buffers, not Python lists).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..basic import Sequence


def _detect_format(path: str, line: str) -> str:
    lower = path.lower()
    for ext, fmt in ((".csv", "csv"), (".tsv", "tsv"), (".svm", "libsvm"),
                     (".libsvm", "libsvm")):
        if lower.endswith(ext):
            return fmt
    # auto-detect like the reference Parser::CreateParser: LibSVM tokens
    # look like idx:value
    tokens = line.replace("\t", " ").split()
    if any(":" in t for t in tokens[1:3]):
        return "libsvm"
    return "tsv" if "\t" in line else "csv"


def _cell_float(v: str) -> float:
    """One metadata cell -> float; empty/unparsable cells are NaN (the
    one-round loader's ``np.genfromtxt`` semantics)."""
    v = v.strip()
    if not v:
        return float("nan")
    try:
        return float(v)
    except ValueError:
        return float("nan")


def _group_sizes_from_ids(gid: np.ndarray) -> np.ndarray:
    """Consecutive identical group ids -> group sizes (reference query
    files; shared by the one-round, two-round, and plugin loaders)."""
    change = np.flatnonzero(np.diff(gid)) + 1
    bounds = np.concatenate([[0], change, [len(gid)]])
    return np.diff(bounds)


def _parse_column_spec(spec: str, names) -> Optional[int]:
    """'0' or 'name:label_col' column addressing (reference:
    config.h label_column docs)."""
    if spec in ("", None):
        return None
    spec = str(spec)
    if spec.startswith("name:"):
        return list(names).index(spec[5:])
    return int(spec)


# -- custom parser plugins (reference: pluggable ParserFactory via
# parser_config_file, src/io/parser.cpp Parser::CreateParser) ----------------
# The reference loads native parser plugins from a shared library named in a
# JSON config file; here plugins are PYTHON callables registered by name —
# the TPU build has no C ABI to load from, and a callable covers the same
# role (turn one text line into (features, label)).
_PARSER_REGISTRY = {}


def register_parser(name: str, fn) -> None:
    """Register a custom line parser: ``fn(line: str) -> (values, label)``
    where ``values`` is a float sequence. Select it with
    ``parser_config_file`` pointing at JSON ``{"className": "<name>"}``
    (the reference's key for its plugin class)."""
    _PARSER_REGISTRY[str(name)] = fn


def _load_with_plugin(path: str, has_header: bool, parser_config_file: str,
                      weight_column: str = "", group_column: str = "",
                      ignore_column: str = ""):
    import json
    with open(parser_config_file) as fh:
        cfg = json.load(fh)
    name = str(cfg.get("className", cfg.get("parser", "")))
    if name not in _PARSER_REGISTRY:
        raise ValueError(
            f"parser_config_file names parser {name!r} but no such parser "
            "is registered; call lightgbm_tpu.register_parser(name, fn)")
    fn = _PARSER_REGISTRY[name]
    xs, ys = [], []
    with open(path) as fh:
        if has_header:
            fh.readline()
        for line in fh:
            line = line.strip()
            if not line:
                continue
            vals, label = fn(line)
            xs.append(np.asarray(vals, np.float64))
            ys.append(np.nan if label is None else float(label))
    X = np.vstack(xs)
    y = np.asarray(ys, np.float64)
    if np.isnan(y).all():
        y = None
    # weight/group/ignore column specs index the PARSED value columns (the
    # reference's plugin parser feeds the normal column pipeline)
    weight = group = None
    drop = []

    def idx_of(spec):
        spec = str(spec).strip()
        if spec == "":
            return None
        if not spec.isdigit():
            # custom parsers produce unnamed columns; name-based (and
            # negative) specs cannot resolve here
            raise ValueError(
                f"column spec {spec!r} is not supported with a custom "
                "parser; use a non-negative 0-based column index")
        return int(spec)

    wi = idx_of(weight_column)
    gi = idx_of(group_column)
    if wi is not None:
        weight = X[:, wi]
        drop.append(wi)
    if gi is not None:
        # contiguous query-id column -> group sizes
        group = _group_sizes_from_ids(
            X[:, gi].astype(np.int64)).astype(np.int64)
        drop.append(gi)
    for spec in str(ignore_column).split(","):
        j = idx_of(spec)
        if j is not None:
            drop.append(j)
    if drop:
        keep = [j for j in range(X.shape[1]) if j not in set(drop)]
        X = X[:, keep]
    return X, y, weight, group, None


class TextFileSequence(Sequence):
    """Random-access second-round view of a CSV/TSV file.

    A ``lightgbm_tpu.Sequence``, so ``Dataset`` routes it through the
    streaming construction path: ``__getitem__`` seeks to the recorded
    byte offsets and parses only the requested rows, so batch reads
    during streaming construction are one contiguous file read each.
    """

    batch_size = 4096

    def __init__(self, path: str, offsets: np.ndarray, feat_cols,
                 delim: str):
        self.path = path
        self._offsets = offsets          # [N + 1] byte offsets (int64)
        self._feat_cols = list(feat_cols)
        self._delim = delim

    def __len__(self):
        return len(self._offsets) - 1

    def _parse_rows(self, start: int, stop: int) -> np.ndarray:
        with open(self.path, "rb") as fh:
            fh.seek(int(self._offsets[start]))
            blob = fh.read(int(self._offsets[stop] - self._offsets[start]))
        lines = blob.decode().splitlines()
        out = np.empty((stop - start, len(self._feat_cols)), np.float64)
        for r, line in enumerate(ln for ln in lines if ln.strip()):
            vals = line.split(self._delim)
            for c, j in enumerate(self._feat_cols):
                # same tolerance as the one-round loader's genfromtxt:
                # empty/junk cells are NaN, never a parse crash
                out[r, c] = _cell_float(vals[j]) if j < len(vals) \
                    else np.nan
        return out

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            rng = range(*idx.indices(len(self)))
            if not rng:
                return np.empty((0, len(self._feat_cols)), np.float64)
            if rng.step == 1:
                return self._parse_rows(rng.start, rng.stop)
            lo, hi = min(rng), max(rng) + 1
            rows = self._parse_rows(lo, hi)
            return rows[[i - lo for i in rng]]
        i = int(idx)
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(f"row {idx} out of range for {len(self)} rows")
        return self._parse_rows(i, i + 1)[0]


def _two_round_load(path, fmt, has_header, label_column, weight_column,
                    group_column, ignore_column):
    """First round: one streaming scan recording per-row byte offsets and
    the scalar metadata columns. Returns the Sequence + metadata."""
    from array import array
    delim = "\t" if fmt == "tsv" else ","
    offsets = array("q", [0])          # compact 8-byte/row buffers: the
    label_v = array("d")               # first pass must stay memory-bounded
    weight_v = array("d")              # at 100M-row files, not grow Python
    group_v = array("d")               # object lists
    names = None
    with open(path, "rb") as fh:
        pos = 0
        first = True
        label_idx = weight_idx = group_idx = None
        ignore = set()
        n_cols = None
        for raw in fh:
            pos += len(raw)
            line = raw.decode().strip()
            if first and has_header:
                names = [c.strip() for c in line.split(delim)]
                offsets[0] = pos
                first = False
                continue
            first = False
            if not line:
                offsets[-1] = pos
                continue
            vals = line.split(delim)
            if n_cols is None:
                n_cols = len(vals)

                def col_of(spec):
                    return _parse_column_spec(spec, names or [])

                label_idx = col_of(label_column)
                weight_idx = col_of(weight_column)
                group_idx = col_of(group_column)
                if ignore_column:
                    for part in str(ignore_column).split(","):
                        j = col_of(part)
                        if j is not None:
                            ignore.add(j)
            if label_idx is not None:
                label_v.append(_cell_float(vals[label_idx]))
            if weight_idx is not None:
                weight_v.append(_cell_float(vals[weight_idx]))
            if group_idx is not None:
                group_v.append(_cell_float(vals[group_idx]))
            offsets.append(pos)
    special = {i for i in (label_idx, weight_idx, group_idx)
               if i is not None} | ignore
    feat_cols = [i for i in range(n_cols or 0) if i not in special]
    seq = TextFileSequence(path, np.asarray(offsets, np.int64), feat_cols,
                           delim)
    label = np.asarray(label_v) if len(label_v) else None
    weight = np.asarray(weight_v) if len(weight_v) else None
    group_sizes = (_group_sizes_from_ids(np.asarray(group_v))
                   if len(group_v) else None)
    feat_names = ([names[i] for i in feat_cols] if names else None)
    return seq, label, weight, group_sizes, feat_names


def load_text_file(
    path: str,
    has_header: bool = False,
    label_column: str = "0",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    parser_config_file: str = "",
    two_round: bool = False,
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray], Optional[list]]:
    """Returns (X, label, weight, group_sizes, feature_names); with
    ``two_round=True`` X is a :class:`TextFileSequence` instead of a dense
    matrix (see the module docstring)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if parser_config_file:
        return _load_with_plugin(path, has_header, parser_config_file,
                                 weight_column, group_column, ignore_column)
    with open(path) as f:
        first = f.readline()
    fmt = _detect_format(path, first if not has_header else "")

    if two_round:
        if fmt == "libsvm":
            import warnings
            warnings.warn("two_round=true is implemented for CSV/TSV; "
                          "LibSVM files load in one round")
        else:
            return _two_round_load(path, fmt, has_header, label_column,
                                   weight_column, group_column,
                                   ignore_column)

    if fmt == "libsvm":
        return _load_libsvm(path, has_header)

    delim = "\t" if fmt == "tsv" else ","
    names = None
    skip = 0
    if has_header:
        names = [c.strip() for c in first.strip().split(delim)]
        skip = 1
    raw = np.genfromtxt(path, delimiter=delim, skip_header=skip,
                        dtype=np.float64)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)

    def col_of(spec):
        return _parse_column_spec(spec, names or [])

    label_idx = col_of(label_column)
    weight_idx = col_of(weight_column)
    group_idx = col_of(group_column)
    ignore = set()
    if ignore_column:
        for part in str(ignore_column).split(","):
            idx = col_of(part)
            if idx is not None:
                ignore.add(idx)
    special = {i for i in (label_idx, weight_idx, group_idx)
               if i is not None} | ignore
    feat_cols = [i for i in range(raw.shape[1]) if i not in special]
    X = raw[:, feat_cols]
    label = raw[:, label_idx] if label_idx is not None else None
    weight = raw[:, weight_idx] if weight_idx is not None else None
    group_sizes = None
    if group_idx is not None:
        group_sizes = _group_sizes_from_ids(raw[:, group_idx])
    feat_names = ([names[i] for i in feat_cols] if names else None)
    return X, label, weight, group_sizes, feat_names


def _load_libsvm(path: str, has_header: bool):
    labels = []
    rows = []
    max_idx = -1
    with open(path) as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                i, _, v = tok.partition(":")
                i = int(i)
                feats[i] = float(v)
                max_idx = max(max_idx, i)
            rows.append(feats)
    x = np.zeros((len(rows), max_idx + 1))
    for r, feats in enumerate(rows):
        for i, v in feats.items():
            x[r, i] = v
    return x, np.asarray(labels), None, None, None


def load_query_file(path: str) -> Optional[np.ndarray]:
    """``<data>.query`` / ``.group`` sidecar with one group size per line
    (reference: Metadata::LoadQueryBoundaries)."""
    for suffix in (".query", ".group"):
        p = path + suffix
        if os.path.exists(p):
            return np.loadtxt(p, dtype=np.int64).reshape(-1)
    return None


def load_weight_file(path: str) -> Optional[np.ndarray]:
    p = path + ".weight"
    if os.path.exists(p):
        return np.loadtxt(p, dtype=np.float64).reshape(-1)
    return None
