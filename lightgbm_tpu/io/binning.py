"""Feature discretization (binning) for lightgbm_tpu.

TPU-native re-design of the reference's ``BinMapper``
(reference: include/LightGBM/bin.h:85, src/io/bin.cpp — ``BinMapper::FindBin``
bin.cpp:311, ``GreedyFindBin`` bin.cpp:78, ``FindBinWithZeroAsOneBin`` bin.cpp:242).

Key semantics preserved:
  * greedy count-balanced binning over sampled distinct values, heavy values get
    dedicated bins, ``min_data_in_bin`` merging for low-cardinality features;
  * zero is guaranteed its own bin (the reference's zero-as-one-bin behavior,
    kZeroThreshold = 1e-35);
  * missing handling: MissingType None / Zero / NaN; with NaN the last bin is the
    missing bin; with zero_as_missing, NaN joins the zero bin;
  * categorical features: categories sorted by descending sample count get bins
    1..K; unseen / missing values map to bin 0.

Unlike the reference there is no sparse/dense bin storage split: the binned matrix
is a dense ``uint8``/``uint16`` ``[N, F]`` array destined for TPU HBM, where dense
layout feeds the histogram matmul kernels (see ops/histogram.py).
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log

K_ZERO_THRESHOLD = 1e-35

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

_MISSING_TYPE_NAMES = {MISSING_NONE: "none", MISSING_ZERO: "zero", MISSING_NAN: "nan"}


def _greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Count-balanced greedy binning over sorted distinct values.

    Returns the list of bin upper bounds (last is +inf). Mirrors the behavior of
    the reference's GreedyFindBin (src/io/bin.cpp:78) without copying it: when the
    number of distinct values fits in ``max_bin``, each value gets its own bin
    (merging neighbors until ``min_data_in_bin`` is met); otherwise bins are grown
    greedily to ~equal counts, with values heavier than the mean bin size given
    dedicated bins.
    """
    n = len(distinct_values)
    if n == 0:
        return [float("inf")]
    upper: List[float] = []
    if n <= max_bin:
        cnt_in_bin = 0
        for i in range(n - 1):
            cnt_in_bin += int(counts[i])
            if cnt_in_bin >= min_data_in_bin:
                upper.append(float(distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cnt_in_bin = 0
        upper.append(float("inf"))
        return upper
    # too many distinct values: greedy count balancing
    eff_max_bin = max_bin
    if min_data_in_bin > 0:
        eff_max_bin = min(max_bin, max(1, total_sample_cnt // min_data_in_bin))
    mean_size = total_sample_cnt / eff_max_bin
    is_big = counts >= mean_size
    rest_cnt = total_sample_cnt - int(counts[is_big].sum())
    rest_bins = eff_max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_rest = rest_cnt / rest_bins
    else:
        mean_rest = float("inf")
    cur_cnt = 0
    bins_remaining = eff_max_bin
    for i in range(n - 1):
        if not is_big[i]:
            rest_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        # close the current bin if: value is heavy, bin is full, or next value is heavy
        if is_big[i] or cur_cnt >= mean_rest or (is_big[i + 1] and cur_cnt >= max(1.0, mean_rest * 0.5)):
            upper.append(float(distinct_values[i] + distinct_values[i + 1]) / 2.0)
            cur_cnt = 0
            bins_remaining -= 1
            if bins_remaining <= 1:
                break
            if not is_big[i] and rest_bins > int(is_big[i + 1 :].sum()):
                rb = bins_remaining - int(is_big[i + 1 :].sum())
                if rb > 0:
                    mean_rest = rest_cnt / rb
    upper.append(float("inf"))
    # dedupe (midpoints can collide for adjacent near-equal values)
    out: List[float] = []
    for u in upper:
        if not out or u > out[-1]:
            out.append(u)
    return out


@dataclass
class BinMapper:
    """Per-feature value -> bin mapping (reference: BinMapper, bin.h:85)."""

    num_bins: int = 1
    is_categorical: bool = False
    missing_type: int = MISSING_NONE
    # numerical
    bin_upper_bounds: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    # categorical: category value (int) -> bin
    cat_to_bin: Dict[int, int] = field(default_factory=dict)
    bin_to_cat: np.ndarray = field(default_factory=lambda: np.array([], dtype=np.int64))
    default_bin: int = 0       # bin of value 0.0 (numerical) / missing bin (categorical)
    min_value: float = 0.0
    max_value: float = 0.0

    @property
    def is_trivial(self) -> bool:
        return self.num_bins <= 1

    @property
    def nan_bin(self) -> int:
        """Bin that NaN values map to."""
        if self.is_categorical:
            return 0
        if self.missing_type == MISSING_NAN:
            return self.num_bins - 1
        if self.missing_type == MISSING_ZERO:
            return self.default_bin
        return self.default_bin

    def _cat_lookup(self) -> Tuple[np.ndarray, np.ndarray]:
        """Sorted (category, bin) arrays for vectorized categorical
        mapping — rebuilt on demand because cat_to_bin is the serialized
        form (construction-time dicts stay the source of truth)."""
        keys = np.fromiter(self.cat_to_bin.keys(), np.int64,
                           len(self.cat_to_bin))
        vals = np.fromiter(self.cat_to_bin.values(), np.int32,
                           len(self.cat_to_bin))
        order = np.argsort(keys)
        return keys[order], vals[order]

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value -> bin (reference: NumericalBin ValueToBin).

        float32 input stays float32: the upcast in each comparison against
        the float64 bounds is exact, so bins match the float64 path
        bit-for-bit without materializing a promoted copy (the
        dataset-construction hot path feeds 10M-row columns through
        here — see also ``bin_columns`` for the multi-column form)."""
        values = np.asarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = values.astype(np.float64)
        if self.is_categorical:
            out = np.zeros(values.shape, dtype=np.int32)
            finite = np.isfinite(values)
            iv = values[finite].astype(np.int64)
            if len(self.cat_to_bin) and len(iv):
                # batched sorted-array lookup instead of a per-value
                # Python dict probe (the construct-time hot path)
                keys, vals = self._cat_lookup()
                pos = np.searchsorted(keys, iv)
                pos = np.minimum(pos, len(keys) - 1)
                hit = keys[pos] == iv
                out[finite] = np.where(hit, vals[pos], 0)
            return out
        nan_mask = np.isnan(values)
        v = np.where(nan_mask, 0.0, values)
        if self.missing_type == MISSING_ZERO:
            # missing (NaN) behaves like zero
            pass
        n_numeric_bins = self.num_bins - (1 if self.missing_type == MISSING_NAN else 0)
        # first upper bound >= value
        bins = np.searchsorted(self.bin_upper_bounds[: n_numeric_bins - 1], v, side="left")
        bins = bins.astype(np.int32)
        if self.missing_type == MISSING_NAN:
            bins[nan_mask] = self.num_bins - 1
        else:
            bins[nan_mask] = self.nan_bin
        return bins

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Real-valued split threshold for ``bin <= bin_idx`` (used for model export /
        raw-value prediction; reference stores both threshold_in_bin and threshold)."""
        if self.is_categorical:
            raise ValueError("categorical bins have no scalar threshold")
        thr = float(self.bin_upper_bounds[bin_idx])
        # splitting at the last numeric bin separates NaN rows only; the
        # reference clamps +inf thresholds (Common::AvoidInf) the same way
        return min(thr, 1e308)


def find_bin_numerical(
    sample_values: np.ndarray,
    total_sample_cnt: int,
    max_bin: int,
    min_data_in_bin: int = 3,
    use_missing: bool = True,
    zero_as_missing: bool = False,
    pre_filter_min_data: int = 0,
    forced_bounds: "Optional[np.ndarray]" = None,
) -> BinMapper:
    """Construct a numerical BinMapper from sampled values.

    ``sample_values`` may contain NaN. ``total_sample_cnt`` includes rows whose
    value was zero and therefore may exceed ``len(sample_values)`` in sparse
    ingestion paths (reference semantics: zeros counted implicitly).
    """
    if forced_bounds is not None and len(forced_bounds):
        # user-specified boundaries take priority; the greedy budget shrinks
        # (reference: forced_bin_bounds in bin.cpp FindBin). The inner fit
        # sees the ORIGINAL values so NaN missing handling is preserved.
        m = _find_bin_with_forced(sample_values, total_sample_cnt, max_bin,
                                  min_data_in_bin, use_missing,
                                  zero_as_missing,
                                  np.asarray(forced_bounds, np.float64))
        if m is not None:
            return m
    values = np.asarray(sample_values, dtype=np.float64)
    nan_cnt = int(np.isnan(values).sum())
    values = values[~np.isnan(values)]

    if zero_as_missing:
        missing_type = MISSING_ZERO
        zero_is_missing = True
    elif nan_cnt > 0 and use_missing:
        missing_type = MISSING_NAN
        zero_is_missing = False
    else:
        missing_type = MISSING_NONE
        zero_is_missing = False

    # zero-as-one-bin: bin negative and positive parts separately, keep a
    # dedicated zero bin between them (reference: FindBinWithZeroAsOneBin).
    zero_cnt = int((np.abs(values) <= K_ZERO_THRESHOLD).sum())
    # implicit zeros (sparse ingestion): rows not materialized in the sample
    zero_cnt += max(0, total_sample_cnt - len(values) - nan_cnt)
    neg = values[values < -K_ZERO_THRESHOLD]
    pos = values[values > K_ZERO_THRESHOLD]
    n_nonzero = len(neg) + len(pos)

    n_avail_bins = max_bin - (1 if missing_type == MISSING_NAN else 0)
    # reserve one bin for zero
    n_nonzero_bins = max(1, n_avail_bins - 1)

    uppers: List[float] = []
    if n_nonzero > 0:
        if len(neg) > 0 and len(pos) > 0:
            neg_bins = max(1, int(round(n_nonzero_bins * len(neg) / n_nonzero)))
            pos_bins = max(1, n_nonzero_bins - neg_bins)
        elif len(neg) > 0:
            neg_bins, pos_bins = n_nonzero_bins, 0
        else:
            neg_bins, pos_bins = 0, n_nonzero_bins
        if len(neg) > 0:
            dv, cnts = np.unique(neg, return_counts=True)
            u = _greedy_find_bin(dv, cnts, neg_bins, len(neg), min_data_in_bin)
            uppers.extend(u[:-1])  # drop the +inf terminator
            uppers.append(-K_ZERO_THRESHOLD)
        else:
            uppers.append(-K_ZERO_THRESHOLD)
        if len(pos) > 0:
            uppers.append(K_ZERO_THRESHOLD)
            dv, cnts = np.unique(pos, return_counts=True)
            u = _greedy_find_bin(dv, cnts, pos_bins, len(pos), min_data_in_bin)
            uppers.extend(u)
        else:
            uppers.append(np.inf)
    else:
        uppers = [np.inf]

    # dedupe & sort
    uppers = sorted(set(float(u) for u in uppers))
    upper_arr = np.array(uppers, dtype=np.float64)
    num_numeric_bins = len(upper_arr)
    # drop the zero-side bin if there were no zeros at all and it is redundant
    num_bins = num_numeric_bins + (1 if missing_type == MISSING_NAN else 0)

    if num_bins <= 1 or (num_numeric_bins <= 1 and missing_type != MISSING_NAN):
        # trivial feature
        if not (missing_type == MISSING_NAN and num_numeric_bins >= 1 and nan_cnt > 0 and n_nonzero + zero_cnt > 0):
            mapper = BinMapper(num_bins=1, missing_type=MISSING_NONE)
            return mapper

    mapper = BinMapper(
        num_bins=num_bins,
        is_categorical=False,
        missing_type=missing_type,
        bin_upper_bounds=upper_arr,
    )
    if len(values) > 0:
        mapper.min_value = float(values.min()) if len(values) else 0.0
        mapper.max_value = float(values.max()) if len(values) else 0.0
    # default bin = bin of 0.0
    mapper.default_bin = int(np.searchsorted(upper_arr[:-1], 0.0, side="left"))
    return mapper


def _find_bin_with_forced(values, total_sample_cnt, max_bin, min_data_in_bin,
                          use_missing, zero_as_missing,
                          forced) -> Optional[BinMapper]:
    """Greedy binning constrained to include the user's boundaries."""
    forced = np.unique(forced)
    if len(forced) == 0:
        return None
    # budget left for greedy refinement after reserving forced boundaries
    base = find_bin_numerical(values, total_sample_cnt,
                              max(max_bin - len(forced), 2),
                              min_data_in_bin, use_missing, zero_as_missing)
    finite = base.bin_upper_bounds[np.isfinite(base.bin_upper_bounds)]
    forced = forced[: max_bin - 1]           # user bounds take priority
    budget = max_bin - 1 - len(forced)
    leftover = np.setdiff1d(finite, forced)
    if budget <= 0:
        greedy = leftover[:0]
    elif len(leftover) > budget:
        # keep the base mapper's resolution profile: sample the complement at
        # evenly spaced positions — the sorted prefix would concentrate every
        # remaining bin at the low end of the feature range
        # len(leftover) > budget makes the linspace spacing strictly > 1, so
        # consecutive rounded indices are always distinct — exactly `budget`
        # bounds survive (no collision top-up needed)
        pick = np.linspace(0, len(leftover) - 1, budget).round().astype(int)
        greedy = leftover[np.unique(pick)]
    else:
        greedy = leftover
    bounds = np.sort(np.concatenate([forced, greedy]))
    m = BinMapper(
        num_bins=len(bounds) + 1 + (1 if base.missing_type == MISSING_NAN
                                    else 0),
        is_categorical=False,
        missing_type=base.missing_type,
        bin_upper_bounds=np.concatenate([bounds, [np.inf]]),
        min_value=base.min_value,
        max_value=base.max_value,
    )
    m.default_bin = int(m.value_to_bin(np.array([0.0]))[0])
    return m


# -- serving featurize state export (ops/device_bin.py consumes this) -------

#: int32 sentinel that can never equal a served categorical code (the
#: device lookup pads its key table with it)
CAT_PAD = np.int32(np.iinfo(np.int32).min)


def round_down_f32(bounds: np.ndarray) -> np.ndarray:
    """Largest float32 <= each float64 bound.

    The device featurizer compares float32 request values against
    float32 thresholds; for a float32 value ``v`` and float64 bound
    ``b``, ``v > b`` (exact, in float64) holds iff ``v > t`` where ``t``
    is the largest float32 <= ``b`` — so binning float32 requests on
    device is bit-identical to the host ``bin_columns`` path, which
    upcasts each comparison to float64. (+/-inf map to themselves /
    +/-float32-max correctly: a bound beyond float32 range keeps the
    comparison outcome for every float32 value.)"""
    b = np.asarray(bounds, np.float64)
    t = b.astype(np.float32)
    over = t.astype(np.float64) > b          # rounded UP past the bound
    if over.any():
        t = t.copy()
        t[over] = np.nextafter(t[over], np.float32(-np.inf))
    return t


@dataclass
class FeaturizeState:
    """Per-feature binning state stacked into dense arrays, built once at
    deploy/warm time so a serving tick's raw->binned featurization can
    run as ONE device program (the reference caches exactly this state in
    its single-row fast path — ``SingleRowPredictor`` + ``FastConfig``,
    src/c_api.cpp:117). ``reason`` is non-None when the model cannot take
    the device featurizer (callers fall back to host ``bin_columns``)."""

    bounds32: np.ndarray        # [F, Kb] f32 round-down thresholds, +inf pad
    nan_bins: np.ndarray        # [F] i32 (0 for trivial features)
    is_cat: np.ndarray          # [F] bool
    cat_keys: np.ndarray        # [F, Kc] i32 sorted, CAT_PAD padded
    cat_vals: np.ndarray        # [F, Kc] i32, 0 padded
    reason: Optional[str] = None


def export_featurize_state(mappers: Sequence[BinMapper]) -> FeaturizeState:
    """Stack fitted per-feature mappers for the device featurizer.

    Numerical features keep their interior upper bounds (exactly the
    array ``value_to_bin``/``bin_columns`` search) as round-down float32
    thresholds padded to a common width with +inf (padding never counts:
    no float32 value exceeds +inf). Categorical features keep their
    sorted (code, bin) tables padded with a sentinel key. A model whose
    categorical codes overflow int32 cannot be looked up on a
    float32/int32 device path; the state then carries a ``reason`` and
    serving stays on the host binner."""
    f = len(mappers)
    num_bounds = [_interior_bounds(m) if not (m.is_trivial or m.is_categorical)
                  else np.empty(0) for m in mappers]
    kb = max((len(b) for b in num_bounds), default=0)
    bounds32 = np.full((f, max(kb, 1)), np.inf, np.float32)
    for j, b in enumerate(num_bounds):
        if len(b):
            bounds32[j, : len(b)] = round_down_f32(b)
    nan_bins = np.array([0 if m.is_trivial else m.nan_bin for m in mappers],
                        np.int32)
    is_cat = np.array([m.is_categorical and not m.is_trivial
                       for m in mappers], bool)
    cat_tables = []
    reason = None
    for j, m in enumerate(mappers):
        if is_cat[j] and len(m.cat_to_bin):
            keys, vals = m._cat_lookup()
            if keys.size and (keys.max() > np.iinfo(np.int32).max
                              or keys.min() < np.iinfo(np.int32).min + 1):
                reason = (f"categorical feature {j} has codes outside "
                          "int32; device featurization cannot represent "
                          "its lookup keys")
            cat_tables.append((j, keys, vals))
    kc = max((len(k) for _, k, _ in cat_tables), default=0)
    cat_keys = np.full((f, max(kc, 1)), CAT_PAD, np.int32)
    cat_vals = np.zeros((f, max(kc, 1)), np.int32)
    if reason is None:
        for j, keys, vals in cat_tables:
            cat_keys[j, : len(keys)] = keys.astype(np.int32)
            cat_vals[j, : len(vals)] = vals.astype(np.int32)
    return FeaturizeState(bounds32, nan_bins, is_cat, cat_keys, cat_vals,
                          reason)


# host featurize call counter: the serving steady-state guard asserts the
# device-featurize path does NO per-tick host binning work (tests read
# host_featurize_calls() around a traffic window). Locked: bin_columns is
# callable from concurrent serving/construct threads and a torn
# read-modify-write would let the guard under-count
_HOST_CALLS = 0
_HOST_CALLS_MU = threading.Lock()


def host_featurize_calls() -> int:
    with _HOST_CALLS_MU:
        return _HOST_CALLS


def bin_occupancy(binned: np.ndarray, mappers: Sequence[BinMapper],
                  bundle_info=None) -> Tuple[np.ndarray, np.ndarray]:
    """Per-ORIGINAL-feature bin-occupancy counts of a binned matrix:
    ``(counts [F, B] float64, num_bins [F] int32)`` with ``B`` the widest
    feature's bin count (padded tail stays zero).

    The serving drift monitor's reference distribution (ISSUE 14): live
    traffic is binned in original feature space, so the training-data
    occupancy must be too. For an EFB-bundled matrix each member feature
    reads its reserved ``[offset+1, offset+num_bins]`` range back out of
    its bundle column's histogram — the encode stores ``offset + 1 + b``
    for every non-default bin and 0 for all-defaults (io/efb.py), so the
    member's default-bin count is ``N - sum(non-default)``. Exact for
    conflict-free bundles; under bounded-conflict bundling a conflicting
    row counts at the losing member's default bin, the same information
    loss ``efb.unbundle`` accepts (bounded by ``max_conflict_rate``)."""
    n, f = binned.shape[0], len(mappers)
    nb = np.array([m.num_bins for m in mappers], np.int32)
    width = int(nb.max(initial=1))
    counts = np.zeros((f, width), np.float64)
    colhist = [np.bincount(binned[:, c].astype(np.int64, copy=False))
               for c in range(binned.shape[1])]
    for j, m in enumerate(mappers):
        w = int(nb[j])
        if bundle_info is None:
            c, off = j, -1
        else:
            c, off = int(bundle_info.col_of[j]), int(bundle_info.offset_of[j])
        h = colhist[c]
        if off < 0:
            seg = h[:w]
            counts[j, :len(seg)] = seg
        else:
            seg = h[off + 1: off + 1 + w]
            counts[j, :len(seg)] = seg
            d = int(m.default_bin)
            counts[j, d] = max(n - counts[j].sum(), 0.0)
    return counts, nb


# row-chunk x column-chunk x bounds budget for the batched compare
# (bool intermediates, ~4MB a piece — cache-resident)
_BATCH_ELEMS = 1 << 22
# columns whose interior-bound count fits this go through the batched
# broadcast compare (one vector op per bound for a whole column chunk);
# wider mappers keep per-column np.searchsorted over the same row chunk
_SMALL_BOUNDS = 16


def _interior_bounds(m: BinMapper) -> np.ndarray:
    """The finite upper bounds ``value_to_bin`` searches (excludes the
    trailing +inf terminator and, for MissingType NaN, the missing bin)."""
    n_numeric = m.num_bins - (1 if m.missing_type == MISSING_NAN else 0)
    return m.bin_upper_bounds[: n_numeric - 1]


def bin_columns(mappers: Sequence[BinMapper], arr: np.ndarray,
                dtype=np.uint8, row_chunk: int = 1 << 18,
                workers: Optional[int] = None) -> np.ndarray:
    """Bin a raw ``[N, F]`` float matrix with fitted mappers, batched.

    The dataset-construction hot path (reference: the per-group
    ``Dataset::PushOneRow`` loops, src/io/dataset.cpp). The scalar form —
    one ``value_to_bin`` pass per column over all N rows — pays the NaN
    mask, the missing fill, and the dtype promotion once per column over
    the full column length; at Allstate shape (F=4228) those per-column
    passes dominate construct time. Here the work is blocked the other
    way:

      * rows stream in cache-resident chunks, with ONE ``isnan`` pass per
        chunk shared by every column;
      * columns with few interior bounds (one-hot blocks: 1-2 bounds)
        batch into a single broadcast compare-and-sum per column chunk —
        ``sum(bounds < v)`` is exactly ``np.searchsorted(bounds, v,
        'left')``, with +inf padding rows contributing nothing;
      * columns with many bounds keep per-column ``np.searchsorted`` on
        the row chunk (a 255-bound binary search beats 255 compares);
      * NaN rows overwrite with the per-column nan bin afterwards, the
        same fill ``value_to_bin`` applies;
      * row chunks fan out over a thread pool — numpy's searchsorted and
        comparison ufuncs release the GIL, and each chunk writes a
        disjoint slice of the output, so the host-side construct scales
        with cores instead of running one column at a time.

    float32 input is never promoted to a float64 matrix (each comparison
    upcasts exactly), so results are bit-identical to the scalar path.
    """
    global _HOST_CALLS
    with _HOST_CALLS_MU:
        _HOST_CALLS += 1
    from ..obs.spans import span
    with span("binning"):
        return _bin_columns(mappers, arr, dtype, row_chunk, workers)


def _bin_columns(mappers: Sequence[BinMapper], arr: np.ndarray,
                 dtype=np.uint8, row_chunk: int = 1 << 18,
                 workers: Optional[int] = None) -> np.ndarray:
    arr = np.asarray(arr)
    if arr.dtype not in (np.float32, np.float64):
        arr = arr.astype(np.float64)
    n, f = arr.shape
    out = np.zeros((n, f), dtype)
    live = [j for j in range(f) if not mappers[j].is_trivial]
    if not live:
        return out
    cat_cols = [j for j in live if mappers[j].is_categorical]
    num_cols = [j for j in live if not mappers[j].is_categorical]
    bounds = {j: _interior_bounds(mappers[j]) for j in num_cols}
    nan_bins = np.array([mappers[j].nan_bin if not mappers[j].is_trivial
                         else 0 for j in range(f)], dtype)
    small = sorted((j for j in num_cols if len(bounds[j]) <= _SMALL_BOUNDS),
                   key=lambda j: len(bounds[j]))
    big = [j for j in num_cols if len(bounds[j]) > _SMALL_BOUNDS]

    for j in cat_cols:
        out[:, j] = mappers[j].value_to_bin(arr[:, j]).astype(dtype)

    if workers is None:
        workers = min(16, os.cpu_count() or 1)
    if n * len(live) < (1 << 21):
        workers = 1          # pool overhead beats tiny inputs
    if workers > 1:
        # shrink chunks until every worker has a few to keep busy
        row_chunk = max(4096, min(row_chunk, -(-n // (2 * workers))))

    def _do_chunk(r0: int) -> None:
        r1 = min(n, r0 + row_chunk)
        chunk = arr[r0:r1]
        nan_mask = np.isnan(chunk)
        any_nan = bool(nan_mask.any())
        for j in big:
            v = chunk[:, j]
            if any_nan:
                v = np.where(nan_mask[:, j], 0.0, v)
            b = np.searchsorted(bounds[j], v, side="left").astype(dtype)
            if any_nan:
                b[nan_mask[:, j]] = nan_bins[j]
            out[r0:r1, j] = b
        if not small:
            return
        rows = r1 - r0
        cc = max(1, _BATCH_ELEMS // max(rows * (_SMALL_BOUNDS + 1), 1))
        for c0 in range(0, len(small), cc):
            cols = small[c0:c0 + cc]
            kmax = max(1, max(len(bounds[j]) for j in cols))
            ub = np.full((len(cols), kmax), np.inf)
            for i, j in enumerate(cols):
                ub[i, : len(bounds[j])] = bounds[j]
            v = chunk[:, cols]
            if any_nan:
                v = np.where(nan_mask[:, cols], 0.0, v)
            # sum(bounds < v) == searchsorted(bounds, v, 'left'); the +inf
            # padding never counts, so ragged bound lists batch exactly
            b = (v[:, :, None] > ub[None, :, :]).sum(axis=2).astype(dtype)
            if any_nan:
                b = np.where(nan_mask[:, cols], nan_bins[cols], b)
            out[r0:r1, cols] = b

    starts = list(range(0, n, row_chunk))
    if workers > 1 and len(starts) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(_do_chunk, starts))
    else:
        for r0 in starts:
            _do_chunk(r0)
    return out


def find_bin_categorical(
    sample_values: np.ndarray,
    max_bin: int,
    min_data_in_bin: int = 3,
) -> BinMapper:
    """Construct a categorical BinMapper (reference: BinMapper::FindBin categorical
    branch, src/io/bin.cpp:335-395): categories sorted by descending count, capped
    at ``max_bin - 1`` categories; rare categories (count < min_data_in_bin when
    overflowing) and unseen values fall into bin 0."""
    values = np.asarray(sample_values, dtype=np.float64)
    finite = values[np.isfinite(values)]
    iv = finite.astype(np.int64)
    if (iv < 0).any():
        log.warning("negative categorical value found; treated as missing")
        iv = iv[iv >= 0]
    if len(iv) == 0:
        return BinMapper(num_bins=1, is_categorical=True)
    cats, counts = np.unique(iv, return_counts=True)
    order = np.argsort(-counts, kind="stable")
    cats, counts = cats[order], counts[order]
    keep = min(len(cats), max_bin - 1)
    if keep < len(cats):
        # when overflowing, drop categories below min_data_in_bin
        ok = counts[:keep] >= max(1, min_data_in_bin)
        keep = int(ok.sum()) if ok.any() else 1
    cats = cats[:keep]
    cat_to_bin = {int(c): i + 1 for i, c in enumerate(cats)}
    mapper = BinMapper(
        num_bins=keep + 1,
        is_categorical=True,
        missing_type=MISSING_NAN,
        cat_to_bin=cat_to_bin,
        bin_to_cat=np.concatenate([[-1], cats]).astype(np.int64),
        default_bin=0,
    )
    return mapper
