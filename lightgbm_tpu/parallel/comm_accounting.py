"""Comm-volume accounting: parse the collectives XLA inserted into a
compiled program and sum their output bytes.

The reference budgets its distributed learners by hand-written message
sizes (ReduceScatter of per-feature histograms,
src/treelearner/data_parallel_tree_learner.cpp:223-300; voting-parallel
reduces only the elected top-2k features' histograms,
voting_parallel_tree_learner.cpp). Under GSPMD/shard_map the collectives
are inserted by XLA, so the honest measurement is to read them back out
of the compiled HLO — dryrun_multichip does exactly that and records the
bytes per train step (COMM_ACCOUNTING.json).
"""
from __future__ import annotations

import re
from typing import Dict

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
}

# async forms (-start) are what post-optimization TPU HLO emits; each
# start/done pair counts once (the -done carries no shape of its own here)
_COLLECTIVES = ("all-reduce-start", "all-gather-start",
                "collective-permute-start", "all-reduce", "all-gather",
                "reduce-scatter", "collective-permute", "all-to-all")

# async ops whose transferred payload is the RESULT shape (second element of
# the (operand, result, ...) async tuple): all-gather's result is num_devices
# times the operand, so counting the operand under-reports the gathered bytes
_RESULT_SHAPE_STARTS = ("all-gather-start", "collective-permute-start")

# one shaped tensor, e.g. f32[7,8,64]{2,1,0} — shapes can be scalar []
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")


def _tensor_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Sum output bytes of every collective instruction in compiled HLO.

    Returns {kind: bytes, ..., "total": bytes, "count": n_instructions}.
    """
    out: Dict[str, int] = {k: 0 for k in _COLLECTIVES}
    count = 0
    for line in hlo_text.splitlines():
        s = line.strip()
        if not s.startswith("%") and " = " not in s:
            continue
        lhs, _, rhs = s.partition(" = ")
        kind = next((k for k in _COLLECTIVES
                     if re.search(rf"\s{k}(\.[0-9]+)?\(", rhs)
                     or rhs.startswith(k)), None)
        if kind is None:
            continue
        # output shape(s) come before the op name on the rhs
        head = rhs.split(kind)[0]
        shapes = _SHAPE_RE.findall(head)
        if kind.endswith("-start") and shapes:
            # async tuple output carries (operand, result, ...); count the
            # transferred payload once
            if kind in _RESULT_SHAPE_STARTS:
                # result shape (second tuple element); fall back to the
                # operand if the tuple was flattened to a single shape
                shapes = shapes[1:2] if len(shapes) > 1 else shapes[:1]
            else:
                # all-reduce-start: operand and result shapes are identical
                shapes = shapes[:1]
        nbytes = sum(_tensor_bytes(d, dims) for d, dims in shapes)
        out[kind] += nbytes
        count += 1
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    out["count"] = count
    return out
