"""Comm-volume accounting: parse the collectives XLA inserted into a
compiled program and sum their output bytes.

The reference budgets its distributed learners by hand-written message
sizes (ReduceScatter of per-feature histograms,
src/treelearner/data_parallel_tree_learner.cpp:223-300; voting-parallel
reduces only the elected top-2k features' histograms,
voting_parallel_tree_learner.cpp). Under GSPMD/shard_map the collectives
are inserted by XLA, so the honest measurement is to read them back out
of the compiled HLO — dryrun_multichip does exactly that and records the
bytes per train step (COMM_ACCOUNTING.json).

The HLO text parser lives in :mod:`lightgbm_tpu.analysis.hlo` (shared
with the hlo_check contract verifier); this module keeps the historical
accounting entry point. The inventory includes the async ``-start`` twins
of every collective — ``reduce-scatter-start``/``all-to-all-start``
included, so the ``lax.psum_scatter`` reduction path stays counted the
day post-optimization HLO goes async — with the payload taken from the
result shape (second async-tuple element) where operand and result
differ.
"""
from __future__ import annotations

from ..analysis.hlo import collective_bytes  # noqa: F401

__all__ = ["collective_bytes"]
