"""Device mesh + sharding helpers for distributed training.

TPU-native replacement for the reference's distributed tree learners and
network layer (reference: src/treelearner/data_parallel_tree_learner.cpp —
rows partitioned across machines, histograms ReduceScattered over the
socket/MPI Network, src/network/network.cpp; topology maps linker_topo.cpp).

Here rows are sharded over a ``jax.sharding.Mesh`` axis and the jitted tree
grower runs under GSPMD: XLA partitions the histogram contraction over the row
axis and inserts the AllReduce over ICI automatically — the explicit
Bruck/recursive-halving machinery of the reference's network layer is subsumed
by the XLA collective implementation (SURVEY §2.7). Multi-host extends the same
mesh over DCN via ``jax.distributed.initialize`` (reference equivalent:
machines/machine_list_file config + TCP mesh construction,
linkers_socket.cpp:29-118).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FEAT_AXIS = "feat"


def parse_mesh_shape(spec: str) -> Optional[Tuple[int, ...]]:
    """``tpu_mesh_shape`` strings: ``""``/``"auto"`` (all devices, 1-D),
    ``"8"`` (first 8 devices, 1-D), ``"4x2"`` (2-D: 4-way rows x 2-way
    features). Returns None for the all-devices default."""
    s = str(spec or "").strip().lower()
    if s in ("", "auto", "0"):
        return None
    parts = [p for p in s.replace("*", "x").split("x") if p]
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"tpu_mesh_shape={spec!r}: expected 'N' (1-D row mesh) or "
            "'RxC' (2-D rows x features), e.g. '8' or '4x2'")
    if not dims or len(dims) > 2 or any(d < 1 for d in dims):
        raise ValueError(
            f"tpu_mesh_shape={spec!r}: need 1 or 2 positive factors "
            "(rows[ x features])")
    return dims


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              mesh_shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Device mesh over the row (data) axis, optionally 2-D rows x features.

    The reference's world is ``num_machines`` ranks in a flat TCP/MPI mesh
    (network.h Init); ours is whatever devices JAX exposes (single host: all
    local chips; multi-host: the global device set). ``mesh_shape``
    (see :func:`parse_mesh_shape`) restricts the device count and, with
    two factors, folds the mesh to ``(data, feat)`` — the 2-D sharding
    for the wide one-hot shapes where the feature axis is worth
    partitioning too (ROADMAP 2; reference analogue: the row-wise vs
    col-wise histogram dispatch, dataset.h:727).
    """
    if devices is None:
        devices = jax.devices()
        if mesh_shape is not None:
            need = 1
            for d in mesh_shape:
                need *= d
            if need > len(devices):
                raise ValueError(
                    f"tpu_mesh_shape={'x'.join(map(str, mesh_shape))} "
                    f"needs {need} devices, have {len(devices)}")
            devices = devices[:need]
        elif num_devices is not None:
            devices = devices[:num_devices]
    devices = np.asarray(devices)
    if mesh_shape is not None and len(mesh_shape) == 2:
        return Mesh(devices.reshape(mesh_shape), (DATA_AXIS, FEAT_AXIS))
    return Mesh(devices, (DATA_AXIS,))


def mesh_axis_sizes(mesh: Mesh) -> Tuple[int, int]:
    """(row shards, feature shards) of a training mesh (1-D: feat=1)."""
    ax = dict(zip(mesh.axis_names, mesh.devices.shape))
    return ax.get(DATA_AXIS, 1), ax.get(FEAT_AXIS, 1)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """[N, ...] arrays sharded along rows."""
    return NamedSharding(mesh, P(DATA_AXIS))


def row_sharding_2d(mesh: Mesh) -> NamedSharding:
    """[N, F] arrays sharded along rows, features replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, None))


def row_feature_sharding(mesh: Mesh) -> NamedSharding:
    """[N, F] arrays sharded along BOTH axes of a 2-D ``(data, feat)``
    mesh (the wide one-hot shape: 4228 one-hot columns are worth
    partitioning too); on a 1-D mesh this is plain row sharding."""
    if FEAT_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(DATA_AXIS, FEAT_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS, None))


def feature_sharding_2d(mesh: Mesh) -> NamedSharding:
    """[N, F] arrays sharded along features, rows replicated
    (feature-parallel learner: reference feature_parallel_tree_learner.cpp)."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def class_row_sharding(mesh: Mesh) -> NamedSharding:
    """[K, N] score arrays: classes replicated, rows sharded."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_rows(n: int, num_shards: int) -> int:
    """Rows must split evenly across shards; callers mask the tail
    (reference analogue: pre_partition / CheckOrPartition, dataset.h:110)."""
    return (-n) % num_shards


_barrier_seq = 0


def sync_barrier(tag: str, deadline_s: float = 0.0) -> None:
    """Named cross-process barrier with an optional watchdog deadline.

    Multi-process runs block until every rank arrives — the reference's
    ``Network::``AllReduce-as-barrier between training phases. A rank
    that never arrives (preempted worker, wedged runtime) used to hang
    the whole pod silently; under a positive ``deadline_s`` the wait
    surfaces as a structured ``TrainingInterrupted`` instead
    (parallel/multihost.py watchdog), and the training engine snapshots
    before exiting. Single-process runs only fire the fault-injection
    hook (so dryrun chaos tests exercise the same code path tier-1 runs
    on CPU).

    The wait goes through the coordination-service KV barrier
    (``wait_at_barrier``), which works on every backend — the XLA
    collective inside ``multihost_utils.sync_global_devices`` is not
    implemented for multiprocess CPU, which the 2-process dryrun tests
    rely on. Barrier ids carry a per-process sequence number; ranks call
    barriers in program order, so the ids line up across the pod.
    """
    from ..analysis.faultinject import active_plan
    from .multihost import run_with_deadline

    global _barrier_seq
    _barrier_seq += 1
    seq = _barrier_seq

    def _sync():
        active_plan().fire("barrier", tag=tag)
        if jax.process_count() <= 1:
            return
        client = None
        try:
            from jax._src import distributed
            client = distributed.global_state.client
        except Exception:  # pragma: no cover - jax internals moved
            pass
        if client is not None:
            # the KV timeout backstops the watchdog: keep it LARGER than
            # deadline_s so a hang surfaces as TrainingInterrupted first
            timeout_s = deadline_s * 2 if deadline_s > 0 else 600.0
            client.wait_at_barrier(f"lgbm_tpu_{tag}_{seq}",
                                   int(timeout_s * 1000))
        else:
            from jax.experimental import multihost_utils as mu
            mu.sync_global_devices(f"{tag}_{seq}")

    run_with_deadline(_sync, deadline_s, f"barrier {tag!r}")


def predict_shard_pad(n: int, num_shards: int, ladder) -> Optional[int]:
    """Padded row count for row-sharded bucketed predict, or None.

    Requests above the serving ladder's largest rung can run as ONE
    GSPMD-sharded program over this mesh instead of a host loop of
    max-rung slices: each shard gets ``bucket_rows(ceil(n/S))`` rows, so
    the compiled program is still keyed on a ladder rung (per shard) and
    steady-state stays zero-recompile. None = the per-shard share
    overflows the ladder too; the caller falls back to slicing.
    """
    from ..ops.predict import bucket_rows
    per_shard = -(-n // num_shards)
    rung = bucket_rows(per_shard, ladder)
    return None if rung is None else rung * num_shards
