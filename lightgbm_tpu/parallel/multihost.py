"""Multi-host (multi-process) training entry.

TPU-native replacement for the reference's cluster bootstrap
(reference: src/network/linkers_socket.cpp:29-118 — parse ``machines`` /
``machine_list_file``, bind ``local_listen_port``, build the full TCP mesh;
Dask analogue python-package/lightgbm/dask.py:374-412 builds the machines
string and runs one training process per worker).

On TPU pods the socket mesh is replaced by ``jax.distributed.initialize``:
every host runs the same training script, JAX wires the hosts over DCN, and
``jax.devices()`` then exposes the GLOBAL device set — the existing
data-parallel/voting/feature learners shard over all chips of all hosts with
no further changes (GSPMD inserts ICI collectives within a host and DCN
collectives across hosts).

Launch recipe (the reference's ``machines=ip1:port1,ip2:port2`` maps 1:1):

    # on every host, with the same machines list:
    params = {"tree_learner": "data",
              "machines": "10.0.0.1:12400,10.0.0.2:12400",
              "num_machines": 2}
    lgb.train(params, dataset, ...)

The first machines entry is the coordinator. Each host's process index is
inferred by matching a local interface address against the machines list, or
set explicitly via the LIGHTGBM_TPU_PROCESS_ID environment variable (the
reference resolves ranks the same way — by finding the local ip/port in the
list, linkers_socket.cpp:78-101).

Data feeding: each process passes only its local shard of rows (like the
reference's ``pre_partition=true``) and JAX's global sharding treats the
per-process arrays as one global dataset.
"""
from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..utils import log

_initialized = False


class TrainingInterrupted(RuntimeError):
    """A collective/step blew its deadline (or a preemption surfaced).

    The structured replacement for a silent pod hang: carries what was
    running and the deadline that fired, and the training engine writes a
    best-effort final snapshot before re-raising it (engine.py), so a
    preemptible run loses at most the iterations since the last
    ``tpu_checkpoint_freq`` tick."""

    def __init__(self, what: str, deadline_s: float = 0.0):
        super().__init__(
            f"{what} exceeded its {deadline_s:.1f}s deadline"
            if deadline_s else what)
        self.what = what
        self.deadline_s = deadline_s


#: transient bootstrap/collective failure signatures (the TPU runtime
#: mid-restart family; matches the fault injector's TRANSIENT_MESSAGE).
#: This is the ONE canonical list — bench.py imports it (with a
#: standalone fallback) for its backend-init/resume retry classifiers.
TRANSIENT_ERRORS = (
    "Unable to initialize backend",
    "UNAVAILABLE", "Unavailable",
    "DEADLINE_EXCEEDED", "Deadline Exceeded",
    "failed to connect", "Failed to connect",
    "Connection reset", "Socket closed",
    "already in use",
    "No visible TPU", "device enumeration",
)


def run_with_deadline(fn: Callable, deadline_s: float, what: str, *,
                      retries: int = 0, backoff_s: float = 1.0):
    """Run ``fn()`` under a wall-clock watchdog.

    ``fn`` executes in a daemon worker thread; if it has not finished
    within ``deadline_s`` a structured :class:`TrainingInterrupted` is
    raised in the caller (the reference's socket linkers fail their
    connects after ``time_out`` minutes the same way,
    src/network/linkers_socket.cpp connect retry loop). ``deadline_s <= 0``
    runs ``fn`` inline with no watchdog (retries still apply).

    Transient failures (:data:`TRANSIENT_ERRORS` substrings) retry up to
    ``retries`` times with exponential backoff — the bootstrap analogue of
    the reference's per-linker connect retries.

    Caveat: a worker that blows its deadline is abandoned, not killed
    (Python cannot safely interrupt a thread blocked in native code). The
    caller is expected to snapshot and exit — the leaked thread dies with
    the process, which is the point of the final snapshot.
    """
    attempt = 0
    while True:
        try:
            if deadline_s and deadline_s > 0:
                box: dict = {}
                done = threading.Event()

                def _runner():
                    try:
                        box["value"] = fn()
                    except BaseException as err:  # noqa: BLE001 - re-raised
                        box["error"] = err
                    finally:
                        done.set()

                worker = threading.Thread(
                    target=_runner, daemon=True,
                    name=f"lgbm-tpu-watchdog[{what}]")
                worker.start()
                if not done.wait(deadline_s):
                    from ..obs import flight
                    flight.note("deadline", what=what,
                                deadline_s=deadline_s)
                    raise TrainingInterrupted(what, deadline_s)
                if "error" in box:
                    raise box["error"]
                return box.get("value")
            return fn()
        except TrainingInterrupted:
            raise
        except Exception as err:  # noqa: BLE001 - classified below
            msg = str(err)
            transient = any(t in msg for t in TRANSIENT_ERRORS)
            if not transient or attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            attempt += 1
            from ..obs import flight
            flight.note("retry", what=what, attempt=attempt,
                        error=msg.splitlines()[0][:200])
            log.warning(
                f"{what}: transient failure (attempt {attempt}/"
                f"{retries}): {msg.splitlines()[0][:200]}; retrying in "
                f"{delay:.1f}s")
            time.sleep(delay)


def _parse_machines(machines: str, machine_list_file: str) -> List[str]:
    if machines:
        return [m.strip() for m in machines.split(",") if m.strip()]
    if machine_list_file:
        with open(machine_list_file) as f:
            out = []
            for line in f:
                line = line.strip().replace(" ", ":")
                if line:
                    out.append(line)
            return out
    return []


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:  # pragma: no cover
        pass
    return addrs


def infer_process_id(machines: List[str]) -> Optional[int]:
    """Rank = index of the local address in the machines list (reference:
    linkers_socket.cpp:78-101 finds the local ip/port the same way)."""
    env = os.environ.get("LIGHTGBM_TPU_PROCESS_ID")
    if env is not None:
        return int(env)
    hosts = [m.rsplit(":", 1)[0] for m in machines]
    if len(set(hosts)) != len(hosts):
        # several processes on one host are indistinguishable by address
        # (the reference disambiguates by binding the port,
        # linkers_socket.cpp:78-101; we cannot bind the coordinator's port)
        raise ValueError(
            "machines lists the same host more than once; set "
            "LIGHTGBM_TPU_PROCESS_ID per process to assign ranks")
    local = _local_addresses()
    for i, host in enumerate(hosts):
        if host in local:
            return i
    return None


_kv_seq = 0


def _kv_client():
    """The coordination-service KV client, or None outside multi-process
    runs (same access path as mesh.sync_barrier — the KV plane works on
    every backend, including multiprocess CPU where XLA collectives may
    not exist)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


def kv_allgather(arr, tag: str, timeout_s: float = 600.0):
    """Allgather a host numpy array across processes over the
    coordination-service KV store — no XLA collective involved.

    Each rank publishes its (npy-serialized) array under a sequenced,
    rank-suffixed key, then blocking-reads every peer's key; the
    sequence number keeps repeated gathers from colliding, and callers
    must invoke KV gathers in the same program order on every rank
    (the sync_barrier discipline). Returns the per-rank arrays in rank
    order — ragged first dimensions are fine, which the padded XLA
    allgather path cannot say.
    """
    import io
    import jax
    import numpy as np
    global _kv_seq
    _kv_seq += 1
    client = _kv_client()
    if client is None:  # pragma: no cover - no coordination service
        raise RuntimeError(
            "kv_allgather needs the jax.distributed coordination service "
            "(call init_distributed first)")
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    prefix = f"lgbm_tpu_kvag_{tag}_{_kv_seq}"
    client.key_value_set_bytes(
        f"{prefix}/{jax.process_index()}", buf.getvalue())
    out = []
    for p in range(jax.process_count()):
        raw = client.blocking_key_value_get_bytes(
            f"{prefix}/{p}", int(timeout_s * 1000))
        out.append(np.load(io.BytesIO(raw), allow_pickle=False))
    # clean up so repeated gathers (one per Dataset construct) do not
    # grow coordinator memory forever: a delete is only safe once EVERY
    # rank has read every key, so fence first, then each rank removes
    # its own key (no contention; the barrier id rides the same seq)
    client.wait_at_barrier(f"{prefix}_read", int(timeout_s * 1000))
    client.key_value_delete(f"{prefix}/{jax.process_index()}")
    return out


def pool_bin_sample(sample):
    """Pool bin-construction samples across processes so every rank builds
    IDENTICAL bin mappers from the global distribution (reference:
    ConstructBinMappersFromTextData gathers per-rank samples and syncs the
    resulting mappers, src/io/dataset_loader.cpp:1070; without this two
    hosts would bin their local shards differently and train a silently
    wrong model).

    On multiprocess CPU the gather rides :func:`kv_allgather` — jax's CPU
    backend has no XLA cross-process collectives unless gloo is compiled
    in, but the coordination-service KV plane always works there (the
    sync_barrier pattern), and the one-shot construct-time sample is tiny.
    """
    import jax
    import numpy as np
    if jax.process_count() <= 1:
        return sample
    if jax.default_backend() == "cpu":
        return np.concatenate(kv_allgather(sample, "binsample"), axis=0)
    from jax.experimental import multihost_utils as mu
    counts = mu.process_allgather(
        np.asarray([sample.shape[0]], np.int64)).reshape(-1)
    m = int(counts.max())
    padded = np.zeros((m, sample.shape[1]), sample.dtype)
    padded[:sample.shape[0]] = sample
    gathered = np.asarray(mu.process_allgather(padded))   # [P, m, F]
    return np.concatenate(
        [gathered[p, :int(c)] for p, c in enumerate(counts)], axis=0)


def gather_metadata(md, n_local: int):
    """Concatenate per-process Metadata into the global Metadata, in process
    order (the same order jax.make_array_from_process_local_data lays out
    the feature rows). Requires equal per-process row counts."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils as mu
    from ..io.dataset import Metadata

    counts = mu.process_allgather(
        np.asarray([n_local], np.int64)).reshape(-1)
    if int(counts.min()) != int(counts.max()):
        raise ValueError(
            "multi-host training needs the same row count on every process "
            f"(got {counts.tolist()}); pre-partition the data evenly "
            "(reference: pre_partition / CheckOrPartition, dataset.h:110)")
    n_global = int(counts.sum())
    out = Metadata(n_global)
    for field in ("label", "weight", "init_score", "position"):
        v = getattr(md, field)
        flags = mu.process_allgather(
            np.asarray([0 if v is None else 1], np.int64)).reshape(-1)
        if int(flags.max()) == 0:
            continue
        if v is None:
            raise ValueError(
                f"metadata field {field} set on some processes but not here")
        v = np.asarray(v)
        # agree on the class-major layout BEFORE branching: every process
        # must run the same collective sequence, so shape validation is
        # itself a collective (kk = -1 marks an indivisible local size)
        if v.ndim == 2:
            kk = -(10 + v.shape[1])  # [n_local, K] row-major layout
        elif n_local > 0 and v.size % n_local == 0:
            kk = v.size // n_local
        else:
            kk = -1
        kks = mu.process_allgather(np.asarray([kk], np.int64)).reshape(-1)
        if int(kks.min()) != int(kks.max()) or kk == -1:
            raise ValueError(
                f"metadata field {field}: inconsistent per-process shapes "
                f"(local size {v.size} for {n_local} rows; gathered layout "
                f"codes {sorted(set(int(x) for x in kks))}; expected "
                "n_local or an exact class-major multiple on every process)")
        if v.ndim == 2:
            # [n_local, K] init scores: concatenate along rows
            g = np.asarray(mu.process_allgather(v))      # [P, n_local, K]
            setattr(out, field, g.reshape(-1, v.shape[1]))
        elif kk != 1:
            # flat class-major [K*n_local] (the reference Metadata layout,
            # src/io/metadata.cpp init_score_): gather per class so the
            # global vector stays class-major
            g = np.asarray(mu.process_allgather(
                v.reshape(kk, n_local)))                 # [P, K, n_local]
            setattr(out, field,
                    np.concatenate(list(g), axis=1).reshape(-1))
        else:
            setattr(out, field,
                    np.asarray(mu.process_allgather(v)).reshape(-1))
    # ranking groups: queries must never straddle processes — each rank
    # holds whole queries and the global boundary vector concatenates with
    # running row offsets (the reference's partition contract:
    # Metadata::CheckOrPartition keeps query blocks intact,
    # src/io/metadata.cpp; dataset.h:110). Validation is COLLECTIVE: every
    # process runs the same allgather sequence and raises together, never
    # leaving a peer blocked inside a collective.
    if md.query_boundaries is None:
        qstat, sizes = 0, np.zeros((0,), np.int64)   # no groups here
    else:
        qb = np.asarray(md.query_boundaries, np.int64)
        ok = qb[-1] == n_local
        qstat = 1 if ok else 2                       # 2 = straddling rows
        sizes = np.diff(qb) if ok else np.zeros((0,), np.int64)
    qstats = mu.process_allgather(
        np.asarray([qstat], np.int64)).reshape(-1)
    if int(qstats.max()) > 0:
        if int(qstats.min()) == 0 or int(qstats.max()) == 2:
            raise ValueError(
                "ranking groups are inconsistent across processes "
                f"(per-rank states {qstats.tolist()}: 0=missing, 1=ok, "
                "2=group sizes do not cover the local rows); every process "
                "needs `group` sizes summing to its local row count — "
                "queries must not straddle processes")
        nq = mu.process_allgather(
            np.asarray([sizes.size], np.int64)).reshape(-1)
        m = int(nq.max())
        padded = np.zeros((m,), np.int64)
        padded[:sizes.size] = sizes
        g = np.asarray(mu.process_allgather(padded))       # [P, m]
        all_sizes = np.concatenate(
            [g[p, :int(c)] for p, c in enumerate(nq)])
        out.group = all_sizes
        out.query_boundaries = np.concatenate(
            [[0], np.cumsum(all_sizes)]).astype(np.int64)
    return out


def to_host(arr):
    """Fetch a (possibly non-addressable) jax.Array as host numpy.

    Multi-process: sharded global arrays are not fully addressable from one
    process; allgather them (metrics and model pulls are host-side)."""
    import jax
    import numpy as np
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        if arr.is_fully_replicated:
            return np.asarray(arr.addressable_data(0))
        from jax.experimental import multihost_utils as mu
        return np.asarray(mu.process_allgather(arr, tiled=True))
    return np.asarray(arr)


def maybe_init_distributed(params) -> bool:
    """Bootstrap multi-process training when num_machines > 1 (alias-aware).

    Must run before dataset construction (bin-mapper sync) and before any
    backend-initializing JAX call."""
    from ..config import Config
    cfg = Config(params) if isinstance(params, dict) else params
    if int(cfg.get("num_machines", 1) or 1) > 1:
        return init_distributed(cfg)
    return False


def _maybe_enable_cpu_collectives() -> None:
    """Multiprocess CPU: switch jax's CPU collectives to gloo when built.

    The default CPU backend has NO cross-process XLA collectives
    (``jax_cpu_collectives_implementation=none``) — every in-jit psum of
    a 2-process CPU run would abort. When this jaxlib ships the gloo TCP
    implementation, select it BEFORE the backend client is created; the
    construct-time sample pooling additionally rides the KV plane
    (:func:`kv_allgather`), which needs no XLA collectives at all.
    Respects an explicit user setting; a no-op off-CPU and on builds
    without gloo."""
    try:
        import jax
        from jax._src import xla_bridge
        from jax._src.lib import xla_client
        # skip only under an EXPLICIT non-cpu platform selection (e.g.
        # the tunneled-TPU box's "axon,cpu"): with jax_platforms unset a
        # CPU-only host still resolves to the CPU backend, and bailing
        # there would leave the default num_machines>1 CPU run to abort
        # at its first in-jit collective. On accelerator runs the flag
        # only configures the SECONDARY cpu client (construction is
        # lazy and cheap), so over-enabling is harmless.
        plats = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS") or "")
        if plats and not plats.startswith("cpu"):
            return
        current = xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value
        if current in (None, "none") \
                and hasattr(xla_client._xla, "make_gloo_tcp_collectives"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            log.info("multiprocess CPU: enabled gloo XLA collectives")
    except Exception:  # pragma: no cover - config/attr drift across jax
        pass


def init_distributed(config) -> bool:
    """Initialize JAX multi-process training when num_machines > 1.

    Returns True when running (or already running) in multi-process mode.
    Safe to call on every host; a no-op for single-machine configs.
    """
    global _initialized
    num_machines = int(config.get("num_machines", 1) or 1)
    if num_machines <= 1:
        return False
    if _initialized:
        return True
    import jax
    machines = _parse_machines(
        str(config.get("machines", "")),
        str(config.get("machine_list_filename", "")))
    if machines and len(machines) != num_machines:
        raise ValueError(
            f"num_machines={num_machines} but machines lists "
            f"{len(machines)} entries")
    coordinator = machines[0] if machines else None
    process_id = infer_process_id(machines) if machines else None
    if coordinator is None or process_id is None:
        raise ValueError(
            "multi-machine training needs machines='ip:port,...' (or "
            "machine_list_filename) naming every host, with this host's "
            "address in the list or LIGHTGBM_TPU_PROCESS_ID set "
            "(reference: config.h machines / linkers_socket.cpp)")
    log.info(f"Initializing multi-host training: rank {process_id}/"
             f"{num_machines}, coordinator {coordinator}")
    _maybe_enable_cpu_collectives()
    # the bootstrap barrier is the first place a preempted/half-up pod
    # hangs: run it under the collective watchdog (deadline + exponential
    # backoff on transient failures) so a dead coordinator surfaces as a
    # structured TrainingInterrupted, not a silent stall (reference:
    # linkers_socket.cpp retries each connect and fails after time_out)
    deadline = float(config.get("tpu_collective_deadline_s", 0.0) or 0.0)
    retries = int(config.get("tpu_collective_retries", 3) or 0)
    from ..analysis.faultinject import active_plan

    def _bootstrap():
        active_plan(config).fire("backend_init")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_machines,
            process_id=process_id)

    run_with_deadline(_bootstrap, deadline,
                      f"multi-host bootstrap (rank {process_id}, "
                      f"coordinator {coordinator})", retries=retries)
    _initialized = True
    # post-bootstrap barrier under the same watchdog: proves every rank
    # actually came up before dataset construction starts (a half-up pod
    # otherwise hangs later, inside the first bin-mapper sync)
    from .mesh import sync_barrier
    sync_barrier("lgbm-tpu-bootstrap", deadline_s=deadline)
    return True
