"""Voting-parallel histogram construction (PV-Tree).

TPU-native re-design of the reference's VotingParallelTreeLearner
(reference: src/treelearner/voting_parallel_tree_learner.cpp — each rank
proposes its local top-k features, GlobalVoting picks the global top-2k by
local gains (:151), and only those features' histograms are reduce-scattered
(CopyLocalHistogram :184) — capping network traffic at O(2k*B) instead of
O(F*B) per split).

Here the same dataflow is expressed for GSPMD: rows reshape to a
[shards, rows/shard] leading axis that stays sharded, so per-shard local
histograms and local gains are computed without communication; the vote and
the final reduction of ONLY the selected features' histograms are the only
collectives XLA inserts (an all-reduce of [2k, B, K] — the comm cap the
reference achieves with its socket ReduceScatter).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.histogram import histogram_block
from ..ops.split import leaf_gain


def _local_feature_gains(hist, p):
    """Cheap per-feature best-gain proxy from a local histogram [F, B, K]:
    the reference ranks features by their local best split gain
    (voting_parallel_tree_learner.cpp local FindBestSplits)."""
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    cg = jnp.cumsum(g, axis=1)
    ch = jnp.cumsum(h, axis=1)
    pg = cg[:, -1:]
    ph = ch[:, -1:]
    gain = leaf_gain(cg, ch, p) + leaf_gain(pg - cg, ph - ch, p)
    return jnp.max(gain, axis=1)                       # [F]


def voting_histogram(
    binned: jnp.ndarray,       # [N, F] u8, row-sharded over the mesh
    chans: jnp.ndarray,        # [N, K] f32, row-sharded
    num_bins: int,
    num_shards: int,           # static: mesh size
    top_k: int,                # static: per-shard vote size (config top_k)
    split_params,
    impl: str = "auto",
    mbatch: int = 1,
    layout: str = "lane",
    overlap: int = 0,
) -> jnp.ndarray:              # [F, B, K] f32 (replicated)
    """Histogram with voting-capped communication: only the globally voted
    2k features carry reduced histograms; every other feature's histogram is
    zero (its candidate splits then fail the min_data gate, exactly like the
    reference never scanning unvoted features).

    ``overlap`` > 1 (tpu_hist_overlap) reduces the elected features in
    that many groups — one cross-shard all-reduce per group instead of a
    single [2k, B, K] reduce, so the groups' collectives pipeline. Same
    addends per element: bit-identical results, unchanged total bytes."""
    n, f = binned.shape
    k = chans.shape[1]
    b = num_bins
    s = num_shards
    n_local = n // s
    top_k = min(top_k, f)
    k2 = min(2 * top_k, f)

    # NOTE: 2k >= F (a full election) never reaches this function — the
    # grower's voting_live gate (ops/grower.py hist3) routes it to the
    # EXACT data-parallel histogram program instead, because the
    # per-shard vmap'd accumulation below orders its f32 sums differently
    # from the global chunked einsum and the last-ulp gain noise used to
    # flip split tie-breaks against the data learner (the pre-PR-8
    # tier-1 voting-parity failure)
    if k2 >= f:  # not an assert: must survive python -O
        raise ValueError("full election (2k >= F) must take the "
                         "data-parallel histogram")

    # per-shard local histograms: the leading axis keeps the row sharding,
    # so this is communication-free under GSPMD
    bs = binned.reshape(s, n_local, f)
    cs = chans.reshape(s, n_local, k)
    local = _vmap_hist(bs, cs, b, impl, mbatch, layout)   # [S, F, B, K]

    # local votes (top-k features by local gain) and the global election
    gains = _vmap_gains(local, split_params)           # [S, F]
    kth = -jnp.sort(-gains, axis=1)[:, top_k - 1:top_k]
    vote = gains >= kth                                # [S, F] local top-k
    score = jnp.sum(jnp.where(vote, gains, 0.0), axis=0)   # [F] replicated
    sel = jnp.argsort(-score)[:k2]                     # [2k] elected features

    # reduce ONLY the elected features' histograms across shards
    full = jnp.zeros((f, b, k), jnp.float32)
    if overlap > 1 and k2 > 1:
        from ..ops.histogram import overlap_groups
        for lo, hi in overlap_groups(k2, overlap):
            sel_g = sel[lo:hi]
            # each group's cross-shard sum is an independent all-reduce:
            # XLA pipelines group g's collective under group g+1's gather
            hist_g = jnp.sum(jnp.take(local, sel_g, axis=1), axis=0)
            full = full.at[sel_g].set(hist_g)
        return full
    hist_sel = jnp.sum(jnp.take(local, sel, axis=1), axis=0)   # [2k, B, K]
    return full.at[sel].set(hist_sel)


def _vmap_hist(bs, cs, b, impl, mbatch=1, layout="lane"):
    import jax
    return jax.vmap(lambda x, c: histogram_block(x, c, b, impl=impl,
                                                 mbatch=mbatch,
                                                 layout=layout))(bs, cs)


def _vmap_gains(local, p):
    import jax
    return jax.vmap(lambda h: _local_feature_gains(h, p))(local)
