"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM (reference: vnherdeiro/LightGBM) for TPUs:
histograms, split finding, tree growth, objectives and scoring all run on
device through JAX/XLA (with Pallas kernels for the hot paths), and the
distributed tree learners use XLA collectives over the ICI mesh instead of the
reference's socket/MPI network.

Public surface mirrors the reference's Python package
(python-package/lightgbm/__init__.py): ``Dataset``, ``Booster``, ``train``,
``cv``, callbacks, and sklearn-style estimators.
"""
from .basic import Booster, Dataset, Sequence
from .callback import (
    EarlyStopException,
    early_stopping,
    log_evaluation,
    record_evaluation,
    reset_parameter,
)
from .config import Config
from .engine import CVBooster, cv, train

__version__ = "0.1.0"

__all__ = [
    "DaskLGBMClassifier",
    "DaskLGBMRegressor",
    "DaskLGBMRanker",
    "Dataset", "Booster", "Config", "Sequence",
    "train", "cv", "CVBooster",
    "early_stopping", "log_evaluation", "record_evaluation", "reset_parameter",
    "EarlyStopException", "TrainingInterrupted",
    "PredictionServer", "ModelRegistry", "ServingError", "ServingTimeout",
    "ServerOverloaded", "ServerClosed", "SwapFailed",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph",
    "register_parser",
]

_PLOTTING = ("plot_importance", "plot_metric", "plot_split_value_histogram",
             "plot_tree", "create_tree_digraph")


def __getattr__(name):
    # sklearn wrappers / plotting import lazily to keep base import light
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"):
        from . import dask as _dk
        return getattr(_dk, name)
    if name == "register_parser":
        from .io.loader import register_parser
        return register_parser
    if name == "TrainingInterrupted":
        from .parallel.multihost import TrainingInterrupted
        return TrainingInterrupted
    if name in ("PredictionServer", "ModelRegistry", "ServingError",
                "ServingTimeout", "ServerOverloaded", "ServerClosed",
                "SwapFailed"):
        # serving layer loads lazily: the coalescer thread machinery is
        # only wanted by processes that actually serve
        from . import serving as _serving
        return getattr(_serving, name)
    if name in _PLOTTING:
        from . import plotting as _pl
        return getattr(_pl, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
