"""scikit-learn estimator API.

Mirror of the reference's sklearn wrappers
(reference: python-package/lightgbm/sklearn.py — LGBMModel :486,
LGBMRegressor :1314, LGBMClassifier :1424, LGBMRanker :1678, custom
objective/metric adapters :151/:238).
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .engine import train as train_fn
from .utils import log


class LGBMModel:
    """(reference: sklearn.py:486)"""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[Union[str, Callable]] = None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state=None,
        n_jobs: Optional[int] = None,
        importance_type: str = "split",
        **kwargs,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features: Optional[int] = None
        self._classes = None
        self._n_classes: Optional[int] = None
        self._evals_result: Dict = {}
        self._best_iteration: int = -1

    # -- sklearn plumbing ----------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            k: getattr(self, k) for k in (
                "boosting_type", "num_leaves", "max_depth", "learning_rate",
                "n_estimators", "subsample_for_bin", "objective",
                "class_weight", "min_split_gain", "min_child_weight",
                "min_child_samples", "subsample", "subsample_freq",
                "colsample_bytree", "reg_alpha", "reg_lambda", "random_state",
                "n_jobs", "importance_type")
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key) and not key.startswith("_"):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _lgb_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("n_estimators", None)
        params.pop("importance_type", None)
        params.pop("class_weight", None)
        params.pop("n_jobs", None)
        obj = params.pop("objective", None)
        params["boosting"] = params.pop("boosting_type", "gbdt")
        params["num_leaves"] = self.num_leaves
        params["bagging_fraction"] = params.pop("subsample", 1.0)
        params["bagging_freq"] = params.pop("subsample_freq", 0)
        params["feature_fraction"] = params.pop("colsample_bytree", 1.0)
        params["lambda_l1"] = params.pop("reg_alpha", 0.0)
        params["lambda_l2"] = params.pop("reg_lambda", 0.0)
        params["min_gain_to_split"] = params.pop("min_split_gain", 0.0)
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight", 1e-3)
        params["min_data_in_leaf"] = params.pop("min_child_samples", 20)
        params["bin_construct_sample_cnt"] = params.pop("subsample_for_bin",
                                                        200000)
        seed = params.pop("random_state", None)
        if seed is not None:
            params["seed"] = seed if isinstance(seed, int) else 0
        params["objective"] = obj if obj is not None else self._default_objective()
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        params = self._lgb_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        if self.class_weight is not None and sample_weight is None:
            sample_weight = _class_weight_to_sample_weight(
                self.class_weight, y)
        train_set = Dataset(
            X, label=y, weight=sample_weight, init_score=init_score,
            group=group, feature_name=feature_name,
            categorical_feature=categorical_feature, params=params,
            free_raw_data=False)
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    vg = eval_group[i] if eval_group else None
                    vi = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(train_set.create_valid(
                        vx, label=vy, weight=vw, group=vg, init_score=vi))
                valid_names.append(
                    eval_names[i] if eval_names else f"valid_{i}")
        self._evals_result = {}
        cbs = list(callbacks) if callbacks else []
        cbs.append(callback_mod.record_evaluation(self._evals_result))
        feval = eval_metric if callable(eval_metric) else None
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            feval=_wrap_sklearn_feval(feval) if feval else None,
            callbacks=cbs)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = train_set.num_feature()
        return self

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise ValueError("Estimator not fitted, call fit first")
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)

    # -- attributes (reference: sklearn.py properties) -----------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise AttributeError("No booster found; call fit first")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()


class LGBMRegressor(LGBMModel):
    """(reference: sklearn.py:1314)"""

    def _default_objective(self) -> str:
        return "regression"

    def fit(self, X, y, **kwargs):
        return super().fit(X, y, **kwargs)


class LGBMClassifier(LGBMModel):
    """(reference: sklearn.py:1424)"""

    def _default_objective(self) -> str:
        return "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y)
        params_extra = {}
        if self._n_classes > 2:
            self._other_params.setdefault("num_class", self._n_classes)
            if self.objective is None:
                self.objective = "multiclass"
        super().fit(X, y_enc, **kwargs)
        return self

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict_proba(self, X, raw_score: bool = False,
                      num_iteration: Optional[int] = None, **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 num_iteration=num_iteration, **kwargs)
        if raw_score:
            return result
        if result.ndim == 1:
            return np.stack([1.0 - result, result], axis=1)
        return result

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(X, raw_score=raw_score,
                                   num_iteration=num_iteration,
                                   pred_leaf=pred_leaf,
                                   pred_contrib=pred_contrib, **kwargs)
        proba = self.predict_proba(X, num_iteration=num_iteration, **kwargs)
        return self._classes[np.argmax(proba, axis=1)]


class LGBMRanker(LGBMModel):
    """(reference: sklearn.py:1678)"""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)


def _class_weight_to_sample_weight(class_weight, y) -> np.ndarray:
    y = np.asarray(y)
    if class_weight == "balanced":
        classes, counts = np.unique(y, return_counts=True)
        weights = {c: len(y) / (len(classes) * cnt)
                   for c, cnt in zip(classes, counts)}
    elif isinstance(class_weight, dict):
        weights = class_weight
    else:
        raise ValueError(f"Unsupported class_weight: {class_weight!r}")
    return np.array([weights.get(v, 1.0) for v in y], dtype=np.float64)


def _wrap_sklearn_feval(feval: Callable) -> Callable:
    """sklearn-style eval: f(y_true, y_pred) -> (name, value, higher_better)
    (reference: _EvalFunctionWrapper, sklearn.py:238)."""

    def _inner(preds, dataset):
        y_true = np.asarray(dataset.get_label())
        return feval(y_true, preds)

    return _inner
