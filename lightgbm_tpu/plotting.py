"""Plotting utilities (reference: python-package/lightgbm/plotting.py —
plot_importance, plot_metric, plot_split_value_histogram, plot_tree,
create_tree_digraph). Matplotlib-backed; graphviz only for tree rendering."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import numpy as np


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("plotting requires matplotlib") from e


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, dpi=None, grid=True,
                    precision=3, **kwargs):
    """(reference: plotting.py plot_importance)"""
    plt = _check_matplotlib()
    imp = booster.feature_importance(importance_type)
    names = booster.feature_name()
    tuples = [(n, v) for n, v in zip(names, imp)
              if not (ignore_zero and v == 0)]
    tuples.sort(key=lambda t: t[1])
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    labels, values = zip(*tuples) if tuples else ((), ())
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for y, v in zip(ylocs, values):
        ax.text(v + 1, y,
                f"{v:.{precision}f}" if importance_type == "gain"
                else str(int(v)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_record, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="@metric@", figsize=None,
                dpi=None, grid=True):
    """(reference: plotting.py plot_metric) — takes a record_evaluation dict
    or a Booster trained with keep_training_booster."""
    plt = _check_matplotlib()
    if isinstance(booster_or_record, dict):
        eval_results = booster_or_record
    else:
        raise TypeError(
            "plot_metric expects the dict filled by record_evaluation()")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    chosen_metric = metric
    for name in names:
        metrics = eval_results[name]
        if chosen_metric is None:
            chosen_metric = next(iter(metrics))
        values = metrics[chosen_metric]
        ax.plot(range(len(values)), values, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", str(chosen_metric)))
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef=0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True):
    """(reference: plotting.py plot_split_value_histogram)"""
    plt = _check_matplotlib()
    d = booster.dump_model()
    names = d["feature_names"]
    if isinstance(feature, str):
        fidx = names.index(feature)
    else:
        fidx = int(feature)
    values = []

    def walk(node):
        if "split_feature" in node:
            if node["split_feature"] == fidx and \
                    not isinstance(node["threshold"], str):
                values.append(float(node["threshold"]))
            walk(node["left_child"])
            walk(node["right_child"])

    for t in d["tree_info"]:
        walk(t["tree_structure"])
    if not values:
        raise ValueError(
            f"feature {feature} was not used in splitting of trees")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, edges = np.histogram(values, bins=bins or "auto")
    centres = (edges[:-1] + edges[1:]) / 2
    ax.bar(centres, hist, width=width_coef * (edges[1] - edges[0]))
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    tag = "name" if isinstance(feature, str) else "index"
    ax.set_title(title.replace("@index/name@", tag)
                 .replace("@feature@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    """(reference: plotting.py create_tree_digraph) — needs graphviz."""
    try:
        import graphviz
    except ImportError as e:  # pragma: no cover
        raise ImportError("create_tree_digraph requires graphviz") from e
    d = booster.dump_model()
    if tree_index >= len(d["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range")
    tree = d["tree_info"][tree_index]
    names = d["feature_names"]
    graph = graphviz.Digraph(**kwargs)
    graph.attr(rankdir="LR" if orientation == "horizontal" else "TB")
    show_info = show_info or []

    def node_id(node):
        if "split_index" in node:
            return f"split{node['split_index']}"
        return f"leaf{node['leaf_index']}"

    def walk(node):
        nid = node_id(node)
        if "split_index" in node:
            f = names[node["split_feature"]]
            thr = node["threshold"]
            op = node["decision_type"]
            label = f"{f} {op} {thr}"
            for info in show_info:
                if info in node:
                    label += f"\\n{info}: {node[info]}"
            graph.node(nid, label=label, shape="rectangle")
            for child, edge in ((node["left_child"], "yes"),
                                (node["right_child"], "no")):
                walk(child)
                graph.edge(nid, node_id(child), label=edge)
        else:
            label = f"leaf {node['leaf_index']}: " \
                    f"{round(node['leaf_value'], precision)}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\\ncount: {node['leaf_count']}"
            graph.node(nid, label=label)

    walk(tree["tree_structure"])
    return graph


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None, dpi=None,
              show_info=None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    """(reference: plotting.py plot_tree) — renders via graphviz+matplotlib."""
    plt = _check_matplotlib()
    graph = create_tree_digraph(booster, tree_index, show_info, precision,
                                orientation, **kwargs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    import io as _io
    try:
        image = graph.pipe(format="png")
    except Exception as e:  # graphviz binary missing
        raise RuntimeError(
            "plot_tree needs the graphviz system binaries") from e
    import matplotlib.image as mpimg
    ax.imshow(mpimg.imread(_io.BytesIO(image)))
    ax.axis("off")
    return ax
