"""Training callbacks.

Mirror of the reference's callback system
(reference: python-package/lightgbm/callback.py — early_stopping :454,
log_evaluation :75, record_evaluation :183, reset_parameter :237,
CallbackEnv namedtuple :60, EarlyStopException :28).

Evaluation entries are ``(dataset_name, metric_name, value, is_higher_better)``
tuples, same shape the reference passes to callbacks.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional

from .utils import log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"],
)


class EarlyStopException(Exception):
    """(reference: callback.py:28)"""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _fmt_eval(entry) -> str:
    name, metric, value, _ = entry
    return f"{name}'s {metric}: {value:g}"


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """(reference: callback.py:75)"""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_fmt_eval(e) for e in env.evaluation_result_list)
            log.info(f"[{env.iteration + 1}]\t{result}")

    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    """(reference: callback.py:183)"""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for name, metric, _, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])
            eval_result[name][metric].append(value)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters on a schedule: each value is either a list (per
    iteration) or a function iteration -> value (reference: callback.py:237)."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'.")
                new_value = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_value = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are supported "
                                 "as a mapping from boosting round index to new "
                                 "parameter value.")
            new_params[key] = new_value
        if new_params:
            env.model.reset_parameter(new_params)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """(reference: callback.py:454 _EarlyStoppingCallback)

    The tracking state (best score/iteration per metric) lives in a plain
    picklable dict exposed as ``callback.state`` so training checkpoints
    can include it — a resumed run then early-stops at exactly the same
    iteration as an uninterrupted one (io/checkpoint.py; engine.py
    captures/restores it by the callback's ``_ckpt_key``).
    """
    if stopping_rounds <= 0:
        raise ValueError("stopping_rounds should be greater than zero.")

    state = {"enabled": True}

    def _init(env: CallbackEnv) -> None:
        state["enabled"] = bool(env.evaluation_result_list)
        if not state["enabled"]:
            log.warning("Early stopping is not available in dart mode or "
                        "without validation data")
            return
        state["best_score"] = []
        state["best_iter"] = []
        state["best_list"] = []
        state["higher_better"] = []
        for _, _, _, higher_better in env.evaluation_result_list:
            state["best_score"].append(
                float("-inf") if higher_better else float("inf"))
            state["higher_better"].append(bool(higher_better))
            state["best_iter"].append(0)
            state["best_list"].append(None)

    def _improved(value: float, best: float, higher_better: bool) -> bool:
        return value > best + min_delta if higher_better \
            else value < best - min_delta

    def _callback(env: CallbackEnv) -> None:
        # re-init at the first iteration of every train() run so a callback
        # object reused across calls (e.g. one early_stopping shared by all
        # cv() folds) does not carry best_score/best_iter over
        # (reference: callback.py _EarlyStoppingCallback.__call__).
        # A checkpoint-resumed run starts past begin_iteration: init then
        # only if no snapshot state was restored into ``state`` (a restored
        # dict already has best_score and must continue, not reset)
        if env.iteration == env.begin_iteration or \
                "best_score" not in state:
            _init(env)
        if not state["enabled"]:
            return
        # skip the training-set entries (reference skips "train" dataset;
        # cv aggregates arrive as ("cv_agg", "train <metric>", ...))
        first_metric_seen = False
        for i, entry in enumerate(env.evaluation_result_list):
            name, metric, value, _ = entry
            if name == "training" or (
                    name == "cv_agg" and metric.split(" ")[0] == "train"):
                continue
            if first_metric_only and first_metric_seen and \
                    metric != env.evaluation_result_list[0][1]:
                continue
            first_metric_seen = True
            if _improved(value, state["best_score"][i],
                         state["higher_better"][i]):
                state["best_score"][i] = value
                state["best_iter"][i] = env.iteration
                state["best_list"][i] = list(env.evaluation_result_list)
            elif env.iteration - state["best_iter"][i] >= stopping_rounds:
                if verbose:
                    log.info(
                        f"Early stopping, best iteration is:"
                        f"\n[{state['best_iter'][i] + 1}]\t"
                        + "\t".join(_fmt_eval(e) for e in state["best_list"][i]))
                raise EarlyStopException(state["best_iter"][i],
                                         state["best_list"][i])
        if env.iteration == env.end_iteration - 1:
            for i, entry in enumerate(env.evaluation_result_list):
                if entry[0] == "training" or (
                        entry[0] == "cv_agg"
                        and entry[1].split(" ")[0] == "train"):
                    continue
                if verbose and state["best_list"][i] is not None:
                    log.info(
                        "Did not meet early stopping. Best iteration is:\n"
                        f"[{state['best_iter'][i] + 1}]\t"
                        + "\t".join(_fmt_eval(e) for e in state["best_list"][i]))
                raise EarlyStopException(state["best_iter"][i],
                                         state["best_list"][i])

    _callback.order = 30
    _callback.state = state           # checkpoint-visible (picklable)
    _callback._ckpt_key = "early_stopping"
    return _callback
