"""Dask integration surface (reference: python-package/lightgbm/dask.py).

The reference uses Dask to place data partitions on workers, assign ports,
and run one socket-connected training process per worker
(dask.py:115,182-412). On TPU pods that orchestration role is filled by
JAX multi-process initialization instead: run the same training script on
every host with ``num_machines``/``machines`` set (see
``lightgbm_tpu.parallel.multihost``) and the data-parallel learner shards
rows over all chips of all hosts — no separate scheduler process is needed.

These classes exist so code written against the reference's Dask API fails
with a actionable message rather than an AttributeError. If dask is
installed, ``DaskLGBM*`` could be implemented as thin wrappers that gather
partitions per host and call the multihost path; this environment does not
ship dask, so they raise.
"""
from __future__ import annotations

_MSG = (
    "Dask orchestration is not available in lightgbm_tpu. On TPU pods use "
    "jax multi-process training instead: run the same script on every host "
    "with params={'tree_learner': 'data', 'num_machines': N, "
    "'machines': 'host1:port,host2:port,...'} (see "
    "lightgbm_tpu.parallel.multihost)."
)


class _DaskUnavailable:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(_MSG)


class DaskLGBMClassifier(_DaskUnavailable):
    pass


class DaskLGBMRegressor(_DaskUnavailable):
    pass


class DaskLGBMRanker(_DaskUnavailable):
    pass
