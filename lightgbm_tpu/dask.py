"""Client-materializing Dask convenience shims — NOT distributed Dask
training (reference: python-package/lightgbm/dask.py).

Be clear about what these are (VERDICT r5 #9): the reference uses Dask to
place data partitions on workers, assign ports, and run one
socket-connected training process per worker (dask.py:115,182-412) — the
dataset never needs to fit on one machine. The ``DaskLGBM*`` classes here
do none of that. They ``compute()`` the whole collection onto the client
process and hand the local arrays to the plain sklearn estimators, so

  * a dataset larger than client RAM cannot be trained through this
    surface, and
  * the Dask cluster contributes nothing to training — it is only the
    storage/ingest layer.

They exist as API-compatible migration shims for code that already says
``DaskLGBMClassifier``. The actually-distributed path on TPU pods is JAX
multi-process initialization: run the same training script on every host
with ``num_machines``/``machines`` set (``lightgbm_tpu.parallel.
multihost``) and ``tree_learner=data`` shards rows over all chips of all
hosts — the device mesh, not the task graph, is where scale lives. When
dask is not installed the methods raise an actionable error.
"""
from __future__ import annotations

from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor

_MSG = (
    "dask is not installed. On TPU pods use jax multi-process training "
    "instead: run the same script on every host with "
    "params={'tree_learner': 'data', 'num_machines': N, "
    "'machines': 'host1:port,host2:port,...'} (see "
    "lightgbm_tpu.parallel.multihost)."
)


def _materialize(part):
    """Dask collection -> local numpy/pandas (no-op for local data)."""
    if part is None:
        return None
    if hasattr(part, "compute"):
        return part.compute()
    return part


def _is_dask(x) -> bool:
    return hasattr(x, "compute")


def _require_dask():
    try:
        import dask  # noqa: F401
    except ImportError as exc:
        raise NotImplementedError(_MSG) from exc


def _wrap_array(out, was_dask: bool):
    """dask in -> dask out; local in -> local out. (Deliberate deviation
    from the reference, which raises TypeError on non-Dask inputs
    (ref: python-package/lightgbm/dask.py _predict): accepting local data
    keeps these wrappers usable on a single TPU host where materialized
    training is the documented design, see the module docstring.)"""
    if not was_dask:
        return out
    try:
        import dask.array as da
    except ImportError:  # pragma: no cover - dask missing mid-flight
        return out
    import numpy as np
    return da.from_array(np.asarray(out))


class _DaskMixin:
    """fit/predict accept Dask arrays/dataframes/series; the collection is
    gathered to the client and training shards rows over the device mesh
    (``tree_learner=data``) — the reference's per-worker socket topology
    has no TPU equivalent worth emulating (SURVEY §7)."""

    def fit(self, X, y, sample_weight=None, init_score=None, **kwargs):
        if any(_is_dask(v) for v in (X, y, sample_weight, init_score)):
            _require_dask()
        for key in ("group", "eval_sample_weight", "eval_init_score",
                    "eval_group"):
            if key in kwargs and kwargs[key] is not None:
                v = kwargs[key]
                kwargs[key] = ([_materialize(p) for p in v]
                               if isinstance(v, (list, tuple)) else
                               _materialize(v))
        if kwargs.get("eval_set") is not None:
            kwargs["eval_set"] = [
                (_materialize(vx), _materialize(vy))
                for vx, vy in kwargs["eval_set"]]
        return super().fit(
            _materialize(X), _materialize(y),
            sample_weight=_materialize(sample_weight),
            init_score=_materialize(init_score), **kwargs)

    def predict(self, X, **kwargs):
        if _is_dask(X):
            _require_dask()
        return _wrap_array(super().predict(_materialize(X), **kwargs),
                           _is_dask(X))

    def to_local(self):
        """The reference's DaskLGBM*.to_local(): the plain estimator."""
        local_cls = next(
            c for c in type(self).__mro__
            if not (issubclass(c, _DaskMixin) or c is _DaskMixin))
        out = local_cls(**self.get_params())
        out.__dict__.update(dict(self.__dict__))
        return out


class DaskLGBMClassifier(_DaskMixin, LGBMClassifier):
    def predict_proba(self, X, **kwargs):
        if _is_dask(X):
            _require_dask()
        return _wrap_array(
            LGBMClassifier.predict_proba(self, _materialize(X), **kwargs),
            _is_dask(X))


class DaskLGBMRegressor(_DaskMixin, LGBMRegressor):
    pass


class DaskLGBMRanker(_DaskMixin, LGBMRanker):
    pass
