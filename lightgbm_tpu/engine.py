"""Training entry points: ``train()`` and ``cv()``.

Mirror of the reference's engine
(reference: python-package/lightgbm/engine.py — train :109 [callback loop +
booster.update :309-345], cv :611, CVBooster :354, early-stop handling :342).
"""
from __future__ import annotations

import collections
import contextlib
import copy
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import Config, alias_table
from .utils import log


def _setup_callbacks(params: Dict[str, Any],
                     callbacks: Optional[Sequence[Callable]]):
    """Resolve the callback set for a training run: inject auto early stopping
    (disabled in dart mode, where tree renormalization invalidates
    best_iteration truncation) and split/sort by before/after-iteration
    (reference: engine.py:262-307 callback setup in train() and cv())."""
    cbs = set(callbacks) if callbacks else set()
    cfg = Config(params)
    early_round = int(cfg.early_stopping_round or 0)
    if early_round > 0 and cfg.boosting != "dart":
        cbs.add(callback_mod.early_stopping(
            early_round, bool(params.get("first_metric_only", False)),
            min_delta=float(params.get("early_stopping_min_delta", 0.0))))
    order_key = lambda cb: getattr(cb, "order", 0)
    cbs_before = sorted(
        (cb for cb in cbs if getattr(cb, "before_iteration", False)),
        key=order_key)
    cbs_after = sorted(
        (cb for cb in cbs if not getattr(cb, "before_iteration", False)),
        key=order_key)
    return cbs_before, cbs_after


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[Sequence[Dataset]] = None,
    valid_names: Optional[Sequence[str]] = None,
    feval: Optional[Union[Callable, Sequence[Callable]]] = None,
    init_model: Optional[Union[str, Booster]] = None,
    keep_training_booster: bool = False,
    callbacks: Optional[Sequence[Callable]] = None,
) -> Booster:
    """Train a booster (reference: engine.py:109)."""
    params = copy.deepcopy(params) if params else {}
    # num_boost_round may come via params aliases (reference: engine.py:139-160)
    at = alias_table()
    for key in list(params.keys()):
        if at.get(key) == "num_iterations" and params[key] is not None:
            num_boost_round = int(params.pop(key))
    params["num_iterations"] = num_boost_round

    # one telemetry session around the WHOLE run — dataset construction
    # included (binning is a span-taxonomy phase) — held as a context
    # manager so the profiler trace closes on every error path
    # (obs/spans.trace_session; tpu_trace_mode=annotations enables span
    # names without a full profiler trace)
    from . import obs
    cfg0 = Config(params)
    trace_dir = str(cfg0.get("tpu_trace_dir", "") or "")
    trace_mode = obs.spans.resolve_trace_mode(cfg0.get("tpu_trace_mode"))
    session = (obs.spans.trace_session(trace_dir, trace_mode)
               if (trace_dir or cfg0.is_explicit("tpu_trace_mode"))
               else contextlib.nullcontext())
    # per-RUN summary baseline: the span phase-time table AND the
    # seen-span set are process-cumulative, and a second train() in the
    # same process (cv folds, sklearn refits, train-after-serve) must
    # not re-report the first run's seconds or phases; taken BEFORE
    # construction so construct-phase spans (binning) count
    obs_baseline = {"phase": obs.spans.phase_times(),
                    "seen": obs.spans.seen_counts()}
    with session:
        try:
            booster = _train_impl(params, train_set, num_boost_round,
                                  valid_sets, valid_names, feval,
                                  init_model, callbacks, obs_baseline)
        except BaseException as err:
            # the flight recorder's "any crash escaping lgb.train" dump
            # site — HERE, not around the boosting loop, so a death
            # during dataset construction / multihost bootstrap /
            # init_model load / checkpoint auto-resume still ships its
            # post-mortem (the r05 gap). ALWAYS dump: a
            # TrainingInterrupted from the boosting loop already dumped
            # inside _train_impl, and re-dumping here only extends that
            # record with the final-snapshot events — while one raised
            # BEFORE the loop (bootstrap deadline, sync barrier) would
            # otherwise leave nothing on disk.
            from .obs import flight
            from .parallel.multihost import TrainingInterrupted
            interrupted = isinstance(err, TrainingInterrupted)
            if not interrupted:
                flight.note("crash", error=repr(err)[:300])
            path = flight.dump(
                "TrainingInterrupted" if interrupted
                else f"crash: {type(err).__name__}",
                extra={"error": repr(err)[:300]})
            if path and not interrupted:
                log.warning(f"flight recorder dumped to {path}")
            raise
    # device-time trace analytics (obs/tracing.py): the profiler only
    # writes its artifact when the session CLOSES, so the parse runs
    # here — after the with-block, strictly off the training path — and
    # emits the per-phase DEVICE-time table next to the host phase table
    # the summary already carries (device_seconds vs host_seconds; a
    # reader diffing the two sees host-dispatch skew instead of
    # mistaking it for compute)
    if trace_dir and trace_mode == "full":
        _emit_device_time(booster, trace_dir, obs_baseline)
    return booster


def _emit_device_time(booster: Booster, trace_dir: str,
                      obs_baseline: Dict[str, Any]) -> None:
    """Parse the just-closed profiler artifact and emit the
    ``device_time`` metrics record. Best-effort: analytics must never
    fail a run that already trained."""
    from . import obs
    from .obs import flight, tracing
    try:
        analysis = tracing.analyze_trace_dir(trace_dir)
    except Exception as err:  # noqa: BLE001 - telemetry is best-effort
        log.warning(f"trace analytics failed for {trace_dir}: {err}")
        return
    if analysis is None:
        log.warning(f"tpu_trace_dir={trace_dir} left no xplane artifact "
                    "to analyze")
        return
    host_phases = obs.spans.phase_times_since(obs_baseline["phase"])
    stream = booster._gbdt._metrics_stream
    if stream is not None:
        stream.emit("device_time", host_phase_times=host_phases,
                    **analysis)
    decomp = analysis.get("decomposition", {})
    flight.note("device_time", source=analysis.get("source"),
                phases={k: v.get("device_seconds")
                        for k, v in analysis.get("phases", {}).items()},
                **{k: decomp.get(k) for k in ("busy_seconds",
                                              "comm_seconds",
                                              "idle_seconds")})
    booster._device_time_analysis = analysis


def _train_impl(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int,
    valid_sets: Optional[Sequence[Dataset]],
    valid_names: Optional[Sequence[str]],
    feval: Optional[Union[Callable, Sequence[Callable]]],
    init_model: Optional[Union[str, Booster]],
    callbacks: Optional[Sequence[Callable]],
    obs_baseline: Dict[str, Any],
) -> Booster:
    # continue-training: the loaded model's trees stay value-space
    # (reference: engine.py init_model -> _InnerPredictor; gbdt.cpp:250-258);
    # its raw predictions seed all cached scores and its tree blocks are
    # re-emitted ahead of the new ones at save time
    pre_model = None
    if init_model is None and params.get("input_model"):
        init_model = str(params["input_model"])
    if init_model is not None:
        from .model_io import LoadedGBDT
        if isinstance(init_model, str):
            with open(init_model) as fh:
                pre_model = LoadedGBDT(fh.read())
        else:
            pre_model = LoadedGBDT(init_model.model_to_string())

    train_set._update_params(params)
    # multi-host bootstrap must precede dataset construction (bin-mapper
    # sync) AND any backend-initializing call (reference: Network::Init
    # before LoadData, application.cpp:88)
    from .parallel.multihost import maybe_init_distributed
    maybe_init_distributed(params)
    if pre_model is not None and train_set.data is None:
        raise ValueError(
            "continue-training needs the Dataset's raw data to score the "
            "loaded model; construct the Dataset with free_raw_data=False")
    pre_train_raw = (pre_model.predict_raw_matrix(np.asarray(train_set.data))
                     if pre_model is not None else None)
    train_set.construct()
    booster = Booster(params=params, train_set=train_set)
    booster._train_data_name = "training"
    if pre_model is not None:
        booster._attach_pre_model(pre_model, pre_train_raw)

    is_valid_contain_train = False
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        for i, valid_data in enumerate(valid_sets):
            if valid_names is not None and len(valid_names) > i:
                name = valid_names[i]
            else:
                name = f"valid_{i}"
            if valid_data is train_set:
                is_valid_contain_train = True
                booster._train_data_name = name
                continue
            pre_valid_raw = None
            if pre_model is not None:
                if valid_data.data is None:
                    raise ValueError(
                        "continue-training needs raw valid data "
                        "(free_raw_data=False)")
                pre_valid_raw = pre_model.predict_raw_matrix(
                    np.asarray(valid_data.data))
            booster.add_valid(valid_data, name)
            if pre_valid_raw is not None:
                booster._seed_valid_scores(-1, pre_valid_raw)

    cbs_before, cbs_after = _setup_callbacks(params, callbacks)
    snapshot_freq = int(params.get("snapshot_freq", -1) or -1)
    snapshot_out = str(params.get("output_model", "LightGBM_model.txt"))

    # fault tolerance: full-state checkpoints + collective watchdog
    # (io/checkpoint.py, parallel/multihost.py; see config.py knobs)
    cfg = booster.config
    ckpt_dir = str(cfg.get("tpu_checkpoint_dir", "") or "")
    ckpt_freq = int(cfg.get("tpu_checkpoint_freq", 0) or 0)
    ckpt_keep = int(cfg.get("tpu_checkpoint_keep", 3) or 3)
    deadline = float(cfg.get("tpu_collective_deadline_s", 0.0) or 0.0)
    from .analysis.faultinject import active_plan
    from .parallel.multihost import TrainingInterrupted, run_with_deadline
    plan = active_plan(cfg)
    all_cbs = cbs_before + cbs_after

    def _callback_states():
        out = {}
        for cb in all_cbs:
            key = getattr(cb, "_ckpt_key", None)
            st = getattr(cb, "state", None)
            if key and isinstance(st, dict):
                out[key] = copy.deepcopy(st)
        return out

    def _write_checkpoint():
        booster.save_checkpoint(ckpt_dir, keep=ckpt_keep,
                                callback_states=_callback_states())

    start_iteration = 0
    if ckpt_dir:
        from .io import checkpoint as ckpt_mod
        found = ckpt_mod.load_latest(ckpt_dir)
        # multi-host: every rank must agree on the resume point BEFORE any
        # state is restored — a rank that cannot see the snapshot (dir not
        # on a shared filesystem, torn read) would otherwise start at 0
        # while the others start at N, desyncing every collective in the
        # step. On disagreement all ranks start fresh, which is safe.
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils as _mu
            it = -1 if found is None else int(found["iteration"])
            all_its = np.asarray(_mu.process_allgather(np.int64(it)))
            if not (all_its == all_its[0]).all():
                log.warning(
                    f"checkpoint resume iteration disagrees across ranks "
                    f"({list(map(int, all_its))}); is tpu_checkpoint_dir "
                    f"on a shared filesystem? starting fresh on all ranks")
                found = None
        if found is not None:
            try:
                booster._restore_checkpoint(found, callbacks=all_cbs)
                start_iteration = int(found["iteration"])
                log.info(f"Resuming from checkpoint at iteration "
                         f"{start_iteration} ({ckpt_dir})")
            except ValueError as err:
                log.warning(f"ignoring incompatible checkpoint in "
                            f"{ckpt_dir}: {err}")

    # telemetry (lightgbm_tpu/obs): the trace session is already held by
    # train() around this whole function; here the flight recorder and
    # the metrics stream get their run-level hooks
    from . import obs
    from .obs import flight
    mstream = booster._gbdt._metrics_stream
    if mstream is not None:
        mstream.emit("mark", name="train_begin",
                     iteration=start_iteration,
                     num_boost_round=num_boost_round)

    # scrapeable while it TRAINS: tpu_metrics_port binds the same
    # Prometheus-text endpoint the serving tier uses, serving the live
    # training tree (iteration progress, phase-keyed compiles, cache
    # counters, rank-stats aggregate incl. straggler flags) for the
    # duration of the run. Rank 0 only — one scrape target per pod, the
    # same single-writer contract as the metrics stream.
    mserver = None
    mport = int(cfg.get("tpu_metrics_port", 0) or 0)
    if mport > 0:
        import jax
        if jax.process_index() == 0:
            from .obs.metrics import MetricsServer
            try:
                mserver = MetricsServer(booster._gbdt.train_metrics_tree,
                                        port=mport)
                log.info(f"training metrics endpoint on "
                         f":{mserver.port} (/metrics, /healthz)")
            except OSError as err:
                log.warning(
                    f"cannot bind tpu_metrics_port={mport}: {err}; "
                    "training continues unscrapeable")

    def _flight_dump(reason: str, err: BaseException) -> None:
        # the TrainingInterrupted dump site; other crashes dump from the
        # train() wrapper, which covers construction/resume too
        flight.note("training_interrupted", error=repr(err)[:300])
        path = flight.dump(reason, extra={"error": repr(err)[:300]})
        if path:
            log.warning(f"flight recorder dumped to {path}")

    try:
        evaluation_result_list: List = []
        for i in range(start_iteration, num_boost_round):
            for cb in cbs_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=None))
            plan.fire("iteration", iteration=i)
            if deadline > 0:
                # collective watchdog: a hung distributed step surfaces as
                # a structured TrainingInterrupted (handled below with a
                # final snapshot) instead of stalling the pod silently
                def _step(i=i):
                    plan.fire("step", iteration=i)
                    return booster.update()
                finished = run_with_deadline(
                    _step, deadline, f"boosting iteration {i}")
            else:
                plan.fire("step", iteration=i)
                finished = booster.update()

            evaluation_result_list = []
            if (valid_sets is not None and (booster._valid_names
                                            or is_valid_contain_train)) or feval:
                if is_valid_contain_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                evaluation_result_list = e.best_score or []
                break
            # periodic model snapshots (reference: GBDT::Train, gbdt.cpp:250-254
            # -> model.txt.snapshot_iter_N every snapshot_freq iterations).
            # The save flushes pending device trees; capture its stop signal
            # instead of discarding it (a no-split iteration pops its trees)
            if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
                finished = booster._gbdt._flush_trees() or finished
                booster.save_model(f"{snapshot_out}.snapshot_iter_{i + 1}")
            # full-state checkpoint tick: the ONE planned device->host
            # fetch outside stop checks (atomic write, keep-last-k). The
            # flight ring rides along — a later SIGKILL leaves the events
            # as of the last durable snapshot on disk
            if ckpt_dir and ckpt_freq > 0 and (i + 1) % ckpt_freq == 0:
                finished = booster._gbdt._flush_trees() or finished
                _write_checkpoint()
                flight.dump(f"checkpoint tick @ iteration {i + 1}")
            if finished:
                log.info("Finished training (no further splits possible)")
                break

    except TrainingInterrupted as err:
        # a deadline fired (hung collective / preempted peer): write a
        # best-effort final snapshot, then surface the structured error.
        # The snapshot itself runs under a deadline — when the hung step
        # still holds the booster lock or the device state is
        # unfetchable, resume falls back to the last periodic snapshot.
        # The flight dump ships the post-mortem either way.
        _flight_dump("TrainingInterrupted", err)
        if ckpt_dir:
            try:
                run_with_deadline(_write_checkpoint,
                                  max(deadline, 30.0),
                                  "final interrupt snapshot")
                log.warning(f"training interrupted ({err}); final "
                            f"snapshot written to {ckpt_dir}")
            except BaseException as snap_err:  # noqa: BLE001 - best effort
                log.warning(f"training interrupted ({err}); final "
                            f"snapshot failed: {snap_err}")
        raise
    finally:
        if mserver is not None:
            mserver.stop()
        if mstream is not None:
            from .analysis import guards
            # spans_seen: sites newly ENTERED during this run — host
            # spans plus programs traced this run. A program reused from
            # the process jit cache (module-level grow_tree across
            # boosters) was named at its original trace and does not
            # re-enter; the cumulative registry is spans.seen_spans()
            mstream.emit(
                "summary",
                iteration=booster._gbdt.iter_,
                phase_times=obs.spans.phase_times_since(
                    obs_baseline["phase"]),
                spans_seen=sorted(obs.spans.seen_since(
                    obs_baseline["seen"])),
                compiles=guards.phase_compile_counts(),
                cache=guards.global_cache_counts())
    # record final scores (reference: engine.py:346-352)
    if evaluation_result_list:
        best: Dict[str, Dict[str, float]] = collections.OrderedDict()
        for name, metric, value, _ in evaluation_result_list:
            best.setdefault(name, collections.OrderedDict())[metric] = value
        booster.best_score = best
    return booster


class CVBooster:
    """Container of per-fold boosters (reference: engine.py:354)."""

    def __init__(self, boosters: Optional[List[Booster]] = None):
        self.boosters = boosters or []
        self.best_iteration = -1

    def append(self, booster: Booster) -> "CVBooster":
        self.boosters.append(booster)
        return self

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool,
                  group: Optional[np.ndarray]):
    full_data.construct()
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if group is not None:
        # group-aware folds: whole queries per fold (reference: engine.py:436)
        ngroups = len(group)
        gidx = np.arange(ngroups)
        if shuffle:
            rng.shuffle(gidx)
        gfolds = np.array_split(gidx, nfold)
        boundaries = np.concatenate([[0], np.cumsum(group)])
        folds = []
        for gf in gfolds:
            rows = np.concatenate(
                [np.arange(boundaries[g], boundaries[g + 1]) for g in gf]) \
                if len(gf) else np.array([], dtype=np.int64)
            folds.append(np.sort(rows))
    elif stratified:
        label = np.asarray(full_data.get_label())
        folds = [[] for _ in range(nfold)]
        for cls in np.unique(label):
            idx = np.where(label == cls)[0]
            if shuffle:
                rng.shuffle(idx)
            for i, part in enumerate(np.array_split(idx, nfold)):
                folds[i].append(part)
        folds = [np.sort(np.concatenate(f)) for f in folds]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        folds = [np.sort(f) for f in np.array_split(idx, nfold)]
    return folds


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics: Optional[Union[str, Sequence[str]]] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    seed: int = 0,
    callbacks: Optional[Sequence[Callable]] = None,
    eval_train_metric: bool = False,
    return_cvbooster: bool = False,
) -> Dict[str, List[float]]:
    """K-fold cross-validation (reference: engine.py:611)."""
    params = copy.deepcopy(params) if params else {}
    if metrics is not None:
        params["metric"] = metrics
    at = alias_table()
    for key in list(params.keys()):
        if at.get(key) == "num_iterations" and params[key] is not None:
            num_boost_round = int(params.pop(key))

    train_set.construct()
    objective = params.get("objective", "regression")
    if stratified and (not isinstance(objective, str)
                       or "binary" not in str(objective)
                       and "multiclass" not in str(objective)):
        stratified = False

    data = train_set._inner
    raw = None
    if train_set.data is not None:
        raw = np.asarray(train_set.data, dtype=np.float64)
    else:
        raise ValueError("cv() needs the raw data; construct the Dataset with "
                         "free_raw_data=False or pass data directly")
    label = np.asarray(train_set.get_label())
    weight = train_set.get_weight()
    group = train_set.get_group()

    if folds is None:
        folds_idx = _make_n_folds(train_set, nfold, params, seed, stratified,
                                  shuffle, group)
        folds = []
        all_idx = np.arange(train_set.num_data())
        for te in folds_idx:
            tr = np.setdiff1d(all_idx, te, assume_unique=False)
            folds.append((tr, te))
    elif hasattr(folds, "split"):
        folds = list(folds.split(raw, label, groups=None))

    cvbooster = CVBooster()
    fold_params = {k: v for k, v in params.items()}
    for tr, te in folds:
        def subset(idx):
            w = None if weight is None else np.asarray(weight)[idx]
            g = None
            if group is not None:
                # recompute group sizes from membership (queries kept whole)
                boundaries = np.concatenate([[0], np.cumsum(group)])
                qid = np.searchsorted(boundaries, idx, side="right") - 1
                _, counts = np.unique(qid, return_counts=True)
                g = counts
            return Dataset(raw[idx], label=label[idx], weight=w, group=g,
                           params=params, free_raw_data=False)
        dtr = subset(tr)
        dte = dtr.create_valid(raw[te], label=label[te],
                               weight=None if weight is None
                               else np.asarray(weight)[te])
        if group is not None:
            boundaries = np.concatenate([[0], np.cumsum(group)])
            qid = np.searchsorted(boundaries, te, side="right") - 1
            _, counts = np.unique(qid, return_counts=True)
            dte.set_group(counts)
        dtr._update_params(fold_params)
        dtr.construct()
        bst = Booster(params=fold_params, train_set=dtr)
        bst._train_data_name = "train"
        bst.add_valid(dte, "valid")
        cvbooster.append(bst)

    # all folds advance together one iteration at a time so per-iteration
    # fold means/stdvs are recorded and early stopping acts on the CV
    # aggregate (reference: engine.py:611 cv loop + _agg_cv_result)
    cbs_before, cbs_after = _setup_callbacks(params, callbacks)

    results: Dict[str, List[float]] = collections.OrderedDict()
    for i in range(num_boost_round):
        for cb in cbs_before:
            cb(callback_mod.CallbackEnv(
                model=cvbooster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        for bst in cvbooster.boosters:
            bst.update()
        merged: Dict = collections.OrderedDict()
        for bst in cvbooster.boosters:
            entries = []
            if eval_train_metric:
                entries.extend(bst.eval_train(feval))
            entries.extend(bst.eval_valid(feval))
            for name, metric, value, hib in entries:
                merged.setdefault((name, metric, hib), []).append(value)
        agg_list = []
        for (name, metric, hib), vals in merged.items():
            key = f"{name} {metric}"
            results.setdefault(f"{key}-mean", []).append(float(np.mean(vals)))
            results.setdefault(f"{key}-stdv", []).append(float(np.std(vals)))
            # same shape the reference hands to callbacks: ("cv_agg", ...)
            agg_list.append(("cv_agg", key, float(np.mean(vals)), hib))
        try:
            for cb in cbs_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg_list))
        except callback_mod.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for bst in cvbooster.boosters:
                bst.best_iteration = cvbooster.best_iteration
            for key in list(results):
                results[key] = results[key][:cvbooster.best_iteration]
            break

    out: Dict[str, Any] = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
