"""Public ``Dataset`` / ``Booster`` API.

Mirror of the reference's Python binding surface
(reference: python-package/lightgbm/basic.py — class Dataset :1900+
[`construct` :2517, `_lazy_init` :2102, `create_valid` :2454], class Booster
:3586 [`update` :4092, `predict` :4701, `rollback_one_iter`, `eval` family,
`save_model`, `feature_importance`]).

Unlike the reference there is no C API / ctypes boundary: the Booster drives the
JAX GBDT directly (boosting/gbdt.py). The binned dataset and all scores live in
TPU HBM; this layer only does host-side bookkeeping.
"""
from __future__ import annotations

import copy
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Union
from typing import Sequence as _Seq

import numpy as np

from .config import Config, alias_table
from .io.dataset import BinnedDataset, Metadata
from .metrics import create_metrics
from .objectives import create_objective
from .utils import log
from .utils.rwlock import RWLock, read_locked, write_locked

_ArrayLike = Any


class Sequence:
    """Generic random-access data interface for streaming Dataset
    construction (reference: lightgbm.Sequence, python-package/lightgbm/
    basic.py:915). Subclasses implement ``__getitem__`` (int -> one row
    [F]; slice -> batch [K, F]) and ``__len__``; ``batch_size`` controls
    the streaming read granularity. The raw [N, F] matrix is never
    materialized — sampling uses random row access, construction reads
    ``batch_size`` rows at a time."""

    batch_size = 4096

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError("Sequence subclasses implement __getitem__")

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError("Sequence subclasses implement __len__")


def _as_sequences(data):
    """data as a list of Sequence objects, or None when not Sequence-like."""
    if isinstance(data, Sequence):
        return [data]
    if isinstance(data, (list, tuple)) and data \
            and all(isinstance(s, Sequence) for s in data):
        return list(data)
    return None


class Dataset:
    """Training/validation data container (reference: Dataset, basic.py:1900).

    Lazily constructed: binning happens at ``construct()`` (first use by
    ``train``), so parameters passed at Booster creation can still influence it
    — same two-phase design as the reference.
    """

    def __init__(
        self,
        data: _ArrayLike,
        label: Optional[_ArrayLike] = None,
        reference: Optional["Dataset"] = None,
        weight: Optional[_ArrayLike] = None,
        group: Optional[_ArrayLike] = None,
        init_score: Optional[_ArrayLike] = None,
        feature_name: Union[str, _Seq[str]] = "auto",
        categorical_feature: Union[str, _Seq] = "auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = True,
        position: Optional[_ArrayLike] = None,
    ):
        # shared-state discipline (reference: the C API's yamc shared mutex,
        # src/c_api.cpp:163): public methods below are @read_locked /
        # @write_locked against this lock; tpulint R007 enforces coverage
        self._api_lock = RWLock()
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) if params else {}
        self.free_raw_data = free_raw_data
        self.position = position
        self._inner: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None

    # binning-relevant parameters a Booster forwards into a not-yet-constructed
    # Dataset (reference: Dataset._update_params, python-package basic.py —
    # train()/Booster() push their params into the lazily-built Dataset)
    _DATASET_PARAM_KEYS = (
        "max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
        "use_missing", "zero_as_missing", "data_random_seed",
        "feature_pre_filter", "max_bin_by_feature", "linear_tree",
        "forcedbins_filename", "enable_bundle", "max_conflict_rate")

    def _update_params(self, params: Optional[Dict[str, Any]]) -> "Dataset":
        """Merge binning params from a Booster into a not-yet-constructed
        Dataset — training params win, matching the reference's
        ``self.params.update(params)`` (reference: Dataset._update_params,
        python-package/lightgbm/basic.py). Once constructed, differing values
        only warn."""
        if not params:
            return self
        at = alias_table()
        # canonical names take priority over their aliases when both appear
        # (reference: _ConfigAliases / Config::Set alias resolution)
        incoming = {}
        for pass_aliases in (True, False):
            for key, value in params.items():
                canon = at.get(key, key)
                is_alias = key != canon
                if canon in self._DATASET_PARAM_KEYS and is_alias == pass_aliases:
                    incoming[canon] = value
        if not incoming:
            return self
        if self._inner is None:
            self.params.update(incoming)
        else:
            cfg = Config(self.params)
            for key, value in incoming.items():
                current = cfg.get(key)
                if Config({key: value}).get(key) != current:
                    log.warning(
                        f"Dataset was already constructed with {key}="
                        f"{current!r}; training parameter {key}={value!r} is "
                        "ignored (reconstruct the Dataset to change binning)")
        return self

    # -- construction --------------------------------------------------------
    @write_locked
    def construct(self) -> "Dataset":
        """(reference: Dataset.construct, basic.py:2517)"""
        if self._inner is not None:
            return self
        cfg = Config(self.params)
        if isinstance(self.data, str) and (self.data.endswith(".npz")
                                           or self.data.endswith(".bin")):
            # binary dataset reload (reference: DatasetLoader::LoadFromBinFile)
            self._inner = BinnedDataset.load_binary(self.data)
            md = self._inner.metadata
            if self.label is not None:
                md.set_label(_maybe_series(self.label))
            if self.weight is not None:
                md.set_weight(_maybe_series(self.weight))
            if self.group is not None:
                md.set_group(self.group)
            if self.init_score is not None:
                md.set_init_score(self.init_score)
            if self.position is not None:
                md.set_position(self.position)
            if self.free_raw_data:
                self.data = None
            return self
        ref_inner = None
        if self.reference is not None:
            self.reference.construct()
            ref_inner = self.reference._inner
        feature_names = (
            None if self.feature_name == "auto" else list(self.feature_name))
        cat = (None if self.categorical_feature == "auto"
               else self.categorical_feature)
        seqs = _as_sequences(self.data)
        if seqs is not None:
            self._inner = BinnedDataset.construct_from_sequences(
                seqs,
                max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                categorical_feature=cat,
                feature_names=feature_names,
                data_random_seed=cfg.get("data_random_seed", 1),
                reference=ref_inner,
                forcedbins_filename=str(
                    cfg.get("forcedbins_filename", "") or ""),
                max_bin_by_feature=cfg.get("max_bin_by_feature"),
                enable_bundle=bool(cfg.get("enable_bundle", True)),
                max_conflict_rate=float(cfg.get("max_conflict_rate", 1e-4)),
            )
            self._finish_metadata()
            if self.free_raw_data:
                self.data = None
            return self
        self._inner = BinnedDataset.construct(
            self.data,
            max_bin=cfg.max_bin,
            min_data_in_bin=cfg.min_data_in_bin,
            bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            categorical_feature=cat,
            feature_names=feature_names,
            data_random_seed=cfg.get("data_random_seed", 1),
            reference=ref_inner,
            # linear leaves fit against raw values (reference keeps raw data
            # when linear_tree is set, dataset.h raw_data_)
            keep_raw=not self.free_raw_data
            or bool(cfg.get("linear_tree", False)),
            forcedbins_filename=str(cfg.get("forcedbins_filename", "") or ""),
            max_bin_by_feature=cfg.get("max_bin_by_feature"),
            enable_bundle=bool(cfg.get("enable_bundle", True)),
            max_conflict_rate=float(
                cfg.get("max_conflict_rate", 1e-4)),
        )
        self._finish_metadata()
        if self.free_raw_data:
            self.data = None
        return self

    def _finish_metadata(self) -> None:
        md = self._inner.metadata
        if self.label is not None:
            md.set_label(_maybe_series(self.label))
        md.set_weight(_maybe_series(self.weight))
        if self.group is not None:
            md.set_group(self.group)
        md.set_init_score(self.init_score)
        md.set_position(self.position)

    @write_locked
    def subset(self, used_indices, params=None) -> "Dataset":
        """Row-subset Dataset sharing this dataset's bin mappers
        (reference: Dataset.subset, python-package basic.py ->
        LGBM_DatasetGetSubset, c_api.cpp; used by cv folds and sklearn).

        The parent must be constructed; the subset re-uses its binned rows
        directly (no re-binning), so bin boundaries match exactly."""
        self.construct()
        # sorted unique indices: group reconstruction and row extraction
        # must agree on order (the reference sorts used_indices the same way)
        idx = np.unique(np.asarray(used_indices, np.int64).reshape(-1))
        inner = self._inner
        sub = Dataset.__new__(Dataset)   # bypasses __init__: lock it here
        sub._api_lock = RWLock()
        sub.data = None
        sub.label = None
        sub.reference = self
        sub.weight = None
        sub.group = None
        sub.init_score = None
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.params = copy.deepcopy(params or self.params)
        sub.free_raw_data = self.free_raw_data
        sub.position = None
        sub.used_indices = idx
        si = BinnedDataset()
        si.binned = inner.binned[idx]
        si.bundle_info = inner.bundle_info
        si.mappers = inner.mappers
        si.feature_names = inner.feature_names
        si.max_num_bins = inner.max_num_bins
        si.num_data = len(idx)
        si.num_total_features = inner.num_total_features
        si.used_features = inner.used_features
        si.categorical_features = inner.categorical_features
        if inner.raw_data is not None:
            si.raw_data = inner.raw_data[idx]
        md = Metadata(len(idx))
        src = inner.metadata
        if src.label is not None:
            md.set_label(src.label[idx])
        if src.weight is not None:
            md.set_weight(src.weight[idx])
        if src.init_score is not None:
            isc = np.asarray(src.init_score)
            md.set_init_score(isc[idx] if isc.ndim == 2
                              else (isc[idx] if isc.size == src.num_data
                                    else isc.reshape(-1, src.num_data)
                                    [:, idx].reshape(-1)))
        if src.position is not None:
            md.set_position(src.position[idx])
        if src.query_boundaries is not None:
            # rebuild per-query sizes from the selected rows; a subset that
            # splits a query apart cannot keep valid ranking structure
            # (reference: Metadata partitioning, CheckOrPartition)
            qb = src.query_boundaries
            qid = np.searchsorted(qb, idx, side="right") - 1
            sizes = np.bincount(qid, minlength=len(qb) - 1)
            full = np.diff(qb)
            partial = (sizes > 0) & (sizes != full)
            if partial.any():
                raise ValueError(
                    "Dataset.subset would split query groups "
                    f"{np.nonzero(partial)[0][:5].tolist()}...; ranking "
                    "subsets must select whole queries")
            md.set_group(sizes[sizes > 0])
        si.metadata = md
        sub._inner = si
        return sub

    @read_locked
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None, position=None) -> "Dataset":
        """(reference: Dataset.create_valid, basic.py:2454)"""
        return Dataset(
            data, label=label, reference=self, weight=weight, group=group,
            init_score=init_score, params=params or self.params,
            free_raw_data=self.free_raw_data, position=position)

    # -- setters (reference: set_field family) -------------------------------
    @write_locked
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None:
            self._inner.metadata.set_label(_maybe_series(label))
        return self

    @write_locked
    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_weight(_maybe_series(weight))
        return self

    @write_locked
    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_group(group)
        return self

    @write_locked
    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_init_score(init_score)
        return self

    @write_locked
    def set_position(self, position) -> "Dataset":
        self.position = position
        if self._inner is not None:
            self._inner.metadata.set_position(position)
        return self

    @write_locked
    def save_binary(self, filename: str) -> "Dataset":
        """Persist the constructed dataset (reference: Dataset.save_binary ->
        LGBM_DatasetSaveBinary; reload by passing the file path as data)."""
        self.construct()
        self._inner.save_binary(filename)
        return self

    @read_locked
    def get_label(self):
        if self._inner is not None and self._inner.metadata.label is not None:
            return self._inner.metadata.label
        return self.label

    @read_locked
    def get_weight(self):
        if self._inner is not None:
            return self._inner.metadata.weight
        return self.weight

    @read_locked
    def get_group(self):
        if self._inner is not None:
            return self._inner.metadata.group
        return self.group

    @read_locked
    def get_init_score(self):
        if self._inner is not None:
            return self._inner.metadata.init_score
        return self.init_score

    @read_locked
    def get_field(self, name):
        getter = {"label": self.get_label, "weight": self.get_weight,
                  "group": self.get_group, "init_score": self.get_init_score}
        if name not in getter:
            raise KeyError(name)
        return getter[name]()

    @write_locked
    def set_field(self, name, value):
        setter = {"label": self.set_label, "weight": self.set_weight,
                  "group": self.set_group, "init_score": self.set_init_score,
                  "position": self.set_position}
        if name not in setter:
            raise KeyError(name)
        return setter[name](value)

    @read_locked
    def num_data(self) -> int:
        if self._inner is not None:
            return self._inner.num_data
        seqs = _as_sequences(self.data)
        if seqs is not None:
            return int(sum(len(s) for s in seqs))
        arr = np.asarray(self.data if not hasattr(self.data, "values")
                         else self.data.values)
        return arr.shape[0]

    @read_locked
    def num_feature(self) -> int:
        if self._inner is not None:
            return self._inner.num_total_features
        seqs = _as_sequences(self.data)
        if seqs is not None:
            probe = next((s for s in seqs if len(s)), None)
            return (int(np.asarray(probe[0]).reshape(-1).shape[0])
                    if probe is not None else 0)
        arr = np.asarray(self.data if not hasattr(self.data, "values")
                         else self.data.values)
        return arr.shape[1] if arr.ndim == 2 else 1

    @write_locked
    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)


def _maybe_series(x):
    if x is None:
        return None
    if hasattr(x, "tocsr") and hasattr(x, "toarray"):  # scipy.sparse
        return x.toarray()
    if hasattr(x, "values"):
        return np.asarray(x.values)
    return np.asarray(x)


class Booster:
    """The trained/training model handle (reference: Booster, basic.py:3586)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
    ):
        # every public method below holds this as reader or writer — the
        # reference's per-handle shared mutex (src/c_api.cpp:163); fixes
        # the predict/update race on the device-tree cache
        self._api_lock = RWLock()
        params = copy.deepcopy(params) if params else {}
        self.params = params
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_data_name = "training"
        self._custom_objective: Optional[Callable] = None
        self._pending_finish = False
        # device-time trace analytics (obs/tracing.py): set by
        # engine.train after a full trace session closes; None means no
        # artifact was recorded/parseable for this booster's run
        self._device_time_analysis = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be a Dataset instance")
            train_set._update_params(params)
            # multi-host bootstrap must precede dataset construction: bin
            # mappers are synced across processes at construct time
            # (reference: Network::Init runs before LoadData,
            # application.cpp:88)
            from .parallel.multihost import maybe_init_distributed
            maybe_init_distributed(params)
            train_set.construct()
            self.config = Config(params)
            objective = self.config.objective
            if callable(objective):
                self._custom_objective = objective
                objective = None
                obj = None
            else:
                obj = create_objective(objective, self.config)
            from .boosting import create_boosting
            self._gbdt = create_boosting(self.config, train_set._inner, obj)
            self.train_set = train_set
            self._gbdt.set_train_metrics(
                create_metrics(self.config.metric, self.config))
            self._valid_names: List[str] = []
        elif model_file is not None or model_str is not None:
            from .model_io import load_booster
            if model_file is not None:
                with open(model_file) as f:
                    model_str = f.read()
            load_booster(self, model_str, params)
        else:
            raise ValueError(
                "need at least one of train_set, model_file and model_str")

    # -- continue-training (reference: init_model -> gbdt.cpp:250-258) ------
    def _attach_pre_model(self, pre_model, pre_train_raw: np.ndarray) -> None:
        """Seed cached train scores with a loaded model's raw predictions and
        keep its value-space trees for prediction/saving."""
        self._pre_model = pre_model
        g = self._gbdt
        k, n = pre_train_raw.shape
        import jax.numpy as jnp
        if k != g.num_tree_per_iteration:
            raise ValueError(
                f"init_model has {k} trees/iteration, training config has "
                f"{g.num_tree_per_iteration}")
        g.train_score = g.train_score.at[:, :n].add(jnp.asarray(pre_train_raw))
        # suppress boost_from_average: scores already carry the loaded model
        g._has_init_score = True

    def _seed_valid_scores(self, which: int, pre_raw: np.ndarray) -> None:
        import jax.numpy as jnp
        vs = self._gbdt.valid_sets[which]
        vs.score = vs.score.at[:, : pre_raw.shape[1]].add(jnp.asarray(pre_raw))

    @read_locked
    def refit(self, data, label, decay_rate: Optional[float] = None,
              weight=None, **kwargs) -> "Booster":
        """Re-fit all leaf values on new data, keeping tree structures
        (reference: Booster.refit, basic.py -> GBDT::RefitTree gbdt.cpp:258:
        gradients computed once per iteration at the running score, and each
        leaf's value becomes decay*old + (1-decay)*shrinkage*(-ThL1(G)/(H+l2)))."""
        from .model_io import LoadedGBDT, loaded_to_string
        if kwargs:
            raise TypeError(
                f"refit got unsupported arguments: {sorted(kwargs)}")
        if decay_rate is None:
            decay_rate = float((self.config.get("refit_decay_rate", 0.9)
                                if self.config else 0.9))
        cfg = self.config or Config(self.params or {})
        lam1 = float(cfg.get("lambda_l1", 0.0))
        lam2 = float(cfg.get("lambda_l2", 0.0))
        loaded = LoadedGBDT(self.model_to_string())
        obj = loaded.objective
        if obj is None:
            raise ValueError("refit requires a model with a known objective")
        import jax.numpy as jnp
        X = np.asarray(_maybe_series(data), np.float64)
        y = np.asarray(_maybe_series(label), np.float64)
        md = Metadata(len(y))
        md.set_label(y)
        md.set_weight(_maybe_series(weight))
        obj.init(md, len(y))
        k = loaded.num_tree_per_iteration
        score = np.zeros((k, len(y)), np.float64)
        for it in range(len(loaded.models) // k):
            # gradients once per iteration (reference: gbdt.cpp:279-281)
            sc = score[0] if k == 1 else score
            g, h = obj.get_gradients(jnp.asarray(sc, jnp.float32))
            g = np.asarray(g, np.float64).reshape(k, -1)
            h = np.asarray(h, np.float64).reshape(k, -1)
            for cls in range(k):
                t = loaded.models[it * k + cls]
                leaf = t.route(X)
                nl = t.num_leaves
                gs = np.bincount(leaf, weights=g[cls], minlength=nl)
                hs = np.bincount(leaf, weights=h[cls], minlength=nl)
                thr = np.sign(gs) * np.maximum(np.abs(gs) - lam1, 0.0)
                new_val = -thr / (hs + lam2 + 1e-15) * t.shrinkage
                t.leaf_value = (decay_rate * t.leaf_value
                                + (1.0 - decay_rate) * new_val)
                score[cls] += t.leaf_value[leaf]
        return Booster(model_str=loaded_to_string(loaded))

    # -- training ------------------------------------------------------------
    @write_locked
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        """(reference: Booster.add_valid, basic.py:3963)"""
        if not isinstance(data, Dataset):
            raise TypeError("Validation data should be a Dataset instance")
        # validation data MUST share the training BinMappers or tree split
        # bins would be meaningless on it (reference: Dataset._set_reference,
        # basic.py — train() rebinds valid sets to the train set silently)
        if data.reference is not self.train_set:
            if data._inner is not None \
                    and data._inner.mappers is self.train_set._inner.mappers:
                pass  # already constructed against the right mappers
            elif data.data is None and data._inner is not None:
                raise ValueError(
                    "validation Dataset was constructed without "
                    "reference=train_set and its raw data was freed; "
                    "create it with train_set.create_valid(...) or "
                    "free_raw_data=False")
            else:
                data.reference = self.train_set
                data._inner = None  # force re-binning with train mappers
        data.construct()
        metrics = create_metrics(self.config.metric, self.config)
        self._gbdt.add_valid(data._inner, name, metrics)
        self._valid_names.append(name)
        return self

    @write_locked
    def update(self, train_set: Optional[Dataset] = None,
               fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration; True if no further splits were possible
        (reference: Booster.update, basic.py:4092)."""
        if train_set is not None:
            raise NotImplementedError(
                "changing train_set on update is not supported")
        from .analysis.guards import compile_phase
        fobj = fobj or self._custom_objective
        t0 = time.perf_counter()
        # every compile inside an update is attributed to the train_step
        # phase (guards.compile_counter by_phase, the metrics plane, and
        # the flight recorder all key on it)
        with compile_phase("train_step"):
            if fobj is not None:
                grad, hess = _call_custom_objective(fobj, self)
                finished = self._gbdt.train_one_iter(grad, hess)
            else:
                finished = self._gbdt.train_one_iter()
        # sampled per-rank attribution (obs/ranks.py): at the
        # tpu_rank_stats_every cadence ONLY, block on the step's device
        # work so step_s is a real measurement (not dispatch), then let
        # the rank-stats plane probe the collective and publish;
        # off-sample iterations take neither the block nor the probe, so
        # the steady-state 0-d2h guard holds between samples. The tick's
        # seconds are captured BEFORE sample_step: the sampling overhead
        # (barrier wait for a slow peer, the rank-0 KV gather) must not
        # inflate the metrics stream's iteration wall
        rank_stats = getattr(self._gbdt, "_rank_stats", None)
        if rank_stats is not None and rank_stats.due(self._gbdt.iter_):
            import jax
            jax.block_until_ready(self._gbdt.train_score)
            elapsed = time.perf_counter() - t0
            rank_stats.sample_step(self._gbdt.iter_, elapsed)
        else:
            elapsed = time.perf_counter() - t0
        self._gbdt._obs_iteration_tick(elapsed)
        # a stop detected by a mid-training flush (e.g. in reset_parameter)
        pending, self._pending_finish = self._pending_finish, False
        return finished or pending

    @write_locked
    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    @write_locked
    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """(reference: Booster.reset_parameter → GBDT::ResetConfig gbdt.cpp:795)"""
        self.params.update(params)
        self.config.set(params)
        gbdt = self._gbdt
        gbdt.learning_rate = float(self.config.learning_rate)
        gbdt.shrinkage_rate = gbdt.learning_rate
        old_gp = gbdt.grower_params
        from .boosting.gbdt import bucketed_tree_shape
        from .engines import registry as engine_registry
        # re-resolve EVERY engine knob through the registry from the
        # JUST-updated config, not the _setup_train-era attributes —
        # reset_parameter({"tpu_step_buckets": "off"}) must actually take
        # the exact-keyed escape hatch and a hist-overlap/mbatch/layout
        # toggle must not be a silent no-op. prior= reuses the run's
        # IN-MEMORY autotune decision verbatim: no cache file I/O in the
        # training loop (the stock learning-rate callback calls this
        # every iteration), and the measured engine can neither vanish
        # (unwritable cache) nor flip (cache rewritten by another
        # process) under a live run
        resolved = engine_registry.resolve(
            self.config, shape=getattr(gbdt, "_engine_shape", None),
            allow_sweep=False,
            prior=getattr(gbdt, "_engine_resolution", None))
        gbdt._engine_resolution = resolved
        gbdt._step_buckets = resolved.step_buckets
        key_leaves, key_depth = bucketed_tree_shape(
            gbdt._step_buckets,
            int(self.config.num_leaves), int(self.config.max_depth))
        gbdt._max_depth_cfg = int(self.config.max_depth)
        resolved_fb = resolved.fused_block
        clamp_ctx = getattr(gbdt, "_fused_clamp_ctx", None)
        if resolved_fb and clamp_ctx:
            # the compact row layout is already built: re-run the SAME
            # record-width scoped-VMEM clamp _setup_compact_state applied
            resolved_fb = engine_registry.clamp_fused_block(
                resolved_fb, clamp_ctx["num_cols"], resolved.hist_mbatch,
                resolved.hist_layout, num_bins=clamp_ctx["num_bins"],
                num_features=clamp_ctx["num_features"],
                env_override=os.environ.get("LGBM_TPU_FUSED_BS", ""))
        gbdt.grower_params = gbdt.grower_params._replace(
            num_leaves=key_leaves,
            max_depth=key_depth,
            step_buckets=gbdt._step_buckets,
            hist_overlap=resolved.hist_overlap,
            hist_impl=resolved.hist_impl,
            hist_mbatch=resolved.hist_mbatch,
            hist_layout=resolved.hist_layout,
            fused_block=resolved_fb,
            lambda_l1=float(self.config.lambda_l1),
            lambda_l2=float(self.config.lambda_l2),
            min_data_in_leaf=float(self.config.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(self.config.min_sum_hessian_in_leaf),
            min_gain_to_split=float(self.config.min_gain_to_split),
            max_delta_step=float(self.config.max_delta_step),
        )
        gbdt.max_leaves = int(self.config.num_leaves)
        gbdt.feature_fraction = float(self.config.feature_fraction)
        if gbdt.grower_params != old_gp:
            # the step fns close over grower_params; rebuild only on actual
            # change — learning_rate (the common per-iteration schedule) is a
            # runtime argument, and an unconditional invalidation would force
            # an XLA recompile every iteration
            gbdt._step_fn = None
            if getattr(gbdt, "_compact", None) is not None:
                # flush trees grown under the old num_leaves first so the
                # pending-tree stack never mixes shapes; a no-split stop
                # detected here must reach the engine loop, not be dropped
                self._pending_finish = gbdt._flush_trees() or \
                    self._pending_finish
                gbdt._compact["step"] = None
        return self

    # -- checkpoint / resume (io/checkpoint.py) ------------------------------
    def _capture_checkpoint(self, callback_states: Optional[Dict] = None
                            ) -> Dict[str, Any]:
        """Complete training-state snapshot dict (gbdt state + the
        booster-level early-stopping bests + engine callback states)."""
        state = self._gbdt.capture_training_state()
        state["best_iteration"] = int(self.best_iteration)
        state["best_score"] = copy.deepcopy(self.best_score)
        if callback_states:
            state["callbacks"] = callback_states
        return state

    @read_locked
    def save_checkpoint(self, directory: str, keep: int = 3,
                        callback_states: Optional[Dict] = None):
        """Write an atomic training snapshot to ``directory``.

        Pending device trees flush first (one batched transfer), then the
        complete state lands via write-temp-fsync-rename with a checksum
        and keep-last-``keep`` rotation (io/checkpoint.py). Multi-host:
        every process participates in the (collective) state fetch but
        only process 0 writes — all ranks resume from the one file.
        Returns the snapshot path (None on non-writing ranks)."""
        from .io.checkpoint import write_snapshot
        self._gbdt._flush_trees()
        state = self._capture_checkpoint(callback_states)
        import jax
        if jax.process_index() != 0:
            return None
        return write_snapshot(directory, int(state["iteration"]), state,
                              keep=keep)

    @write_locked
    def _restore_checkpoint(self, state: Dict[str, Any],
                            callbacks=None) -> None:
        """Rebind this booster to a snapshot (raises ValueError when the
        snapshot is structurally incompatible with this run)."""
        reason = self._gbdt.snapshot_compatible(state)
        if reason is not None:
            raise ValueError(reason)
        self._gbdt.restore_training_state(state)
        self.best_iteration = int(state.get("best_iteration", -1))
        self.best_score = state.get("best_score", {}) or {}
        saved = state.get("callbacks") or {}
        for cb in callbacks or ():
            key = getattr(cb, "_ckpt_key", None)
            cb_state = getattr(cb, "state", None)
            if key and key in saved and isinstance(cb_state, dict):
                cb_state.clear()
                cb_state.update(copy.deepcopy(saved[key]))

    # -- evaluation ----------------------------------------------------------
    @write_locked
    def eval_train(self, feval=None):
        out = self._gbdt.eval_train()
        out = [(self._train_data_name, m, v, hb) for (_, m, v, hb) in out]
        if feval is not None:
            out.extend(self._eval_custom(feval, self._train_data_name, "train"))
        return out

    @write_locked
    def eval_valid(self, feval=None):
        out = self._gbdt.eval_valid()
        if feval is not None:
            for i, name in enumerate(self._valid_names):
                out.extend(self._eval_custom(feval, name, i))
        return out

    def _eval_custom(self, feval, name, which):
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        if which == "train":
            from .parallel.multihost import to_host
            raw = to_host(self._gbdt.train_score)
            if getattr(self._gbdt, "_compact", None) is not None:
                # compact grower keeps train scores in a permuted row order;
                # user fevals see the dataset's original order
                perm = self._gbdt._compact_perm()
                unperm = np.empty_like(raw)
                unperm[:, perm] = raw
                raw = unperm[:, :self._gbdt._n_real]
            data = self.train_set
        else:
            vs = self._gbdt.valid_sets[which]
            raw = np.asarray(vs.score)
            data = _DatasetView(vs.dataset)
        # multiclass preds are handed to custom metrics as [n, K], matching
        # the reference's documented feval contract (sklearn.py/engine.py)
        preds = raw[0] if raw.shape[0] == 1 else raw.T
        out = []
        for f in fevals:
            res = f(preds, data)
            if isinstance(res, list):
                for metric, value, hb in res:
                    out.append((name, metric, value, hb))
            else:
                metric, value, hb = res
                out.append((name, metric, value, hb))
        return out

    # -- prediction ----------------------------------------------------------
    @read_locked
    def predict(
        self,
        data: _ArrayLike,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        validate_features: bool = False,
        **kwargs,
    ) -> np.ndarray:
        """(reference: Booster.predict, basic.py:4701 → Predictor)"""
        inner = self._gbdt
        start_iteration, num_iteration = self._predict_window(
            start_iteration, num_iteration)
        arr = np.asarray(_maybe_series(data), dtype=np.float64)
        (pre, pre_start, pre_cut, own_start, own_cut, pre_empty,
         own_empty) = self._global_tree_window(start_iteration,
                                               num_iteration)
        if pred_leaf:
            own = (inner.predict_leaf_matrix(arr, own_cut, own_start)
                   if not own_empty else None)
            if not pre_empty:
                pre_leaf = pre.predict_leaf_matrix(arr, pre_cut, pre_start)
                own = (pre_leaf if own is None
                       else np.concatenate([pre_leaf, own], axis=1))
            return own
        if pred_contrib:
            return self._predict_contrib(arr, num_iteration, start_iteration)
        early = self._predict_early_stop(kwargs)
        raw = (inner.predict_raw_matrix(arr, own_cut, own_start, early)
               if not own_empty else None)   # [K, N]
        if not pre_empty:
            pre_raw = pre.predict_raw_matrix(arr, pre_cut, pre_start)
            raw = pre_raw if raw is None else raw + pre_raw
        if raw is None:
            raw = np.zeros((max(inner.num_tree_per_iteration, 1),
                            arr.shape[0]), np.float32)
        k = raw.shape[0]
        if raw_score or inner.objective is None:
            return raw[0] if k == 1 else raw.T
        conv = np.asarray(inner.objective.convert_output(
            raw.T if k > 1 else raw[0]))
        return conv

    @read_locked
    def predict_device(self, data: _ArrayLike,
                       start_iteration: int = 0,
                       num_iteration: Optional[int] = None):
        """Serve raw scores WITHOUT materializing them on the host.

        Bins the request, routes it through the bucketed inference engine
        (ops/predict.py) and returns a device-resident ``jax.Array`` —
        ``[N]`` raw scores for binary/regression, ``[N, K]`` for
        multiclass — for downstream device pipelines to consume in HBM.
        Steady-state calls (warm bucket rung) compile nothing; the only
        transfers are the request upload and the final [K, rung] -> [K, N]
        device-side slice. Loaded-from-file models predict on the host
        path and are not supported here."""
        inner = self._device_serving_inner()
        start_iteration, num_iteration = self._predict_window(
            start_iteration, num_iteration)
        arr = np.asarray(_maybe_series(data), dtype=np.float64)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        n = arr.shape[0]
        import jax.numpy as jnp
        binned = inner.bin_matrix(arr)
        _, ladder, engine = inner._predict_cfg()
        from .ops.predict import bucket_rows
        if (engine != "scan" and bucket_rows(n, ladder) is None
                and not inner._can_shard_predict(n, ladder)):
            # above the ladder with no mesh: device-side concat of
            # max-rung slices, each through the warm max-rung program
            top = ladder[-1]
            parts = [inner.predict_raw_device(
                binned[a:a + top], num_iteration,
                start_iteration)[:, :min(top, n - a)]
                for a in range(0, n, top)]
            raw = jnp.concatenate(parts, axis=1)
        else:
            raw = inner.predict_raw_device(binned, num_iteration,
                                           start_iteration)[:, :n]
        if inner.average_output:
            raw = raw / inner._average_divisor(num_iteration,
                                               start_iteration)
        return raw[0] if raw.shape[0] == 1 else raw.T

    def _global_tree_window(self, start_iteration: int,
                            num_iteration: Optional[int]):
        """Split a (start, num) iteration window across the loaded base
        model and this booster's own trees — global tree-window semantics
        (reference: models_ holds loaded-then-new trees in order and
        start/num address that sequence). THE one implementation behind
        predict() and _predict_contrib(); returns ``(pre, pre_start,
        pre_cut, own_start, own_cut, pre_empty, own_empty)`` with
        ``None`` cuts meaning "to the end"."""
        pre = getattr(self, "_pre_model", None)
        pre_iters = pre.current_iteration if pre is not None else 0
        end = (start_iteration + num_iteration
               if num_iteration is not None and num_iteration > 0 else None)
        pre_start = min(start_iteration, pre_iters)
        pre_cut = (max(min(end, pre_iters) - pre_start, 0)
                   if end is not None else None)
        own_start = max(start_iteration - pre_iters, 0)
        own_cut = (max(end - pre_iters - own_start, 0)
                   if end is not None else None)
        pre_empty = pre is None or pre_start >= pre_iters or pre_cut == 0
        return (pre, pre_start, pre_cut, own_start, own_cut, pre_empty,
                own_cut == 0)

    def _predict_window(self, start_iteration: int,
                        num_iteration: Optional[int]):
        """Params-level prediction-window resolution shared by every
        prediction entry (reference: start_iteration_predict /
        num_iteration_predict, config.h predict section; default window
        cuts at best_iteration after early-stopped training)."""
        src = self.params or {}
        if start_iteration == 0 and int(src.get("start_iteration_predict",
                                                0) or 0) > 0:
            start_iteration = int(src["start_iteration_predict"])
        if num_iteration is None and int(src.get("num_iteration_predict",
                                                 -1) or -1) > 0:
            num_iteration = int(src["num_iteration_predict"])
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else None)
        return start_iteration, num_iteration

    def _predict_early_stop(self, kwargs=None):
        """Resolved ``(margin, freq)`` pair or None: the pred_early_stop
        controls shared by predict() and predict_serving. The reference
        only early-stops classification predictions (predictor.hpp
        NeedAccuratePrediction gate)."""
        kwargs = kwargs or {}
        src = self.params or {}
        want = kwargs.get("pred_early_stop",
                          bool(src.get("pred_early_stop")))
        if not want:
            return None
        inner = self._gbdt
        obj_name = getattr(inner.objective, "name", "")
        if obj_name != "binary" and inner.num_tree_per_iteration <= 1:
            return None
        return (float(kwargs.get("pred_early_stop_margin",
                                 src.get("pred_early_stop_margin", 10.0))),
                int(kwargs.get("pred_early_stop_freq",
                               src.get("pred_early_stop_freq", 10))))

    def _device_serving_inner(self):
        """The trained GBDT behind the device serving fast path, or a
        ``NotImplementedError`` naming why this booster cannot take it
        (loaded-from-file and continue-trained models predict on the host
        path — see predict_device)."""
        inner = self._gbdt
        if not hasattr(inner, "predict_raw_device"):
            raise NotImplementedError(
                "device serving needs a trained booster (models loaded "
                "from file predict on the host path; use predict())")
        if getattr(self, "_pre_model", None) is not None:
            raise NotImplementedError(
                "device serving does not support continue-trained "
                "boosters (the loaded base model predicts on the host "
                "path); use predict()")
        return inner

    def _serving_request(self, data, start_iteration: int,
                         num_iteration: Optional[int]):
        """``(inner, start_iteration, num_iteration, arr32, n)`` — the
        request-normalization preamble shared by every serving endpoint
        (predict/leaf/contrib): window resolution and the float32 cast
        (the serving wire format) live HERE, once."""
        inner = self._device_serving_inner()
        start_iteration, num_iteration = self._predict_window(
            start_iteration, num_iteration)
        arr = np.asarray(_maybe_series(data), dtype=np.float32)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        return inner, start_iteration, num_iteration, arr, arr.shape[0]

    @staticmethod
    def _serving_binned(inner, arr32: np.ndarray):
        """Bins for one serving batch: the jitted device featurizer
        (default — returns the rung-padded device matrix, pack4 layout
        included) or the host ``bin_columns`` escape hatch
        (``tpu_serve_featurize=host``; predict_raw_device pads it)."""
        if inner._serve_featurize_mode() == "device":
            return inner.featurize_rung(arr32)
        return inner.bin_matrix(arr32)

    @read_locked
    def predict_serving(self, data: _ArrayLike, raw_score: bool = False,
                        start_iteration: int = 0,
                        num_iteration: Optional[int] = None,
                        observe=None):
        """One coalesced serving batch: ``(padded host scores, n_valid)``.

        The serving twin of :meth:`predict`: bins the request, routes it
        through the bucketed device engine, applies the objective's
        output conversion at the PADDED rung shape, and returns the
        padded host array — callers (serving/coalescer.py) slice their
        per-request rows on the host, so no device op ever carries a
        request-dependent shape. That is the coalescer's zero-recompile
        contract: :meth:`predict_device`'s device-side ``[:, :n]`` slice
        would lower one trivial program per distinct request size.

        Rows ``[:n_valid]`` of the result equal
        ``predict(float32(data))`` bit-for-bit (row routing, score sums,
        and the elementwise output conversion are all per-row
        independent, so padding rows change nothing; float32 is the
        serving wire format below). Shape ``[rung]`` for
        binary/regression, ``[rung, K]`` for multiclass. The request
        must fit the bucket ladder.

        Honors the same params-level controls predict() does — the
        start_iteration_predict / num_iteration_predict window and the
        pred_early_stop margin/freq approximation (both per-row
        independent, so parity survives batching).

        The serving wire format is raw float32 (requests cast here, in
        BOTH featurize modes, so flipping ``tpu_serve_featurize`` can
        never change a response): with the default ``device`` mode the
        request is ONE host->device copy of the padded raw f32 matrix —
        binning runs as a jitted program (ops/device_bin.py), bit-
        identical to the ``host`` escape hatch's ``bin_columns`` pass."""
        inner, start_iteration, num_iteration, arr, n = \
            self._serving_request(data, start_iteration, num_iteration)
        early = self._predict_early_stop()
        binned = self._serving_binned(inner, arr)
        raw_dev = inner.predict_raw_device(
            binned, num_iteration, start_iteration, early_stop=early,
            device_packed=inner._pred_pack4)              # [K, rung] device
        raw = np.asarray(raw_dev)                         # [K, rung] host
        if observe is not None:
            # drift window (obs/drift.py): pure on-device adds of the
            # tick's bins + raw margins, enqueued AFTER the response
            # materialized so the accumulates overlap the host-side
            # slice/complete work instead of sitting on the latency path
            observe.observe_binned(binned, n)
            observe.observe_scores(raw_dev, n)
        if inner.average_output:
            raw = raw / inner._average_divisor(num_iteration,
                                               start_iteration)
        k = raw.shape[0]
        out = raw[0] if k == 1 else raw.T
        if raw_score or inner.objective is None:
            return out, n
        # elementwise (sigmoid) / per-row (softmax) conversion on the
        # padded shape: one eager program per rung, warmed alongside the
        # predict program by warm_predict_ladder
        return np.asarray(inner.objective.convert_output(out)), n

    @read_locked
    def predict_leaf_serving(self, data: _ArrayLike,
                             start_iteration: int = 0,
                             num_iteration: Optional[int] = None,
                             observe=None):
        """One coalesced ``pred_leaf`` batch: ``(padded leaves, n_valid)``.

        The serving twin of ``predict(pred_leaf=True)`` (reference:
        PredictLeafIndex): the depth walk's final node ids, returned
        rung-padded ``[rung, T]`` so callers slice per-request rows on
        the host. Rows ``[:n_valid]`` equal the reference routing
        bit-for-bit — leaf-index embeddings for downstream rankers."""
        inner, start_iteration, num_iteration, arr, n = \
            self._serving_request(data, start_iteration, num_iteration)
        binned = self._serving_binned(inner, arr)
        out = inner.predict_leaf_padded(
            binned, num_iteration, start_iteration,
            device_packed=inner._pred_pack4)
        if observe is not None:
            observe.observe_binned(binned, n)
        return out, n

    @read_locked
    def predict_contrib_serving(self, data: _ArrayLike,
                                start_iteration: int = 0,
                                num_iteration: Optional[int] = None,
                                observe=None):
        """One coalesced ``pred_contrib`` batch:
        ``(padded [rung, K*(F+1)] contributions, n_valid)``.

        Exact TreeSHAP (Lundberg et al.; reference ``Tree::TreeSHAP``,
        src/io/tree.cpp) served from the device engine
        (ops/treeshap_device.py) through the same rung ladder as
        predict — matches the numpy reference within f32 tolerance and
        sums to the raw score per row."""
        inner, start_iteration, num_iteration, arr, n = \
            self._serving_request(data, start_iteration, num_iteration)
        binned = self._serving_binned(inner, arr)
        out = inner.predict_contrib_padded(
            binned, num_iteration, start_iteration,
            device_packed=inner._pred_pack4)
        if observe is not None:
            observe.observe_binned(binned, n)
        return out, n

    def _serve_endpoints(self) -> tuple:
        """Resolved ``tpu_serve_endpoints``: which request kinds this
        booster's servers warm and accept. ``predict`` is always on."""
        cfg = self._gbdt.config
        raw = str(cfg.get("tpu_serve_endpoints", "predict") or "predict")
        eps = {e.strip().lower() for e in raw.split(",") if e.strip()}
        unknown = eps - {"predict", "leaf", "contrib"}
        if unknown:
            log.warning(f"unknown tpu_serve_endpoints {sorted(unknown)}; "
                        "valid: predict, leaf, contrib")
            eps -= unknown
        eps.add("predict")
        return tuple(sorted(eps))

    @read_locked
    def warm_predict_ladder(self, max_rows: Optional[int] = None,
                            start_iteration: int = 0,
                            num_iteration: Optional[int] = None
                            ) -> Dict[str, Any]:
        """Pre-compile the serving bucket ladder; returns warmup stats.

        Pushes one dummy request per row rung (ops/predict.warmup_rungs)
        through the full serving path — binning, the bucketed predict
        program, and the output conversion — so a server that warms
        before taking traffic compiles NOTHING in steady state, and a
        hot-swap candidate warms before the swap commits. With
        ``tpu_compile_cache_dir`` set, a restarted process re-arms the
        whole ladder from the persistent cache with zero backend
        compiles (the returned ``cache`` counters prove it: hits ==
        requests, misses == 0 on a warm cache).

        Every endpoint in ``tpu_serve_endpoints`` warms per rung —
        predict always, plus the ``pred_leaf`` walk and the device
        TreeSHAP ``pred_contrib`` programs when enabled — so all three
        request kinds serve mixed batch sizes with zero steady-state
        compiles through the same ladder.

        Stats: ``rungs`` warmed, ``endpoints``, ``seconds``,
        ``lowerings`` / ``backend_compiles`` spent, and the
        persistent-cache ``cache`` ``{requests, hits, misses}``.
        ``max_rows`` caps the rung enumeration
        (``tpu_serve_warm_max_rows``); the scan escape-hatch engine
        recompiles per shape by design and reports ``skipped``."""
        import time as _time

        from .analysis import guards
        from .analysis.faultinject import active_plan
        from .ops.predict import parse_bucket_ladder, warmup_rungs
        inner = self._device_serving_inner()
        cfg = inner.config
        if str(cfg.get("tpu_predict_engine", "batched")).lower() == "scan":
            return {"rungs": [], "seconds": 0.0,
                    "skipped": "tpu_predict_engine=scan recompiles per "
                               "shape by design"}
        if max_rows is None:
            max_rows = int(cfg.get("tpu_serve_warm_max_rows", 0) or 0)
        ladder = parse_bucket_ladder(cfg.get("tpu_predict_buckets", "auto"))
        rungs = warmup_rungs(ladder, max_rows)
        from .obs import flight
        from .obs.spans import span
        n_feat = inner.train_set.num_total_features
        endpoints = self._serve_endpoints()
        plan = active_plan(cfg)
        t0 = _time.time()
        with guards.compile_counter() as cc, \
                guards.cache_counter() as cache, \
                guards.compile_phase("predict_warmup"):
            for rung in rungs:
                # ordinal-matched site (no iteration= kwarg): warmup=N
                # means the Nth rung warmed this process
                plan.fire("warmup", rung=rung)
                dummy = np.zeros((rung, n_feat), np.float32)
                with span("predict_warmup"):
                    self.predict_serving(dummy,
                                         start_iteration=start_iteration,
                                         num_iteration=num_iteration)
                    if "leaf" in endpoints:
                        self.predict_leaf_serving(
                            dummy, start_iteration=start_iteration,
                            num_iteration=num_iteration)
                    if "contrib" in endpoints:
                        self.predict_contrib_serving(
                            dummy, start_iteration=start_iteration,
                            num_iteration=num_iteration)
                flight.note("warmup_rung", rung=rung)
        return {"rungs": list(rungs), "endpoints": list(endpoints),
                "seconds": round(_time.time() - t0, 3),
                "lowerings": cc.lowerings,
                "backend_compiles": cc.backend_compiles,
                "cache": {"requests": cache.requests, "hits": cache.hits,
                          "misses": cache.misses}}

    @read_locked
    def serve(self, **kwargs):
        """Stand up a :class:`~lightgbm_tpu.serving.PredictionServer` on
        this booster: micro-batch coalescing over the bucket ladder,
        bounded admission, per-request deadlines, and hot-swap-ready
        model registry. Keyword arguments override the ``tpu_serve_*``
        config knobs (``tick_ms``, ``queue_max``, ``deadline_ms``,
        ``warm_max_rows``, ``warm``, ``version``); ``metrics_port``
        (or ``tpu_metrics_port``) exposes ``GET /metrics`` Prometheus
        text + ``/healthz`` over stdlib HTTP (obs/metrics.py)."""
        from .serving import PredictionServer
        return PredictionServer(self, **kwargs)

    def _predict_contrib(self, arr, num_iteration, start_iteration: int = 0):
        """Exact TreeSHAP contributions [N, K*(F+1)] (reference:
        PredictContrib -> Tree::TreeSHAP, src/io/tree.cpp).

        Trained boosters route in bin space (bit-identical to training);
        loaded models and continue-training bases route on the model text's
        raw-value thresholds, like the reference's dataset-free path.
        Linear trees attribute their constant leaf outputs, matching the
        reference (TreeSHAP reads leaf_value_, never leaf coefficients).

        The (start_iteration, num_iteration) window addresses the global
        loaded+new tree sequence exactly like predict() — SHAP is
        additive over trees, so windowing the model stack is the whole
        story (the ``start_iteration != 0 is not supported`` restriction
        is gone)."""
        from .ops.treeshap import booster_contrib, loaded_booster_contrib
        g = self._gbdt
        k = max(g.num_tree_per_iteration, 1)
        arr = np.atleast_2d(np.asarray(arr, np.float64))
        if not hasattr(g, "bin_matrix"):
            # model-only path (Booster(model_file=...))
            models = g.models[start_iteration * k:]
            if num_iteration is not None and num_iteration > 0:
                models = models[: num_iteration * k]
            return loaded_booster_contrib(models, arr, k,
                                          g.max_feature_idx + 1)
        (pre, pre_start, pre_cut, own_start, own_cut, pre_empty,
         own_empty) = self._global_tree_window(start_iteration,
                                               num_iteration)
        g._flush_trees()
        models = [] if own_empty else g.models[own_start * k:]
        if own_cut is not None:
            models = models[: own_cut * k]
        binned = np.asarray(g.bin_matrix(arr))
        # tree split_feature holds ORIGINAL feature ids; under EFB the
        # gbdt's nan/cat arrays are column-space, so route with the
        # original-space twins like every other prediction path
        if getattr(g, "_efb", None) is not None:
            nan_bin = np.asarray(g._orig_nan_arr)
            is_cat = np.asarray(g._orig_cat_arr)
        else:
            nan_bin = np.asarray(g.nan_bin_arr)
            is_cat = np.asarray(g.is_cat_arr)

        from .obs.spans import span
        from .ops.split import go_left_scalar_np
        with span("contrib"):
            out = booster_contrib(models, binned, nan_bin, is_cat,
                                  go_left_scalar_np,
                                  g.num_tree_per_iteration,
                                  int(binned.shape[1]))
        if not pre_empty:
            # continue-trained: SHAP is additive over trees, so the loaded
            # base model's contributions (raw-space routing) sum in
            pre_models = pre.models[pre_start * k:]
            if pre_cut is not None:
                pre_models = pre_models[: pre_cut * k]
            out = out + loaded_booster_contrib(
                pre_models, arr, k, int(binned.shape[1]))
        return out

    # -- model IO ------------------------------------------------------------
    @read_locked
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        from .model_io import booster_to_string, merge_model_texts
        if num_iteration is None and self.best_iteration > 0:
            # reference behavior: default save cuts at best_iteration
            # (basic.py save_model num_iteration doc)
            num_iteration = self.best_iteration
        pre = getattr(self, "_pre_model", None)
        if pre is None:
            return booster_to_string(self, num_iteration)
        pre_cut, own_cut = self._split_iteration_window(num_iteration, pre)
        text = booster_to_string(self, own_cut)
        return merge_model_texts(pre, text, pre_num_iteration=pre_cut)

    @staticmethod
    def _split_iteration_window(num_iteration, pre):
        """Split a leading num_iteration window across a loaded base model
        and the booster's own trees: (pre_cut, own_cut), None = all."""
        if num_iteration is None or num_iteration <= 0:
            return None, None
        if pre is None:
            return None, num_iteration
        return (min(num_iteration, pre.current_iteration),
                max(num_iteration - pre.current_iteration, 0))

    @read_locked
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        with open(filename, "w") as f:
            f.write(self.model_to_string(num_iteration))
        return self

    @read_locked
    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> Dict:
        from .model_io import booster_to_dict
        if getattr(self, "_pre_model", None) is not None:
            # continue-trained boosters dump via the merged text (keeps the
            # loaded trees; a text round-trip is exact for them)
            from .model_io import LoadedGBDT, loaded_dump
            return loaded_dump(LoadedGBDT(self.model_to_string(num_iteration)))
        return booster_to_dict(self, num_iteration)

    # -- introspection -------------------------------------------------------
    @read_locked
    def num_trees(self) -> int:
        g = self._gbdt
        own = g.num_total_trees if hasattr(g, "num_total_trees") \
            else len(g.models)
        pre = getattr(self, "_pre_model", None)
        return own + (len(pre.models) if pre is not None else 0)

    @read_locked
    def current_iteration(self) -> int:
        pre = getattr(self, "_pre_model", None)
        return self._gbdt.current_iteration + \
            (pre.current_iteration if pre is not None else 0)

    @read_locked
    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    @read_locked
    def num_feature(self) -> int:
        ts = getattr(self._gbdt, "train_set", None)
        if ts is not None:
            return ts.num_total_features
        return self._gbdt.max_feature_idx + 1  # loaded model

    @read_locked
    def feature_name(self) -> List[str]:
        ts = getattr(self._gbdt, "train_set", None)
        if ts is not None:
            return list(ts.feature_names)
        return list(self._gbdt.feature_names)  # loaded model

    @read_locked
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        imp = self._gbdt.feature_importance(importance_type, iteration)
        pre = getattr(self, "_pre_model", None)
        if pre is not None:
            pre_imp = pre.feature_importance(importance_type)
            n = max(len(imp), len(pre_imp))
            out = np.zeros(n, imp.dtype)
            out[: len(imp)] += imp
            out[: len(pre_imp)] += pre_imp
            return out
        return imp

    def _all_leaf_values(self):
        pre = getattr(self, "_pre_model", None)
        models = list(self._gbdt.models) + \
            (list(pre.models) if pre is not None else [])
        return models

    @read_locked
    def lower_bound(self):
        return min((m.leaf_value.min() for m in self._all_leaf_values()),
                   default=0.0)

    @read_locked
    def upper_bound(self):
        return max((m.leaf_value.max() for m in self._all_leaf_values()),
                   default=0.0)


class _DatasetView:
    """Minimal Dataset-like view over an internal BinnedDataset (for feval)."""

    def __init__(self, inner: BinnedDataset):
        self._inner = inner

    def get_label(self):
        return self._inner.metadata.label

    def get_weight(self):
        return self._inner.metadata.weight

    def get_group(self):
        return self._inner.metadata.group


def _call_custom_objective(fobj: Callable, booster: Booster):
    """Custom objective protocol: fobj(preds, train_dataset) -> (grad, hess)
    (reference: Booster.update fobj path, basic.py:4117-4132)."""
    gbdt = booster._gbdt
    raw = np.asarray(gbdt.train_score)
    # multiclass: hand the custom objective [n, K] preds and accept [n, K]
    # (or flat row-major) grads back — the reference's documented contract
    preds = raw[0] if raw.shape[0] == 1 else raw.T
    grad, hess = fobj(preds, booster.train_set)
    grad = np.asarray(grad, np.float32)
    hess = np.asarray(hess, np.float32)
    k, n = gbdt.num_tree_per_iteration, gbdt.num_data
    if grad.size != k * n:
        raise ValueError(f"gradient size {grad.size} != num_class*num_data {k * n}")
    if k > 1:
        grad = grad.reshape(n, k).T
        hess = hess.reshape(n, k).T
    return grad, hess
