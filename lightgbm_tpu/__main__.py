"""``python -m lightgbm_tpu config=train.conf`` — the reference CLI surface
(reference: src/main.cpp)."""
from .cli import run

if __name__ == "__main__":
    raise SystemExit(run())
