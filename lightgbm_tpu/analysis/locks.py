"""Interprocedural lock-order & blocking-call analyzer (tpulint R011).

The reference serializes its whole public surface behind ONE shared
mutex at the C API boundary (src/c_api.cpp:163) — lock ordering cannot
go wrong with a single lock. This port grew many fine-grained locks
(``@read_locked``/``@write_locked`` RWLocks on Booster/Dataset,
``GBDT._trees_mu``, the coalescer condition variable,
``registry._deploy_mu``, module-level observability mutexes), so the
discipline the reference gets for free must be *proved* here: the
whole-program lock-acquisition-order graph has to stay acyclic, and
nothing slow may run while a lock is held.

The analysis (pure AST, no jax import — loads anywhere, like the rest
of tpulint):

  1. discovers every lock object in the package: ``self.attr = Lock()/
     RLock()/Condition()/Semaphore()/RWLock()/Mutex()`` class members
     (keyed ``Class.attr``), module-level ``name = Lock()`` (keyed
     ``module.name``);
  2. walks each function in statement order tracking the held-lock set:
     ``with lock:``, ``with rw.read()/.write():``, bare ``.acquire()``/
     ``.release()`` (incl. the acquire-then-release-in-finally shape),
     and the ``@read_locked``/``@write_locked`` decorators (which hold
     ``Class._api_lock`` for the whole body);
  3. propagates "this call transitively acquires lock L" and "this call
     transitively blocks (join/get/result/wait/sleep/fsync, d2h
     funnels, jitted dispatch)" facts across calls — including
     functions passed by reference (``run_with_deadline(_commit, ...)``)
     — via a bounded fixpoint, each fact carrying a witness call chain;
  4. reports:
       (a) lock-order cycles, with the witness chain of every edge;
       (b) blocking calls / d2h transfers / jitted dispatch reached
           while a lock is held;
       (c) RWLock read->write upgrade paths (the runtime raises —
           this finds them before a thread does);
       (d) ``Condition.wait()`` outside a predicate ``while`` loop.

Deliberate-policy carve-outs (encoded, not allowlisted):
  * ``cv.wait()`` while holding that same cv is the condition-variable
    pattern itself, not a blocking-under-lock hazard;
  * the ``@read_locked``/``@write_locked`` API lock intentionally spans
    device work — that coarse lock over compute IS the reference's
    design (c_api.cpp API_BEGIN) — so decorator-granted holds are
    exempt from the d2h/dispatch categories (NOT from sleep/join/fsync
    blocking, and NOT from upgrade checks);
  * re-entrant same-lock re-acquisition is silent (RWLock/Mutex/RLock
    all nest), except read->write which upgrades (c).

Everything else ships fixed or anchored in analysis/tpulint.allow with
a justification. CLI: ``scripts/tpulint locks [--dot]``.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules.base import (Finding, FunctionInfo, JIT_NAMES, ModuleInfo,
                         PackageInfo, call_name, dotted_name)

#: constructor basename -> lock kind
LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "lock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "RWLock": "rwlock",
    "Mutex": "lock",
}

#: decorator basename -> rwlock side granted for the whole method body
LOCKED_DECORATORS = {"read_locked": "read", "write_locked": "write"}

#: time.sleep is blocking even WITH an argument — that is its job
_SLEEP_NAMES = {"time.sleep", "sleep"}
_FSYNC_NAMES = {"os.fsync", "fsync"}
#: explicit device->host funnels / sync points
_D2H_NAMES = {"jax.device_get", "device_get", "jax.block_until_ready",
              "np.asarray", "numpy.asarray"}

#: method-call attrs that are lock protocol, never package callees
_LOCK_PROTOCOL_ATTRS = {
    "acquire", "release", "acquire_read", "acquire_write", "release_read",
    "release_write", "read", "write", "locked", "wait", "wait_for",
    "notify", "notify_all",
}

#: attr-call basenames too generic to resolve package-wide by basename
_ATTR_RESOLVE_STOPLIST = _LOCK_PROTOCOL_ATTRS | {
    "get", "put", "join", "result", "set", "is_set", "clear", "append",
    "extend", "pop", "popleft", "add", "discard", "remove", "update",
    "items", "keys", "values", "copy", "split", "strip", "format",
    "encode", "decode", "flush", "close", "info", "warning", "error",
    "debug",
    "exception", "startswith", "endswith", "sort", "index", "count",
    "todict", "tolist", "astype", "reshape", "sum", "mean", "min", "max",
}

_MAX_CHAIN = 6          # witness chain hops kept per fact
_FIXPOINT_ITERS = 10


class LockDecl:
    """One discovered lock object."""

    def __init__(self, key: str, kind: str, path: str, line: int):
        self.key = key          # "Class.attr" or "module.name"
        self.kind = kind        # "lock" | "condition" | "rwlock"
        self.path = path
        self.line = line

    def __repr__(self):
        return f"LockDecl({self.key}, {self.kind})"


class Held:
    """One entry of the held-lock stack during traversal."""

    def __init__(self, key: str, side: str, line: int,
                 via_decorator: bool = False):
        self.key = key
        self.side = side        # "excl" | "read" | "write"
        self.line = line
        self.via_decorator = via_decorator


class Edge:
    """First witness of a src-held -> dst-acquired order relation."""

    def __init__(self, src: str, dst: str, fn: "FunctionInfo",
                 held_line: int, chain: List[str]):
        self.src = src
        self.dst = dst
        self.fn = fn
        self.held_line = held_line
        self.chain = chain      # call chain from holder to acquisition

    def describe(self) -> str:
        where = f"{self.fn.module.path}:{self.held_line}"
        return (f"{self.src} -> {self.dst} [{self.fn.qualname} holds "
                f"{self.src} at {where}; acquired via "
                f"{' -> '.join(self.chain)}]")


class LockAnalysis:
    """Package-wide result: declared locks, the order graph, findings."""

    def __init__(self, package: PackageInfo):
        self.package = package
        self.locks: Dict[str, LockDecl] = {}
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self.findings: List[Finding] = []
        self.cycles: List[List[str]] = []
        _Analyzer(package, self).run()

    # -- rendering ------------------------------------------------------
    def order_graph_lines(self) -> List[str]:
        out = [f"locks discovered: {len(self.locks)}"]
        for key in sorted(self.locks):
            d = self.locks[key]
            out.append(f"  {key}  ({d.kind}, {d.path}:{d.line})")
        out.append(f"order edges: {len(self.edges)}")
        for (src, dst) in sorted(self.edges):
            out.append(f"  {self.edges[(src, dst)].describe()}")
        return out

    def to_dot(self) -> str:
        lines = ["digraph lock_order {", "  rankdir=LR;"]
        nodes = sorted(set(self.locks)
                       | {e[0] for e in self.edges}
                       | {e[1] for e in self.edges})
        cyc_nodes = {n for cyc in self.cycles for n in cyc}
        for n in nodes:
            kind = self.locks[n].kind if n in self.locks else "lock"
            shape = {"condition": "diamond",
                     "rwlock": "box"}.get(kind, "ellipse")
            color = ', color=red' if n in cyc_nodes else ""
            lines.append(f'  "{n}" [shape={shape}{color}];')
        for (src, dst), e in sorted(self.edges.items()):
            label = e.chain[-1].replace('"', "'") if e.chain else ""
            lines.append(f'  "{src}" -> "{dst}" [label="{label}"];')
        lines.append("}")
        return "\n".join(lines)


def _basename(cname: Optional[str]) -> Optional[str]:
    return cname.rsplit(".", 1)[-1] if cname else None


def _timeout_is_set(call: ast.Call, first_pos_is_timeout: bool) -> bool:
    """True when the call carries a non-None timeout (so it cannot block
    forever). Mirrors R008: for join/result/wait the first positional IS
    the timeout; for get the first positional is ``block``."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    if first_pos_is_timeout and call.args:
        a = call.args[0]
        return not (isinstance(a, ast.Constant) and a.value is None)
    return False


class _FnFacts:
    """Per-function interprocedural facts with witness chains."""

    def __init__(self):
        # (lock key, side) -> call chain to the acquisition site
        self.acquires: Dict[Tuple[str, str], List[str]] = {}
        # (category, label) -> call chain;  category in
        # {"blocking", "d2h", "dispatch"}
        self.blocking: Dict[Tuple[str, str], List[str]] = {}


class _Analyzer:
    def __init__(self, package: PackageInfo, result: LockAnalysis):
        self.pkg = package
        self.res = result
        # per-module: module-level lock name -> decl
        self.module_locks: Dict[int, Dict[str, LockDecl]] = {}
        # class name -> attr -> decl (package-wide; class names are
        # unique in this package)
        self.class_locks: Dict[str, Dict[str, LockDecl]] = {}
        # attr -> decls across all classes (for self.X in un-declaring
        # classes: unique-match fallback)
        self.attr_locks: Dict[str, List[LockDecl]] = {}
        # id(FunctionDef node) -> class name, for methods
        self.class_of_node: Dict[int, str] = {}
        self.facts: Dict[int, _FnFacts] = {}
        self._events: Dict[int, List[tuple]] = {}

    # ==================================================================
    def _all_fns(self) -> List[FunctionInfo]:
        # NOT m.functions.values(): method qualnames carry no class
        # prefix, so same-named methods of two classes collide there;
        # by_basename keeps every FunctionInfo
        out: List[FunctionInfo] = []
        seen: Set[int] = set()
        for m in self.pkg.modules:
            for lst in m.by_basename.values():
                for f in lst:
                    if id(f) not in seen:
                        seen.add(id(f))
                        out.append(f)
        return out

    def run(self) -> None:
        for m in self.pkg.modules:
            self._discover(m)
        for fn in self._all_fns():
            self._events[id(fn)] = self._trace(fn)
        self._fixpoint()
        self._report()
        self._find_cycles()

    # -- discovery ------------------------------------------------------
    def _ctor_kind(self, call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        return LOCK_CTORS.get(_basename(call_name(call)))

    def _discover(self, m: ModuleInfo) -> None:
        mod_base = os.path.splitext(os.path.basename(m.path))[0]
        mlocks: Dict[str, LockDecl] = {}
        for node in m.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = self._ctor_kind(node.value)
                if kind:
                    d = LockDecl(f"{mod_base}.{node.targets[0].id}", kind,
                                 m.path, node.lineno)
                    mlocks[node.targets[0].id] = d
                    self.res.locks[d.key] = d
        self.module_locks[id(m)] = mlocks

        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cname = node.name
            for meth in node.body:
                if isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self.class_of_node[id(meth)] = cname
                    for sub in ast.walk(meth):
                        d = self._self_lock_assign(sub, cname, m)
                        if d is not None:
                            self.class_locks.setdefault(
                                cname, {})[d.key.split(".", 1)[1]] = d
                            self.attr_locks.setdefault(
                                d.key.split(".", 1)[1], []).append(d)
                            self.res.locks[d.key] = d

    def _self_lock_assign(self, node: ast.AST, cname: str,
                          m: ModuleInfo) -> Optional[LockDecl]:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            return None
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            return None
        kind = self._ctor_kind(node.value)
        if kind is None:
            return None
        return LockDecl(f"{cname}.{t.attr}", kind, m.path, node.lineno)

    def _class_of(self, fn: FunctionInfo) -> Optional[str]:
        f: Optional[FunctionInfo] = fn
        while f is not None:
            c = self.class_of_node.get(id(f.node))
            if c is not None:
                return c
            f = f.parent
        return None

    # -- lock-expression resolution ------------------------------------
    def _resolve_lock(self, fn: FunctionInfo, expr: ast.AST
                      ) -> Optional[LockDecl]:
        """LockDecl for an expression naming a lock object, else None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self":
                cls = self._class_of(fn)
                if cls and attr in self.class_locks.get(cls, {}):
                    return self.class_locks[cls][attr]
                cands = self.attr_locks.get(attr, [])
                if len(cands) == 1:
                    return cands[0]
                return None
            # module-alias reference: `mod.LOCK`
            if base in fn.module.imports:
                mod_name, symbol = fn.module.imports[base]
                if symbol is None:
                    target = self.pkg.by_dotted.get(mod_name)
                    if target is not None:
                        return self.module_locks.get(
                            id(target), {}).get(attr)
            return None
        if isinstance(expr, ast.Name):
            d = self.module_locks.get(id(fn.module), {}).get(expr.id)
            if d is not None:
                return d
            if expr.id in fn.module.imports:
                mod_name, symbol = fn.module.imports[expr.id]
                if symbol is not None:
                    target = self.pkg.by_dotted.get(mod_name)
                    if target is not None:
                        return self.module_locks.get(
                            id(target), {}).get(symbol)
        return None

    def _acquisition_of(self, fn: FunctionInfo, expr: ast.AST
                        ) -> Optional[Tuple[LockDecl, str]]:
        """(decl, side) when ``expr`` acquires a lock as a context
        manager: ``lock``, ``rw.read()``, ``rw.write()``."""
        d = self._resolve_lock(fn, expr)
        if d is not None:
            return d, "excl"
        if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                     ast.Attribute) \
                and expr.func.attr in ("read", "write"):
            d = self._resolve_lock(fn, expr.func.value)
            if d is not None and d.kind == "rwlock":
                return d, expr.func.attr
        return None

    # -- per-function ordered event trace ------------------------------
    # events:  ("acquire", decl_key, side, line, held_snapshot)
    #          ("call",    call_node, line, held_snapshot, callees)
    #          ("block",   category, label, line, held_snapshot)
    #          ("cvwait",  recv_desc, line, in_while)
    # held_snapshot: tuple of Held (shared objects; snapshot of the list)
    def _trace(self, fn: FunctionInfo) -> List[tuple]:
        events: List[tuple] = []
        held: List[Held] = []
        cls = self._class_of(fn)
        for dec in fn.node.decorator_list:
            side = LOCKED_DECORATORS.get(_basename(dotted_name(dec)))
            if side:
                key = f"{cls or '?'}._api_lock"
                held.append(Held(key, side, fn.node.lineno,
                                 via_decorator=True))
        self._walk_body(fn, list(fn.node.body), held, events, in_while=0)
        return events

    def _walk_body(self, fn: FunctionInfo, stmts: List[ast.stmt],
                   held: List[Held], events: List[tuple],
                   in_while: int) -> None:
        for st in stmts:
            self._walk_stmt(fn, st, held, events, in_while)

    def _walk_stmt(self, fn: FunctionInfo, st: ast.stmt, held: List[Held],
                   events: List[tuple], in_while: int) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                              # analyzed separately
        if isinstance(st, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in st.items:
                acq = self._acquisition_of(fn, item.context_expr)
                if acq is not None:
                    d, side = acq
                    self._note_acquire(fn, d, side, item.context_expr,
                                       held, events)
                    held.append(Held(d.key, side,
                                     item.context_expr.lineno))
                    pushed += 1
                else:
                    self._scan_expr(fn, item.context_expr, held, events,
                                    in_while)
            self._walk_body(fn, st.body, held, events, in_while)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(st, ast.While):
            self._scan_expr(fn, st.test, held, events, in_while)
            self._walk_body(fn, st.body, held, events, in_while + 1)
            self._walk_body(fn, st.orelse, held, events, in_while)
            return
        if isinstance(st, ast.For):
            self._scan_expr(fn, st.iter, held, events, in_while)
            self._walk_body(fn, st.body, held, events, in_while)
            self._walk_body(fn, st.orelse, held, events, in_while)
            return
        if isinstance(st, ast.If):
            self._scan_expr(fn, st.test, held, events, in_while)
            self._walk_body(fn, st.body, held, events, in_while)
            self._walk_body(fn, st.orelse, held, events, in_while)
            return
        if isinstance(st, ast.Try):
            self._walk_body(fn, st.body, held, events, in_while)
            for h in st.handlers:
                self._walk_body(fn, h.body, held, events, in_while)
            self._walk_body(fn, st.orelse, held, events, in_while)
            self._walk_body(fn, st.finalbody, held, events, in_while)
            return
        # generic statement: scan contained expressions in source order
        for node in ast.iter_child_nodes(st):
            self._scan_expr(fn, node, held, events, in_while)

    def _scan_expr(self, fn: FunctionInfo, node: ast.AST,
                   held: List[Held], events: List[tuple],
                   in_while: int) -> None:
        """Walk an expression tree, evaluation-ish order, handling calls."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        for child in ast.iter_child_nodes(node):
            self._scan_expr(fn, child, held, events, in_while)
        if isinstance(node, ast.Call):
            self._handle_call(fn, node, held, events, in_while)

    def _note_acquire(self, fn: FunctionInfo, d: LockDecl, side: str,
                      site: ast.AST, held: List[Held],
                      events: List[tuple]) -> None:
        events.append(("acquire", d.key, side, site.lineno, tuple(held)))

    def _handle_call(self, fn: FunctionInfo, node: ast.Call,
                     held: List[Held], events: List[tuple],
                     in_while: int) -> None:
        cname = call_name(node)
        snapshot = tuple(held)

        # ---- lock protocol on a resolved lock object ----
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = node.func.value
            if attr in _LOCK_PROTOCOL_ATTRS:
                d = self._resolve_lock(fn, recv)
                if d is not None:
                    if attr in ("acquire", "acquire_read",
                                "acquire_write"):
                        side = {"acquire": "excl", "acquire_read": "read",
                                "acquire_write": "write"}[attr]
                        self._note_acquire(fn, d, side, node, held,
                                           events)
                        held.append(Held(d.key, side, node.lineno))
                        return
                    if attr in ("release", "release_read",
                                "release_write"):
                        for i in range(len(held) - 1, -1, -1):
                            if held[i].key == d.key:
                                del held[i]
                                break
                        return
                    if attr in ("wait", "wait_for"):
                        held_same = any(h.key == d.key for h in held)
                        if attr == "wait" and d.kind == "condition" \
                                and not in_while:
                            events.append(("cvwait", d.key, node.lineno,
                                           False))
                        if held_same:
                            return      # the cv pattern itself — exempt
                        if attr == "wait" and \
                                not _timeout_is_set(node, True):
                            events.append(("block", "blocking",
                                           f"{d.key}.wait()",
                                           node.lineno, snapshot))
                        return
                    return              # notify/locked/read()/write()
            # ---- blocking method calls on arbitrary receivers ----
            desc = dotted_name(node.func)
            if attr == "join" and not _timeout_is_set(node, True):
                events.append(("block", "blocking", f"{desc or attr}()",
                               node.lineno, snapshot))
                return
            if attr == "result" and not _timeout_is_set(node, True):
                events.append(("block", "blocking", f"{desc or attr}()",
                               node.lineno, snapshot))
                return
            if attr == "wait" and not _timeout_is_set(node, True):
                events.append(("block", "blocking", f"{desc or attr}()",
                               node.lineno, snapshot))
                return
            if attr == "get" and not node.args \
                    and not _timeout_is_set(node, False):
                # zero-arg q.get() with no timeout blocks forever;
                # dict-style .get(key[, default]) always has positionals
                if not any(kw.arg == "block" and
                           isinstance(kw.value, ast.Constant) and
                           kw.value.value is False
                           for kw in node.keywords):
                    events.append(("block", "blocking",
                                   f"{desc or attr}()", node.lineno,
                                   snapshot))
                    return
            if attr == "block_until_ready":
                events.append(("block", "d2h", f"{desc or attr}()",
                               node.lineno, snapshot))
                return

        if cname in _SLEEP_NAMES and self._is_time_sleep(fn, cname):
            events.append(("block", "blocking", "time.sleep",
                           node.lineno, snapshot))
            return
        if cname in _FSYNC_NAMES:
            events.append(("block", "blocking", "os.fsync", node.lineno,
                           snapshot))
            return
        if cname in _D2H_NAMES:
            events.append(("block", "d2h", cname, node.lineno, snapshot))
            return
        if cname in JIT_NAMES:
            events.append(("block", "dispatch", f"{cname}(...)",
                           node.lineno, snapshot))
            return

        # ---- package-internal call edge ----
        callees = self._callees_of(fn, node, cname)
        jitted = [c for c in callees if c.jit_decorated]
        if jitted:
            events.append(("block", "dispatch",
                           f"{jitted[0].qualname}()", node.lineno,
                           snapshot))
        if callees:
            events.append(("call", node, node.lineno, snapshot, callees))

    def _is_time_sleep(self, fn: FunctionInfo, cname: str) -> bool:
        if cname == "time.sleep":
            return True
        imp = fn.module.imports.get("sleep")
        return bool(imp and imp[0] == "time")

    def _callees_of(self, fn: FunctionInfo, node: ast.Call,
                    cname: Optional[str]) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        if cname:
            if "." not in cname:
                out.extend(self.pkg._callees(fn.module, cname))
            else:
                head, _, rest = cname.partition(".")
                if "." not in rest:
                    out.extend(self.pkg._resolve_attr(fn.module, head,
                                                      rest))
                    if not out and rest not in _ATTR_RESOLVE_STOPLIST:
                        # method-style call: resolve by basename across
                        # the package (R008-style), methods only
                        cands = [f for m in self.pkg.modules
                                 for f in m.by_basename.get(rest, ())
                                 if id(f.node) in self.class_of_node]
                        if len(cands) <= 4:
                            out.extend(cands)
        # functions passed by reference: run_with_deadline(_commit, ...)
        for a in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(a, ast.Name):
                out.extend(f for f in
                           self.pkg._callees(fn.module, a.id))
        seen: Set[int] = set()
        uniq = []
        for f in out:
            if id(f) not in seen and f.node is not fn.node:
                seen.add(id(f))
                uniq.append(f)
        return uniq

    # -- interprocedural fixpoint --------------------------------------
    def _chain_site(self, fn: FunctionInfo, line: int) -> str:
        return f"{fn.qualname} ({fn.module.path}:{line})"

    def _fixpoint(self) -> None:
        all_fns = self._all_fns()
        for f in all_fns:
            self.facts[id(f)] = _FnFacts()
        # seed with direct facts
        for f in all_fns:
            facts = self.facts[id(f)]
            for ev in self._events[id(f)]:
                if ev[0] == "acquire":
                    _, key, side, line, _held = ev
                    facts.acquires.setdefault(
                        (key, side), [self._chain_site(f, line)])
                elif ev[0] == "block":
                    _, cat, label, line, _held = ev
                    facts.blocking.setdefault(
                        (cat, label), [self._chain_site(f, line)])
            # decorator-granted acquisition is a fact too (drives the
            # read->write upgrade check across calls)
            for dec in f.node.decorator_list:
                side = LOCKED_DECORATORS.get(_basename(dotted_name(dec)))
                if side:
                    key = f"{self._class_of(f) or '?'}._api_lock"
                    facts.acquires.setdefault(
                        (key, side),
                        [self._chain_site(f, f.node.lineno)])
        for _ in range(_FIXPOINT_ITERS):
            changed = False
            for f in all_fns:
                facts = self.facts[id(f)]
                for ev in self._events[id(f)]:
                    if ev[0] != "call":
                        continue
                    _, _node, line, _held, callees = ev
                    site = self._chain_site(f, line)
                    for callee in callees:
                        sub = self.facts[id(callee)]
                        for fact_key, chain in sub.acquires.items():
                            if fact_key not in facts.acquires and \
                                    len(chain) < _MAX_CHAIN:
                                facts.acquires[fact_key] = \
                                    [site] + chain
                                changed = True
                        for fact_key, chain in sub.blocking.items():
                            if fact_key not in facts.blocking and \
                                    len(chain) < _MAX_CHAIN:
                                facts.blocking[fact_key] = \
                                    [site] + chain
                                changed = True
            if not changed:
                break

    # -- reporting ------------------------------------------------------
    def _report(self) -> None:
        for fn in self._all_fns():
            self._report_fn(fn)
        self.res.findings.sort(key=lambda f: (f.path, f.line, f.message))

    def _find(self, fn: FunctionInfo, line: int, message: str) -> None:
        self.res.findings.append(Finding(
            "R011", fn.module.path, line, fn.qualname, message))

    def _edge(self, fn: FunctionInfo, h: Held, key: str,
              chain: List[str]) -> None:
        if (h.key, key) not in self.res.edges:
            self.res.edges[(h.key, key)] = Edge(h.key, key, fn,
                                                h.line, chain)

    def _check_acquire(self, fn: FunctionInfo, h: Held, key: str,
                       side: str, line: int, chain: List[str]) -> None:
        if h.key == key:
            if h.side == "read" and side == "write":
                self._find(fn, line,
                           f"read->write upgrade on {key}: read side "
                           f"held at line {h.line}, write acquired via "
                           f"{' -> '.join(chain)} (RWLock raises at "
                           "runtime)")
            return                      # re-entrant same-lock: fine
        self._edge(fn, h, key, chain)

    def _check_block(self, fn: FunctionInfo, h: Held, cat: str,
                     label: str, line: int, chain: List[str]) -> None:
        if h.via_decorator and cat in ("d2h", "dispatch"):
            return      # the coarse API lock spans device work by design
        what = {"blocking": "blocking call",
                "d2h": "device transfer",
                "dispatch": "jitted dispatch"}[cat]
        self._find(fn, line,
                   f"{what} under lock: {label} reached while holding "
                   f"{h.key} ({h.side} side, line {h.line}) via "
                   f"{' -> '.join(chain)}")

    def _report_fn(self, fn: FunctionInfo) -> None:
        for ev in self._events[id(fn)]:
            if ev[0] == "acquire":
                _, key, side, line, heldsnap = ev
                chain = [self._chain_site(fn, line)]
                for h in heldsnap:
                    self._check_acquire(fn, h, key, side, line, chain)
            elif ev[0] == "block":
                _, cat, label, line, heldsnap = ev
                chain = [self._chain_site(fn, line)]
                for h in heldsnap:
                    self._check_block(fn, h, cat, label, line, chain)
            elif ev[0] == "cvwait":
                _, key, line, _ = ev
                self._find(fn, line,
                           f"condition wait outside a predicate loop: "
                           f"{key}.wait() must sit in a `while "
                           "not <predicate>` loop (spurious wakeups, "
                           "missed-signal races)")
            elif ev[0] == "call":
                _, _node, line, heldsnap, callees = ev
                if not heldsnap:
                    continue
                site = self._chain_site(fn, line)
                for callee in callees:
                    sub = self.facts.get(id(callee))
                    if sub is None:
                        continue
                    for (key, side), chain in sub.acquires.items():
                        for h in heldsnap:
                            self._check_acquire(fn, h, key, side, line,
                                                [site] + chain)
                    for (cat, label), chain in sub.blocking.items():
                        for h in heldsnap:
                            self._check_block(fn, h, cat, label, line,
                                              [site] + chain)

    # -- cycles ---------------------------------------------------------
    def _find_cycles(self) -> None:
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self.res.edges:
            adj.setdefault(src, []).append(dst)
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str) -> None:
            stack: List[Tuple[str, List[str]]] = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == start and len(path) > 1:
                        cyc = path[:]
                        i = cyc.index(min(cyc))
                        canon = tuple(cyc[i:] + cyc[:i])
                        if canon not in seen_cycles:
                            seen_cycles.add(canon)
                            self._report_cycle(list(canon))
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + [nxt]))

        for n in sorted(adj):
            dfs(n)

    def _report_cycle(self, cyc: List[str]) -> None:
        self.res.cycles.append(cyc)
        parts = []
        first_edge: Optional[Edge] = None
        for i, src in enumerate(cyc):
            dst = cyc[(i + 1) % len(cyc)]
            e = self.res.edges[(src, dst)]
            if first_edge is None:
                first_edge = e
            parts.append(f"{src} -> {dst} (held at "
                         f"{e.fn.module.path}:{e.held_line} in "
                         f"{e.fn.qualname}, acquired via "
                         f"{' -> '.join(e.chain)})")
        assert first_edge is not None
        self.res.findings.append(Finding(
            "R011", first_edge.fn.module.path, first_edge.held_line,
            first_edge.fn.qualname,
            "lock-order cycle (potential deadlock): "
            + "; ".join(parts)))


# ======================================================================
def analyze_package(package: PackageInfo) -> LockAnalysis:
    """Run (or fetch the cached) whole-package lock analysis."""
    cached = getattr(package, "_r011_analysis", None)
    if cached is None:
        cached = LockAnalysis(package)
        package._r011_analysis = cached
    return cached


def analyze_paths(paths: Sequence[str]
                  ) -> Tuple[LockAnalysis, List[str]]:
    from . import tpulint as _tl
    modules: List[ModuleInfo] = []
    errors: List[str] = []
    for path in _tl._iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(path, source, _tl._dotted_of(path)))
        except (SyntaxError, OSError, UnicodeDecodeError) as err:
            errors.append(f"{path}: {err}")
    return analyze_package(PackageInfo(modules)), errors


def _default_package_path() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    from . import tpulint as _tl

    ap = argparse.ArgumentParser(
        prog="tpulint locks",
        description="interprocedural lock-order & blocking-call "
                    "analyzer (R011)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the package)")
    ap.add_argument("--dot", action="store_true",
                    help="emit the lock-order graph as Graphviz")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--allowlist", default=_tl.DEFAULT_ALLOWLIST)
    ap.add_argument("--no-allowlist", action="store_true")
    args = ap.parse_args(argv)

    paths = args.paths or [_default_package_path()]
    analysis, errors = analyze_paths(paths)
    findings = list(analysis.findings)

    entries: List[_tl.AllowEntry] = []
    allow_errors: List[str] = []
    if not args.no_allowlist:
        entries, allow_errors = _tl.load_allowlist(args.allowlist)
        entries = [e for e in entries if e.rule == "R011"]
        findings = _tl.apply_allowlist(findings, entries)

    if args.dot:
        print(analysis.to_dot())
    elif args.as_json:
        import json
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        for line in analysis.order_graph_lines():
            print(line)
        print(f"cycles: {len(analysis.cycles)}")
        for f in findings:
            print(f.render())
        print(f"tpulint locks: {len(findings)} finding(s)",
              file=sys.stderr)
    for err in errors + allow_errors:
        print(f"tpulint locks: error: {err}", file=sys.stderr)

    if errors or allow_errors:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
