"""Interprocedural resource-lifecycle & cache-bound analyzer (tpulint R012).

The reference frees every handle through ONE disciplined surface
(``LGBM_BoosterFree`` / ``Network::Dispose``, src/c_api.cpp) — nothing
long-lived exists outside it. This port owns dozens of long-lived
resources: coalescer worker threads, MetricsServer HTTP endpoints,
profiler ``trace_session``s, checkpoint temp files, monitoring
listeners, and keyed jit/device caches — and ROADMAP items 2-3
(multi-tenant fleet, unattended refit daemon) multiply them by
N tenants x M versions running for weeks. The leak class kept getting
fixed by hand (the PR 10 pre-try profiler leak, PR 14's float-keyed
retained program and per-swap /metrics cardinality, PR 5's hand-added
LRU cap); this makes the class statically checkable, the way locks.py
(R011) made lock-order inversions checkable.

The analysis (pure AST, no jax import — loads anywhere, like the rest
of tpulint):

  1. discovers every resource acquisition in the package — stdlib
     constructors (``threading.Thread``, ``ThreadingHTTPServer``,
     ``open``/``mkstemp``/``NamedTemporaryFile``, ``jax.profiler.trace``
     / ``trace_session``, ``jax.monitoring`` listener registrations) AND
     package classes that *own* such resources (a class with a resource
     attr becomes a resource constructor itself, transitively — the
     "registered owner" closure: constructing a PredictionServer
     acquires its coalescer's worker);
  2. verifies each acquisition has a guaranteed release on ALL paths:
     ``with``-managed, released in an enclosing/immediately-following
     ``finally``, ownership-transferred (returned / stored into a
     container / passed onward), a daemon thread, or registered on
     ``self`` with an owner class whose close/stop IS release-complete
     (checked per class, with ``x = self.attr``-alias and
     method-calls-method resolution);
  3. flags the exception edges: a release that straight-line code
     reaches but a raise in between skips (the PR 10 leak shape), a
     temp-file cleanup handler narrower than ``BaseException`` (a
     SimulatedKill or TypeError orphans the file), and an ``__init__``
     that can raise AFTER acquiring a resource attr (the partially
     built object is dropped with the resource live and no handle to
     close it);
  4. the retained-program bound half: ``functools.lru_cache``/``cache``
     factories of jitted programs must be bounded or keyed only on
     small annotated domains (``int``/``bool`` — float or unannotated
     keys are the PR 14 ``_score_accum_fn`` bug), and dict caches keyed
     from function arguments holding jitted callables / metric series
     must carry a statically visible bound (an eviction/prune call, a
     re-assignment that trims, or a rung/bucket key mapping).

Deliberate holds (the process-lifetime metrics listener, a shared
probe thread) ship anchored in analysis/tpulint.allow with a
justification. CLI: ``scripts/tpulint resources [--dot|--json]``.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules.base import (Finding, FunctionInfo, JIT_NAMES, ModuleInfo,
                         PackageInfo, call_name, dotted_name)

#: constructor basename -> resource kind (stdlib / jax surface)
RESOURCE_CTORS = {
    "Thread": "thread",
    "Timer": "thread",
    "HTTPServer": "server",
    "ThreadingHTTPServer": "server",
    "TCPServer": "server",
    "ThreadingTCPServer": "server",
    "UDPServer": "server",
    "open": "file",
    "fdopen": "file",
    "NamedTemporaryFile": "file",
    "TemporaryFile": "file",
    "mkstemp": "tempfile",
    "start_trace": "profiler",
    "trace_session": "profiler",
}

#: release-protocol method names per kind (called on the binding)
RELEASE_ATTRS: Dict[str, Set[str]] = {
    "thread": {"join"},
    "server": {"shutdown", "server_close", "stop", "close"},
    "file": {"close", "__exit__"},
    "tempfile": {"close", "__exit__"},
    "profiler": {"__exit__", "stop", "stop_trace", "close"},
    "listener": set(),
    "owner": {"close", "stop", "shutdown", "__exit__", "__del__", "join",
              "release", "terminate", "cancel", "disconnect", "teardown",
              "server_close", "final_flush", "cleanup", "dispose",
              "finalize", "unbind", "kill", "abort", "drain"},
}

#: an owner class releases an attr only through a method reachable from
#: one of these surfaces (close() calling _join_worker() counts — the
#: per-class fixpoint follows self-calls)
RELEASE_SURFACE = RELEASE_ATTRS["owner"]

#: path-consuming calls that release a mkstemp temp NAME
_TEMPFILE_FREE = {"unlink", "remove", "replace", "rename", "move", "link"}

#: call basenames treated as non-raising for the exception-edge scan
#: (logging/printing/introspection — telemetry by contract never raises
#: into the path it observes)
_SAFE_CALLS = {"print", "len", "isinstance", "issubclass", "str", "int",
               "float", "bool", "repr", "min", "max", "round", "format",
               "getattr", "hasattr", "id", "type", "warn", "warning",
               "info", "debug", "error", "exception", "critical", "write",
               "flush", "fileno", "append", "items", "keys", "values",
               "get", "strip", "split", "join", "startswith", "endswith",
               "setdefault", "note", "fire", "active_plan", "time",
               "perf_counter", "monotonic"}

_MAX_CLASS_FIXPOINT = 8


def _basename(cname: Optional[str]) -> Optional[str]:
    return cname.rsplit(".", 1)[-1] if cname else None


class ResourceDecl:
    """One discovered acquisition site and how (or whether) it releases."""

    def __init__(self, kind: str, ctor: str, path: str, line: int,
                 func: str, binding: Optional[str]):
        self.kind = kind          # thread|server|file|tempfile|profiler|
        #                           listener|owner
        self.ctor = ctor          # constructor basename (or owner class)
        self.path = path
        self.line = line
        self.func = func          # acquiring function qualname
        self.binding = binding    # local name, "self.attr", or None
        self.status = "leak"      # with|finally|handler|inline|escape|
        #                           daemon|owned|module|leak
        self.detail = ""          # human-readable release description
        self.daemon = False
        self.owner: Optional[str] = None   # "Class.attr" for owned attrs

    def describe(self) -> str:
        where = f"{self.path}:{self.line}"
        bind = self.binding or "<unbound>"
        return (f"{self.kind:9s} {where} [{self.func}] {bind} "
                f"-> {self.status}" + (f" ({self.detail})" if self.detail
                                       else ""))


class ResourceAnalysis:
    """Package-wide result: acquisitions, the ownership graph, findings."""

    def __init__(self, package: PackageInfo):
        self.package = package
        self.resources: List[ResourceDecl] = []
        #: class name -> {attr: kind} for resource-owning classes
        self.owner_classes: Dict[str, Dict[str, str]] = {}
        #: (class, attr) -> releasing surface method name
        self.owner_release: Dict[Tuple[str, str], str] = {}
        self.findings: List[Finding] = []
        _Analyzer(package, self).run()

    # -- rendering ------------------------------------------------------
    def ownership_lines(self) -> List[str]:
        out = [f"resources discovered: {len(self.resources)}"]
        for r in sorted(self.resources, key=lambda r: (r.path, r.line)):
            out.append(f"  {r.describe()}")
        out.append(f"owner classes: {len(self.owner_classes)}")
        for cls in sorted(self.owner_classes):
            for attr, kind in sorted(self.owner_classes[cls].items()):
                rel = self.owner_release.get((cls, attr))
                out.append(f"  {cls}.{attr}  ({kind}, released by "
                           f"{rel + '()' if rel else 'NOTHING'})")
        return out

    def to_dot(self) -> str:
        lines = ["digraph resource_ownership {", "  rankdir=LR;"]
        for cls in sorted(self.owner_classes):
            lines.append(f'  "{cls}" [shape=box];')
            for attr, kind in sorted(self.owner_classes[cls].items()):
                rel = self.owner_release.get((cls, attr))
                color = "" if rel else ", color=red"
                lines.append(f'  "{cls}.{attr}" [shape=ellipse, '
                             f'label="{attr}\\n({kind})"{color}];')
                label = f"{rel}()" if rel else "LEAK"
                lines.append(f'  "{cls}" -> "{cls}.{attr}" '
                             f'[label="{label}"];')
        for r in self.resources:
            if r.binding and r.binding.startswith("self."):
                continue            # drawn via the owner-class edge
            node = f"{os.path.basename(r.path)}:{r.line}"
            color = ", color=red" if r.status == "leak" else ""
            lines.append(f'  "{node}" [shape=ellipse, '
                         f'label="{r.kind}\\n{node}\\n{r.status}"{color}];')
        lines.append("}")
        return "\n".join(lines)


class _Acq:
    """In-flight acquisition being verified inside one function."""

    def __init__(self, kind: str, ctor: str, node: ast.AST,
                 binding: Optional[str], daemon: bool):
        self.kind = kind
        self.ctor = ctor
        self.node = node
        self.binding = binding       # local name / "self.attr" / None
        self.daemon = daemon


class _Analyzer:
    def __init__(self, package: PackageInfo, result: ResourceAnalysis):
        self.pkg = package
        self.res = result
        # class name -> id(FunctionDef) members; and reverse
        self.class_of_node: Dict[int, str] = {}
        self.class_methods: Dict[str, List[FunctionInfo]] = {}
        # dynamic ctor map: RESOURCE_CTORS + discovered owner classes
        self.ctors: Dict[str, str] = dict(RESOURCE_CTORS)
        # ownership candidates to verify: (cls, attr) -> (kind, decl)
        self.pending_owned: Dict[Tuple[str, str], ResourceDecl] = {}

    # ==================================================================
    def _all_fns(self) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        seen: Set[int] = set()
        for m in self.pkg.modules:
            for lst in m.by_basename.values():
                for f in lst:
                    if id(f) not in seen:
                        seen.add(id(f))
                        out.append(f)
        return out

    def run(self) -> None:
        self._index_classes()
        self._discover_owner_classes()
        for fn in self._all_fns():
            self._walk_function(fn)
        self._verify_ownership()
        for m in self.pkg.modules:
            _CacheChecker(self.pkg, m, self.res).run()
        self.res.findings.sort(key=lambda f: (f.path, f.line, f.message))

    # -- class indexing / owner-class closure ---------------------------
    def _index_classes(self) -> None:
        for m in self.pkg.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.class_of_node[id(meth)] = node.name
        for fn in self._all_fns():
            cls = self.class_of_node.get(id(fn.node))
            if cls is not None:
                self.class_methods.setdefault(cls, []).append(fn)

    def _ctor_kind(self, call: ast.AST) -> Optional[Tuple[str, str]]:
        """(kind, ctor basename) when ``call`` constructs a resource."""
        if not isinstance(call, ast.Call):
            return None
        cname = call_name(call)
        base = _basename(cname)
        if base is None:
            return None
        if base == "trace":
            # only the profiler's trace context is a resource — not
            # str.trace or a package helper named trace
            if cname and "profiler" in cname:
                return "profiler", base
            return None
        kind = self.ctors.get(base)
        if kind is None:
            return None
        if base in ("open", "fdopen") and cname not in (
                "open", "io.open", "os.fdopen", "fdopen", "gzip.open"):
            return None              # image.open(...) etc.: not a file ctor
        return kind, base

    def _discover_owner_classes(self) -> None:
        """Classes holding a resource in a ``self.attr`` become resource
        constructors themselves (transitively): acquiring one acquires
        everything it owns, and its release surface is its close()."""
        for _ in range(_MAX_CLASS_FIXPOINT):
            grew = False
            for cls, methods in self.class_methods.items():
                for fn in methods:
                    for node in fn.own_nodes():
                        attr = self._self_attr_target(node)
                        if attr is None:
                            continue
                        ck = self._ctor_kind(node.value)
                        if ck is None:
                            continue
                        kind = ck[0]
                        kind = "owner" if kind == "owner" else kind
                        owned = self.res.owner_classes.setdefault(cls, {})
                        if attr not in owned:
                            owned[attr] = kind
                            grew = True
                        if cls not in self.ctors:
                            self.ctors[cls] = "owner"
                            grew = True
            if not grew:
                break

    @staticmethod
    def _self_attr_target(node: ast.AST) -> Optional[str]:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            return None
        t = node.targets[0]
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr
        return None

    # -- ownership verification (release-complete close) ----------------
    def _verify_ownership(self) -> None:
        """Per owner class: which attrs does each method release (direct
        ``self.attr.close()``, via a local alias, or via ``self.m()``
        where ``m`` releases it), then require a RELEASE_SURFACE method
        among the releasers of every owned resource attr."""
        releases: Dict[Tuple[str, str], Set[str]] = {}
        for cls, owned in self.res.owner_classes.items():
            for fn in self.class_methods.get(cls, []):
                for attr in owned:
                    if self._method_releases_attr(fn, attr, owned[attr]):
                        releases.setdefault((cls, fn.basename),
                                            set()).add(attr)
        # fixpoint: close() -> self._shutdown() -> joins the worker
        for _ in range(_MAX_CLASS_FIXPOINT):
            grew = False
            for cls, owned in self.res.owner_classes.items():
                for fn in self.class_methods.get(cls, []):
                    mine = releases.setdefault((cls, fn.basename), set())
                    for node in fn.own_nodes():
                        if isinstance(node, ast.Call) and \
                                isinstance(node.func, ast.Attribute) and \
                                isinstance(node.func.value, ast.Name) and \
                                node.func.value.id == "self":
                            callee = node.func.attr
                            extra = releases.get((cls, callee), set())
                            if extra - mine:
                                mine |= extra
                                grew = True
            if not grew:
                break
        for cls, owned in self.res.owner_classes.items():
            for attr, kind in owned.items():
                surface = sorted(
                    meth for (c, meth), attrs in releases.items()
                    if c == cls and attr in attrs
                    and meth in RELEASE_SURFACE)
                if surface:
                    self.res.owner_release[(cls, attr)] = surface[0]
        for (cls, attr), decl in sorted(self.pending_owned.items()):
            rel = self.res.owner_release.get((cls, attr))
            kind = self.res.owner_classes.get(cls, {}).get(attr, decl.kind)
            if rel is not None:
                decl.status = "owned"
                decl.detail = f"released by {cls}.{rel}()"
                decl.owner = f"{cls}.{attr}"
            elif decl.daemon:
                decl.status = "daemon"
                decl.detail = "daemon thread (dies with the process)"
            else:
                decl.status = "leak"
                self._find(decl.path, decl.line, decl.func,
                           f"{cls}.{attr} holds a {kind} acquired here "
                           f"but no release-surface method of {cls} "
                           "(close/stop/shutdown/__exit__) ever releases "
                           "it — every long-lived resource needs a "
                           "release-complete owner")

    def _method_releases_attr(self, fn: FunctionInfo, attr: str,
                              kind: str) -> bool:
        rel_attrs = RELEASE_ATTRS.get(kind, set()) | RELEASE_SURFACE
        aliases: Set[str] = set()
        for node in fn.own_nodes():
            # element-wise tuple assign: ms, self._x = self._x, None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                pairs = []
                if isinstance(tgt, ast.Tuple) and \
                        isinstance(val, ast.Tuple) and \
                        len(tgt.elts) == len(val.elts):
                    pairs = list(zip(tgt.elts, val.elts))
                else:
                    pairs = [(tgt, val)]
                for t, v in pairs:
                    if isinstance(t, ast.Name) and \
                            self._is_self_attr(v, attr):
                        aliases.add(t.id)
        for node in fn.own_nodes():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in rel_attrs:
                continue
            recv = node.func.value
            if self._is_self_attr(recv, attr):
                return True
            if isinstance(recv, ast.Name) and recv.id in aliases:
                return True
        return False

    @staticmethod
    def _is_self_attr(node: ast.AST, attr: str) -> bool:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr == attr):
            return True
        # getattr(self, "attr", default) — the defensive-teardown idiom
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == "self"
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value == attr)

    # -- per-function acquisition walk ----------------------------------
    def _find(self, path: str, line: int, func: str, message: str) -> None:
        self.res.findings.append(Finding("R012", path, line, func, message))

    def _walk_function(self, fn: FunctionInfo) -> None:
        self._walk_block(fn, list(fn.node.body), frames=[])

    def _walk_block(self, fn: FunctionInfo, stmts: List[ast.stmt],
                    frames: List[Tuple[List[ast.stmt], int]]) -> None:
        for i, st in enumerate(stmts):
            here = frames + [(stmts, i)]
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue            # analyzed separately
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    for sub in ast.walk(item.context_expr):
                        ck = self._ctor_kind(sub)
                        if ck:
                            self._record(fn, ck[0], ck[1], sub, None,
                                         "with", "context-managed")
                self._walk_block(fn, st.body, here)
                continue
            acq = self._acquisition_in(fn, st)
            if acq is not None:
                self._verify(fn, acq, st, here)
            for body in self._sub_blocks(st):
                self._walk_block(fn, body, here)

    @staticmethod
    def _sub_blocks(st: ast.stmt) -> List[List[ast.stmt]]:
        out = []
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(st, field, None)
            if blk:
                out.append(blk)
        for h in getattr(st, "handlers", []) or []:
            out.append(h.body)
        return out

    def _record(self, fn: FunctionInfo, kind: str, ctor: str,
                node: ast.AST, binding: Optional[str], status: str,
                detail: str) -> ResourceDecl:
        decl = ResourceDecl(kind, ctor, fn.module.path,
                            getattr(node, "lineno", 0), fn.qualname,
                            binding)
        decl.status = status
        decl.detail = detail
        self.res.resources.append(decl)
        return decl

    def _acquisition_in(self, fn: FunctionInfo,
                        st: ast.stmt) -> Optional[_Acq]:
        """An acquisition anchored at statement ``st`` (assign roots,
        bare constructor expressions, listener registrations)."""
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            value, tgt = st.value, st.targets[0]
            ck = self._root_ctor(value)
            if ck is None:
                return None
            kind, ctor = ck
            daemon = self._daemon_flag(value)
            if isinstance(tgt, ast.Name):
                return _Acq(kind, ctor, st, tgt.id, daemon)
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name):
                if tgt.value.id == "self":
                    return _Acq(kind, ctor, st, f"self.{tgt.attr}", daemon)
                self._record(fn, kind, ctor, st, dotted_name(tgt),
                             "escape", "stored on another object")
                return None
            if isinstance(tgt, ast.Subscript):
                self._record(fn, kind, ctor, st, None, "escape",
                             "stored into a container")
                return None
            if isinstance(tgt, ast.Tuple) and kind == "tempfile" and \
                    len(tgt.elts) == 2 and \
                    all(isinstance(e, ast.Name) for e in tgt.elts):
                # fd, tmp = mkstemp(): track the PATH name (the fd is
                # consumed by the fdopen the pattern wraps in `with`)
                return _Acq(kind, ctor, st, tgt.elts[1].id, daemon)
            return None
        if isinstance(st, ast.Expr):
            ck = self._root_ctor(st.value)
            if ck is None and isinstance(st.value, ast.Call) and \
                    isinstance(st.value.func, ast.Attribute) and \
                    st.value.func.attr == "start":
                ck = self._root_ctor(st.value.func.value)
            if ck is not None:
                kind, ctor = ck
                daemon = self._daemon_flag(
                    st.value.func.value if isinstance(st.value, ast.Call)
                    and isinstance(st.value.func, ast.Attribute)
                    and st.value.func.attr == "start" else st.value)
                return _Acq(kind, ctor, st, None, daemon)
            reg = self._listener_registration(st.value)
            if reg is not None:
                return _Acq("listener", reg[0], st, reg[1], False)
        return None

    def _root_ctor(self, value: ast.AST) -> Optional[Tuple[str, str]]:
        """Constructor at the ROOT of an assigned/expr value (nested-in-
        call constructions escape into the wrapper); an ``a if c else b``
        root follows both arms (the nullcontext-or-session idiom)."""
        if isinstance(value, ast.IfExp):
            return self._root_ctor(value.body) or \
                self._root_ctor(value.orelse)
        return self._ctor_kind(value)

    @staticmethod
    def _daemon_flag(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        for kw in value.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False

    @staticmethod
    def _listener_registration(value: ast.AST
                               ) -> Optional[Tuple[str, Optional[str]]]:
        if not isinstance(value, ast.Call):
            return None
        base = _basename(call_name(value))
        if base and base.startswith("register") and "listener" in base:
            arg = value.args[0] if value.args else None
            return base, arg.id if isinstance(arg, ast.Name) else None
        return None

    # -- release verification -------------------------------------------
    def _verify(self, fn: FunctionInfo, acq: _Acq, st: ast.stmt,
                frames: List[Tuple[List[ast.stmt], int]]) -> None:
        kind, line = acq.kind, getattr(st, "lineno", 0)
        is_init = fn.basename == "__init__"
        self_attr = acq.binding.split(".", 1)[1] \
            if acq.binding and acq.binding.startswith("self.") else None

        if acq.binding is None and acq.kind != "listener":
            if acq.daemon:
                self._record(fn, kind, acq.ctor, st, None, "daemon",
                             "unbound daemon thread")
            else:
                decl = self._record(fn, kind, acq.ctor, st, None, "leak",
                                    "constructed and dropped")
                self._find(fn.module.path, line, fn.qualname,
                           f"{acq.ctor}(...) {kind} started at line "
                           f"{line} without a binding — no handle exists "
                           "to join/close it (daemon=True, or keep a "
                           "reference with a release-complete owner)")
                del decl
            return

        verdict, detail, hazard = self._scan_release(fn, acq, frames)
        decl = self._record(fn, kind, acq.ctor, st, acq.binding,
                            "leak", "")
        decl.daemon = acq.daemon
        if verdict == "released":
            decl.status, decl.detail = "inline", detail
            if hazard is not None:
                decl.status = "leak"
                self._find(fn.module.path, line, fn.qualname,
                           f"{kind} acquired at line {line} is released "
                           f"only {detail}, but the call at line "
                           f"{hazard} in between can raise and skip the "
                           "release (the PR-10 pre-try profiler leak "
                           "shape) — move the acquisition next to its "
                           "try/finally")
            return
        if verdict == "protected":
            decl.status, decl.detail = "finally", detail
            return
        if verdict == "narrow-handler":
            decl.status = "leak"
            self._find(fn.module.path, line, fn.qualname,
                       f"temp file from {acq.ctor}() at line {line} is "
                       f"cleaned up by {detail} — a raise outside those "
                       "types (SimulatedKill, TypeError from a "
                       "serializer) orphans the temp file; catch "
                       "BaseException and re-raise")
            return
        if verdict == "escape":
            decl.status, decl.detail = "escape", detail
            return
        if self_attr is not None:
            cls = self.class_of_node.get(id(fn.node))
            if cls is not None:
                decl.owner = f"{cls}.{self_attr}"
                self.pending_owned.setdefault((cls, self_attr), decl)
                # owned attrs still leak out of a raising __init__: the
                # object is dropped before anyone can call close()
                if is_init and hazard is not None and not acq.daemon:
                    self._find(
                        fn.module.path, line, fn.qualname,
                        f"__init__ acquires self.{self_attr} ({kind}) at "
                        f"line {line} and the call at line {hazard} "
                        "after it can raise — the partially built object "
                        f"is dropped with the {kind} still live and no "
                        "handle to close it; wrap post-acquisition init "
                        "in try/except BaseException that releases "
                        f"self.{self_attr} and re-raises")
                return
        if acq.daemon:
            decl.status, decl.detail = "daemon", "daemon thread"
            return
        decl.status = "leak"
        what = ("listener registered" if kind == "listener"
                else f"{kind} acquired")
        self._find(fn.module.path, line, fn.qualname,
                   f"{what} at line {line} is never released on any "
                   "path — use `with`, release in a finally, transfer "
                   "ownership, or anchor a deliberate process-lifetime "
                   "hold in tpulint.allow with a justification")

    def _scan_release(self, fn: FunctionInfo, acq: _Acq,
                      frames: List[Tuple[List[ast.stmt], int]]
                      ) -> Tuple[str, str, Optional[int]]:
        """Scan enclosing finallys, then the statement remainder, for a
        guaranteed release of ``acq.binding``.

        Returns (verdict, detail, first_hazard_line): verdict in
        {"released", "protected", "narrow-handler", "escape", "none"} —
        "protected" means exception-safe (finally / catch-all handler),
        "released" means straight-line (caller decides whether a hazard
        before it makes an exception-edge finding).
        """
        binding = acq.binding
        aliases: Set[str] = set()
        # mutable scan state shared across nested blocks
        narrow: List[Optional[str]] = [None]
        exc_covered: List[bool] = [False]
        hazard: List[Optional[int]] = [None]

        # enclosing trys: release in a finalbody is guaranteed; a
        # releasing handler covers (or narrowly covers) the raise edge
        for outer_stmts, outer_idx in frames:
            st = outer_stmts[outer_idx]
            if isinstance(st, ast.Try):
                if self._block_releases(st.finalbody, acq, aliases):
                    return ("protected",
                            f"in the finally at line {st.lineno}", None)
                cover, nar = self._handler_release(st, acq, aliases)
                exc_covered[0] = exc_covered[0] or cover
                narrow[0] = narrow[0] or nar

        def verdict_for(line: int, via_with: bool
                        ) -> Tuple[str, str, Optional[int]]:
            # `with session:` on a lazily-entered context manager
            # (trace_session / jax.profiler.trace) acquires only at
            # __enter__, inside the with — hazards before it are moot
            protected = via_with and acq.kind == "profiler"
            if protected or exc_covered[0]:
                return "protected", f"at line {line}", None
            if hazard[0] is not None and acq.kind == "tempfile" and \
                    narrow[0] is not None:
                return "narrow-handler", narrow[0], hazard[0]
            return "released", f"at line {line}", hazard[0]

        def scan(stmts: Sequence[ast.stmt]
                 ) -> Optional[Tuple[str, str, Optional[int]]]:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.Try):
                    local = set(aliases)
                    if self._block_releases(st.finalbody, acq, local):
                        if hazard[0] is not None:
                            return ("released",
                                    f"in the finally at line {st.lineno}",
                                    hazard[0])
                        return ("protected",
                                f"in the finally at line {st.lineno}",
                                None)
                    cover, nar = self._handler_release(st, acq, local)
                    exc_covered[0] = exc_covered[0] or cover
                    narrow[0] = narrow[0] or nar
                    r = scan(st.body)
                    if r is not None:
                        return r
                    if st.orelse:
                        r = scan(st.orelse)
                        if r is not None:
                            return r
                    continue
                self._collect_aliases(st, binding, aliases)
                rel = self._stmt_contains_release(st, acq, aliases)
                if rel is not None:
                    return verdict_for(rel[0], rel[1])
                if self._stmt_escapes(st, binding, aliases):
                    return ("escape",
                            "ownership transferred (returned / stored / "
                            "passed onward)", None)
                h = self._stmt_hazard(st, binding, aliases)
                if h is not None and hazard[0] is None:
                    hazard[0] = h
            return None

        # linear remainder: rest of each block, innermost outward
        for stmts, idx in reversed(frames):
            r = scan(stmts[idx + 1:])
            if r is not None:
                return r
        if acq.kind == "tempfile" and narrow[0] is not None and \
                not exc_covered[0]:
            return "narrow-handler", narrow[0], hazard[0]
        # a covering catch-all handler releases on the raise edge: any
        # hazard is moot (the normal-path release is judged separately —
        # for owned self-attrs that is the owner's close())
        return "none", "", None if exc_covered[0] else hazard[0]

    # -- statement predicates -------------------------------------------
    def _is_binding(self, node: ast.AST, binding: Optional[str],
                    aliases: Set[str]) -> bool:
        if binding is None:
            return False
        if binding.startswith("self."):
            return self._is_self_attr(node, binding.split(".", 1)[1])
        return (isinstance(node, ast.Name)
                and (node.id == binding or node.id in aliases))

    def _collect_aliases(self, st: ast.stmt, binding: Optional[str],
                         aliases: Set[str]) -> None:
        if not isinstance(st, ast.Assign) or len(st.targets) != 1:
            return
        tgt, val = st.targets[0], st.value
        pairs = [(tgt, val)]
        if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                and len(tgt.elts) == len(val.elts):
            pairs = list(zip(tgt.elts, val.elts))
        for t, v in pairs:
            if isinstance(t, ast.Name) and \
                    self._is_binding(v, binding, aliases):
                aliases.add(t.id)

    def _expr_releases(self, node: ast.AST, acq: _Acq,
                       aliases: Set[str]) -> bool:
        """One expression node releasing the binding."""
        if not isinstance(node, ast.Call):
            return False
        binding = acq.binding
        if acq.kind == "tempfile":
            base = _basename(call_name(node))
            if base in _TEMPFILE_FREE:
                return any(self._is_binding(a, binding, aliases)
                           for a in node.args)
        if acq.kind == "listener":
            base = _basename(call_name(node)) or ""
            if "unregister" in base:
                return binding is None or any(
                    self._is_binding(a, binding, aliases)
                    for a in node.args)
        if isinstance(node.func, ast.Attribute):
            rel = RELEASE_ATTRS.get(acq.kind, set())
            if acq.kind == "owner":
                rel = RELEASE_SURFACE
            if node.func.attr in rel and \
                    self._is_binding(node.func.value, acq.binding,
                                     aliases):
                return True
        return False

    def _stmt_contains_release(self, st: ast.AST, acq: _Acq,
                               aliases: Set[str]
                               ) -> Optional[Tuple[int, bool]]:
        """(line, via_with) of a release of the binding inside ``st``."""
        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if self._expr_releases(node, acq, aliases):
                return (getattr(node, "lineno",
                                getattr(st, "lineno", 0)), False)
            # `with binding:` / `with closing(binding):` enters the
            # context manager — its __exit__ IS the release
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call) and expr.args:
                        expr = expr.args[0]
                    if self._is_binding(expr, acq.binding, aliases):
                        return (getattr(item.context_expr, "lineno",
                                        node.lineno), True)
        return None

    def _block_releases(self, stmts: Sequence[ast.stmt], acq: _Acq,
                        aliases: Set[str]) -> bool:
        local = set(aliases)
        for st in stmts:
            self._collect_aliases(st, acq.binding, local)
            if self._stmt_contains_release(st, acq, local) is not None:
                return True
        return False

    def _handler_release(self, st: ast.Try, acq: _Acq,
                         aliases: Set[str]) -> Tuple[bool, Optional[str]]:
        """(catch-all handler releases, narrow-handler description)."""
        covered, narrow = False, None
        for h in st.handlers:
            if not self._block_releases(h.body, acq, aliases):
                continue
            tname = dotted_name(h.type) if h.type is not None else None
            if h.type is None or tname == "BaseException":
                covered = True
            else:
                narrow = (f"an `except {tname or '<...>'}` handler at "
                          f"line {h.lineno} only")
        return covered, narrow

    def _stmt_escapes(self, st: ast.stmt, binding: Optional[str],
                      aliases: Set[str]) -> bool:
        if binding is None or binding.startswith("self."):
            return False
        if isinstance(st, ast.Return) and st.value is not None:
            return any(isinstance(n, ast.Name) and
                       (n.id == binding or n.id in aliases)
                       for n in ast.walk(st.value))
        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)) and \
                            self._value_refs(node.value, binding, aliases):
                        return True
                # tuple-assign into subscripts (the shared-probe shape:
                # d["thread"], d["box"] = thread, box)
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and \
                            isinstance(node.value, ast.Tuple) and \
                            len(t.elts) == len(node.value.elts):
                        for te, ve in zip(t.elts, node.value.elts):
                            if isinstance(te, (ast.Subscript,
                                               ast.Attribute)) and \
                                    self._value_refs(ve, binding, aliases):
                                return True
            if isinstance(node, ast.Call):
                recv = node.func.value \
                    if isinstance(node.func, ast.Attribute) else None
                for a in list(node.args) + \
                        [k.value for k in node.keywords]:
                    if isinstance(a, ast.Name) and \
                            (a.id == binding or a.id in aliases) and \
                            not (isinstance(recv, ast.Name)
                                 and recv.id in {binding} | aliases):
                        return True
            if isinstance(node, (ast.Yield, ast.YieldFrom)) and \
                    node.value is not None and \
                    self._value_refs(node.value, binding, aliases):
                return True
        return False

    @staticmethod
    def _value_refs(node: ast.AST, binding: Optional[str],
                    aliases: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and
                   (n.id == binding or n.id in aliases)
                   for n in ast.walk(node))

    def _stmt_hazard(self, st: ast.stmt, binding: Optional[str],
                     aliases: Set[str]) -> Optional[int]:
        """Line of the first can-raise call in ``st`` that is not on the
        binding itself and not a declared-safe telemetry call."""
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Import, ast.ImportFrom)):
            return None
        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            base = _basename(call_name(node))
            if base in _SAFE_CALLS:
                continue
            if isinstance(node.func, ast.Attribute):
                recv = node.func.value
                if self._is_binding(recv, binding, aliases):
                    continue        # x.start()/x.__enter__(): the
                #                     resource's own protocol
                rd = dotted_name(recv)
                if rd in ("log", "logging", "logger", "warnings",
                          "flight"):
                    continue
            return getattr(node, "lineno", getattr(st, "lineno", 0))
        return None


# ======================================================================
# retained-program / cache-bound checker (the PR 14 class)

#: decorator basenames that memoize
_MEMO_DECORATORS = {"lru_cache", "cache"}
#: annotation names whose key domain is unbounded for a program cache
_UNBOUNDED_ANNOTATIONS = {"float", "complex"}
#: value-constructor suffixes that mark a per-key metric series
_SERIES_SUFFIXES = ("Histogram", "Series", "Window", "Accumulator")
#: cache-name fragments that mark retained programs/arrays
_CACHE_NAME_HINTS = ("cache", "jitted", "program", "compiled")
#: key-mapping basename fragments that bound the key domain
_BUCKET_HINTS = ("rung", "bucket")


class _CacheChecker:
    def __init__(self, package: PackageInfo, module: ModuleInfo,
                 result: ResourceAnalysis):
        self.pkg = package
        self.m = module
        self.res = result

    def _find(self, node: ast.AST, func: str, message: str) -> None:
        self.res.findings.append(Finding(
            "R012", self.m.path, getattr(node, "lineno", 0), func,
            message))

    def run(self) -> None:
        self._check_memo_factories()
        self._check_dict_caches()

    # -- lru_cache jitted-program factories ------------------------------
    def _check_memo_factories(self) -> None:
        for fn in self.m.functions.values():
            deco = self._memo_decorator(fn.node)
            if deco is None:
                continue
            bounded, label = deco
            if bounded:
                continue
            if not self._body_builds_jit(fn):
                continue
            bad = self._unbounded_params(fn)
            if bad:
                self._find(
                    fn.node, fn.qualname,
                    f"unbounded {label} retains one jitted program per "
                    f"distinct key, and parameter(s) {', '.join(bad)} "
                    "have float/unannotated key domains — a long-lived "
                    "refit loop retains a program per model version "
                    "forever (the PR 14 _score_accum_fn bug); bound the "
                    "cache (maxsize=N) or key only on small annotated "
                    "int/bool domains with the varying floats passed as "
                    "traced scalars")

    @staticmethod
    def _memo_decorator(node: ast.AST
                        ) -> Optional[Tuple[bool, str]]:
        """(bounded, label) for an lru_cache/functools.cache decorator."""
        for dec in node.decorator_list:
            name = dotted_name(dec if not isinstance(dec, ast.Call)
                               else dec.func)
            base = _basename(name)
            if base not in _MEMO_DECORATORS:
                continue
            if base == "cache":
                if name not in ("functools.cache", "cache"):
                    continue
                return False, "functools.cache"
            if not isinstance(dec, ast.Call):
                return True, "lru_cache"        # bare: default 128
            maxsize = None
            has_kw = False
            for kw in dec.keywords:
                if kw.arg == "maxsize":
                    has_kw = True
                    maxsize = kw.value
            if not has_kw and dec.args:
                has_kw, maxsize = True, dec.args[0]
            if not has_kw:
                return True, "lru_cache()"      # default 128
            if isinstance(maxsize, ast.Constant) and \
                    maxsize.value is None:
                return False, "lru_cache(maxsize=None)"
            return True, "lru_cache"
        return None

    @staticmethod
    def _body_builds_jit(fn: FunctionInfo) -> bool:
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) and call_name(node) in JIT_NAMES:
                return True
        return False

    @staticmethod
    def _unbounded_params(fn: FunctionInfo) -> List[str]:
        out = []
        a = fn.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg in ("self", "cls"):
                continue
            ann = p.annotation
            if ann is None:
                out.append(f"{p.arg} (unannotated)")
                continue
            name = _basename(dotted_name(ann)) or ""
            if name in _UNBOUNDED_ANNOTATIONS:
                out.append(f"{p.arg}: {name}")
        return out

    # -- dict caches keyed from arguments --------------------------------
    def _check_dict_caches(self) -> None:
        caches = self._discover_caches()
        if not caches:
            return
        assigns, prunes = self._bound_evidence(caches)
        stores = self._keyed_stores(caches)
        for key, (decl_node, where) in caches.items():
            sites = [s for s in stores if s[0] == key]
            if not sites:
                continue
            retained = any(s[3] for s in sites)
            if not retained:
                continue
            if prunes.get(key) or len(assigns.get(key, [])) >= 2:
                continue
            if all(s[4] for s in sites):
                continue                 # every store key is bucketed
            node, func = sites[0][1], sites[0][2]
            label = key[1] if key[0] == "<module>" else \
                f"{key[0]}.{key[1]}"
            self._find(
                node, func,
                f"retained-program cache {label} is keyed from function "
                "arguments and stores jitted programs / per-key metric "
                "series with no statically visible bound (no eviction "
                "pop/clear, no pruning re-assignment, no rung/bucket "
                "key mapping) — a long-lived server grows it per "
                "version/request forever (the PR 14 /metrics "
                "cardinality class); add an LRU cap or prune on swap")

    def _discover_caches(self) -> Dict[Tuple[str, str],
                                       Tuple[ast.AST, str]]:
        """(scope, name) -> (decl node, init func); scope is the class
        name for ``self._x`` caches, "<module>" for module-level dicts."""
        caches: Dict[Tuple[str, str], Tuple[ast.AST, str]] = {}
        for node in self.m.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name) and node.value:
                tgt, val = node.target.id, node.value
            else:
                continue
            if self._is_empty_dict(val):
                caches[("<module>", tgt)] = (node, "<module>")
        for cls_node in ast.walk(self.m.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for meth in cls_node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(meth):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1:
                        t = sub.targets[0]
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self" and \
                                self._is_empty_dict(sub.value):
                            caches.setdefault(
                                (cls_node.name, t.attr),
                                (sub, meth.name))
        return caches

    @staticmethod
    def _is_empty_dict(val: ast.AST) -> bool:
        if isinstance(val, ast.Dict) and not val.keys:
            return True
        return (isinstance(val, ast.Call)
                and _basename(call_name(val)) in ("dict", "OrderedDict")
                and not val.args and not val.keywords)

    def _cache_key_of(self, node: ast.AST, caches, cls: Optional[str],
                      aliases: Dict[str, Tuple[str, str]]
                      ) -> Optional[Tuple[str, str]]:
        """Resolve an expression to a discovered cache binding."""
        if isinstance(node, ast.Name):
            if node.id in aliases:
                return aliases[node.id]
            if ("<module>", node.id) in caches:
                return ("<module>", node.id)
            return None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cls is not None and \
                (cls, node.attr) in caches:
            return (cls, node.attr)
        return None

    def _class_of_fn(self, fn: FunctionInfo) -> Optional[str]:
        for cls_node in ast.walk(self.m.tree):
            if isinstance(cls_node, ast.ClassDef):
                for meth in cls_node.body:
                    if meth is fn.node:
                        return cls_node.name
        return None

    def _keyed_stores(self, caches):
        """Every ``cache[key] = value`` / ``cache.setdefault(key, v)``
        whose key derives from the enclosing function's arguments:
        (cache key, node, func qualname, retained, bucketed)."""
        out = []
        for fn in self.m.functions.values():
            cls = self._class_of_fn(fn)
            params = set(fn.pos_params) | set(fn.kwonly_params)
            params.discard("self")
            derived = self._derived_names(fn, params)
            aliases: Dict[str, Tuple[str, str]] = {}
            for node in fn.own_nodes():
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    ck = self._cache_key_of(node.value, caches, cls,
                                            aliases)
                    if ck is not None:
                        aliases[node.targets[0].id] = ck
            for node in fn.own_nodes():
                key_expr = value_expr = None
                target = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            target, key_expr = t.value, t.slice
                            value_expr = node.value
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "setdefault" and node.args:
                    target = node.func.value
                    key_expr = node.args[0]
                    value_expr = node.args[1] if len(node.args) > 1 \
                        else None
                if target is None or key_expr is None:
                    continue
                ck = self._cache_key_of(target, caches, cls, aliases)
                if ck is None:
                    continue
                if not self._refs_any(key_expr, derived):
                    continue
                retained = self._is_retained(ck, value_expr)
                bucketed = self._is_bucketed(fn, key_expr)
                out.append((ck, node, fn.qualname, retained, bucketed))
        return out

    @staticmethod
    def _derived_names(fn: FunctionInfo, seed: Set[str]) -> Set[str]:
        names = set(seed)
        for _ in range(6):
            grew = False
            for n in fn.own_nodes():
                if isinstance(n, ast.Assign) and \
                        any(isinstance(x, ast.Name) and x.id in names
                            for x in ast.walk(n.value)):
                    for t in n.targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name) and \
                                    leaf.id not in names:
                                names.add(leaf.id)
                                grew = True
            if not grew:
                break
        return names

    @staticmethod
    def _refs_any(node: ast.AST, names: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in names
                   for n in ast.walk(node))

    def _is_retained(self, ck: Tuple[str, str],
                     value_expr: Optional[ast.AST]) -> bool:
        name = ck[1].lower()
        if any(h in name for h in _CACHE_NAME_HINTS):
            return True
        if value_expr is None:
            return False
        for n in ast.walk(value_expr):
            if isinstance(n, ast.Call):
                if call_name(n) in JIT_NAMES:
                    return True
                base = _basename(call_name(n)) or ""
                if base.endswith(_SERIES_SUFFIXES):
                    return True
        return False

    def _is_bucketed(self, fn: FunctionInfo, key_expr: ast.AST) -> bool:
        def expr_bucketed(e: ast.AST) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    base = (_basename(call_name(n)) or "").lower()
                    if any(h in base for h in _BUCKET_HINTS):
                        return True
            return False

        if expr_bucketed(key_expr):
            return True
        # one level of indirection: key = rung_of(n); cache[key] = ...
        key_names = {n.id for n in ast.walk(key_expr)
                     if isinstance(n, ast.Name)}
        for n in fn.own_nodes():
            if isinstance(n, ast.Assign) and \
                    any(isinstance(t, ast.Name) and t.id in key_names
                        for t in n.targets) and expr_bucketed(n.value):
                return True
        return False

    def _bound_evidence(self, caches):
        """Per cache: assignment sites (any value) and prune operations
        (pop/popitem/clear/del) found anywhere in the module."""
        assigns: Dict[Tuple[str, str], List[int]] = {}
        prunes: Dict[Tuple[str, str], bool] = {}

        def note_assign(ck, line):
            sites = assigns.setdefault(ck, [])
            if line not in sites:
                sites.append(line)

        for fn in list(self.m.functions.values()):
            cls = self._class_of_fn(fn)
            for node in fn.own_nodes():
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        ck = self._cache_key_of(t, caches, cls, {})
                        if ck is not None:
                            note_assign(ck, node.lineno)
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("pop", "popitem", "clear"):
                    ck = self._cache_key_of(node.func.value, caches,
                                            cls, {})
                    if ck is not None:
                        prunes[ck] = True
                if isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            ck = self._cache_key_of(t.value, caches,
                                                    cls, {})
                            if ck is not None:
                                prunes[ck] = True
        for node in self.m.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                ck = ("<module>", node.targets[0].id)
                if ck in caches:
                    note_assign(ck, node.lineno)
        return assigns, prunes


# ======================================================================
def analyze_package(package: PackageInfo) -> ResourceAnalysis:
    """Run (or fetch the cached) whole-package resource analysis."""
    cached = getattr(package, "_r012_analysis", None)
    if cached is None:
        cached = ResourceAnalysis(package)
        package._r012_analysis = cached
    return cached


def analyze_paths(paths: Sequence[str]
                  ) -> Tuple[ResourceAnalysis, List[str]]:
    from . import tpulint as _tl
    modules: List[ModuleInfo] = []
    errors: List[str] = []
    for path in _tl._iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(path, source, _tl._dotted_of(path)))
        except (SyntaxError, OSError, UnicodeDecodeError) as err:
            errors.append(f"{path}: {err}")
    return analyze_package(PackageInfo(modules)), errors


def _default_package_path() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    from . import tpulint as _tl

    ap = argparse.ArgumentParser(
        prog="tpulint resources",
        description="interprocedural resource-lifecycle & cache-bound "
                    "analyzer (R012)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the package)")
    ap.add_argument("--dot", action="store_true",
                    help="emit the ownership graph as Graphviz")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--allowlist", default=_tl.DEFAULT_ALLOWLIST)
    ap.add_argument("--no-allowlist", action="store_true")
    args = ap.parse_args(argv)

    paths = args.paths or [_default_package_path()]
    analysis, errors = analyze_paths(paths)
    findings = list(analysis.findings)

    entries: List[_tl.AllowEntry] = []
    allow_errors: List[str] = []
    if not args.no_allowlist:
        entries, allow_errors = _tl.load_allowlist(args.allowlist)
        entries = [e for e in entries if e.rule == "R012"]
        findings = _tl.apply_allowlist(findings, entries)

    if args.dot:
        print(analysis.to_dot())
    elif args.as_json:
        import json
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        for line in analysis.ownership_lines():
            print(line)
        for f in findings:
            print(f.render())
        print(f"tpulint resources: {len(findings)} finding(s)",
              file=sys.stderr)
    for err in errors + allow_errors:
        print(f"tpulint resources: error: {err}", file=sys.stderr)

    if errors or allow_errors:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
