"""tpulint — static JAX/TPU hazard analyzer for this repo.

An AST pass over the package with repo-specific rules (the reference
LightGBM ships sanitizer/CI wiring around its treelearner/network layers
for the same reason — correctness tooling as a first-class layer):

  R001  host sync in jit-reachable code (float()/.item()/np.asarray/
        jax.device_get on traced values in the growers and train step)
  R002  recompilation hazards (jit-in-loop, unhashable static defaults,
        Python branching on traced values)
  R003  dtype drift (numpy ops on traced values, f64 requests in device
        code)
  R004  Pallas contracts (32-multiple block sizes, validated env
        overrides, fused_split pad contract via num_rows=)
  R005  async collective accounting must count result shapes; inventories
        need the -start twins (psum_scatter => reduce-scatter-start) and
        -done ops carry no bytes
  R006  shard_map/collective axis names must exist in a declared mesh;
        sharded values gather explicitly before host readback
  R007  public Booster/Dataset methods hold the _api_lock rwlock;
        mutating methods take the write side
  R008  serving request paths shed load and time out: no unbounded
        queues (maxsize/maxlen mandatory, SimpleQueue banned), no
        blocking get/result/wait/join without a timeout, no blocking
        put without block=False/timeout
  R009  host-clock timing around async dispatch: time.time()/
        perf_counter()/span-close in jit-reachable code is a finding,
        and any clock-plus-dispatch function without block_until_ready
        is pinned (declared tick sites carry allowlist anchors)

Deliberate exceptions live in the checked-in allowlist
(analysis/tpulint.allow), one entry per line:

    R002 lightgbm_tpu/ops/compact.py::partition_segment  # justification

Every entry MUST carry a ``# justification`` — entries without one are a
lint error themselves. The function part accepts a bare basename or
``*`` for module-level findings. Unused entries print a warning so the
file cannot rot silently.

CLI: ``scripts/tpulint lightgbm_tpu/`` (exit 0 = clean tree); the tier-1
suite runs the same pass via tests/test_tpulint.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .rules import ALL_RULES, Finding, ModuleInfo, PackageInfo

DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__), "tpulint.allow")


class AllowEntry:
    def __init__(self, rule: str, path: str, func: str, justification: str,
                 lineno: int):
        self.rule = rule
        self.path = path.replace(os.sep, "/")
        self.func = func
        self.justification = justification
        self.lineno = lineno
        self.used = False

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule:
            return False
        fpath = f.path.replace(os.sep, "/")
        if not (fpath == self.path or fpath.endswith("/" + self.path)):
            return False
        return self.func in ("*", f.func, f.func.rsplit(".", 1)[-1])

    def render(self) -> str:
        return f"{self.rule} {self.path}::{self.func}"


def load_allowlist(path: str) -> Tuple[List[AllowEntry], List[str]]:
    """Parse the allowlist; returns (entries, format errors)."""
    entries: List[AllowEntry] = []
    errors: List[str] = []
    if not os.path.exists(path):
        return entries, errors
    with open(path) as fh:
        for lineno, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, justification = line.partition("#")
            justification = justification.strip()
            if not justification:
                errors.append(
                    f"{path}:{lineno}: allowlist entry without a "
                    "justification — every exception needs a one-line "
                    "'# why'")
                continue
            parts = body.split()
            if len(parts) != 2 or "::" not in parts[1]:
                errors.append(
                    f"{path}:{lineno}: malformed entry (expected "
                    "'RXXX path::func  # justification')")
                continue
            rule = parts[0]
            fpath, _, func = parts[1].partition("::")
            entries.append(AllowEntry(rule, fpath, func or "*",
                                      justification, lineno))
    return entries, errors


def _allowlist_root(allowlist_path: str) -> str:
    """The package root the allowlist's anchors are judged against: walk
    up from the allowlist file through ``__init__.py`` packages, so a
    subset lint (``tpulint lightgbm_tpu/ops --check-allow``) still
    validates entries anchored elsewhere in the package instead of
    reporting them stale."""
    d = os.path.dirname(os.path.abspath(allowlist_path))
    while os.path.exists(os.path.join(os.path.dirname(d), "__init__.py")):
        d = os.path.dirname(d)
    return d


def check_allowlist_staleness(entries: Sequence[AllowEntry],
                              paths: Sequence[str],
                              allowlist_path: Optional[str] = None
                              ) -> List[str]:
    """Flag allowlist entries whose file::func anchor no longer matches
    the source — the staleness pass that keeps the file from accumulating
    exceptions for code that moved or died.

    Anchors are resolved against the union of ``paths`` and (when given)
    the allowlist's own package root, so linting a subtree does not
    false-flag entries anchored outside it. An entry is stale when no
    file matches its path suffix, or (for a non-``*`` func) the anchored
    file no longer defines a function with that basename. Returned
    strings are error messages; the tier-1 gate and ``--check-allow``
    treat any as a failure.
    """
    import ast as _ast
    roots = list(paths)
    if allowlist_path is not None:
        roots.append(_allowlist_root(allowlist_path))
    files = sorted({p.replace(os.sep, "/") for p in _iter_py_files(roots)})
    defined_cache: Dict[str, set] = {}

    def defined_in(f: str) -> set:
        if f not in defined_cache:
            names: set = set()
            try:
                with open(f, encoding="utf-8") as fh:
                    tree = _ast.parse(fh.read(), filename=f)
                names = {n.name for n in _ast.walk(tree)
                         if isinstance(n, (_ast.FunctionDef,
                                           _ast.AsyncFunctionDef))}
            except (SyntaxError, OSError, UnicodeDecodeError):
                pass
            defined_cache[f] = names
        return defined_cache[f]

    stale: List[str] = []
    for e in entries:
        hits = [f for f in files
                if f == e.path or f.endswith("/" + e.path)]
        if not hits:
            stale.append(
                f"allowlist line {e.lineno}: stale entry {e.render()} — "
                f"no file matches '{e.path}'")
            continue
        if e.func == "*":
            continue
        want = e.func.rsplit(".", 1)[-1]
        if not any(want in defined_in(f) for f in hits):
            stale.append(
                f"allowlist line {e.lineno}: stale entry {e.render()} — "
                f"'{e.path}' no longer defines a function '{want}'")
    return stale


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _dotted_of(path: str) -> Optional[str]:
    """Dotted module name by walking up through __init__.py packages."""
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(os.path.abspath(path))
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if len(parts) == 1:
        return None
    if parts[0] == "__init__":
        parts = parts[1:]
    return ".".join(reversed(parts))


def lint_paths(paths: Sequence[str], rules=None
               ) -> Tuple[List[Finding], List[str]]:
    """Run all rules over the python files under ``paths``.

    Returns (findings, parse/read errors). Findings are sorted by
    (path, line, rule) for stable output.
    """
    rules = [r() for r in (rules or ALL_RULES)]
    modules: List[ModuleInfo] = []
    errors: List[str] = []
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(path, source, _dotted_of(path)))
        except (SyntaxError, OSError, UnicodeDecodeError) as err:
            errors.append(f"{path}: {err}")
    package = PackageInfo(modules)
    findings: List[Finding] = []
    for module in modules:
        for rule in rules:
            findings.extend(rule.check(module, package))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


def apply_allowlist(findings: List[Finding], entries: List[AllowEntry]
                    ) -> List[Finding]:
    kept: List[Finding] = []
    for f in findings:
        hit = next((e for e in entries if e.matches(f)), None)
        if hit is not None:
            hit.used = True
        else:
            kept.append(f)
    return kept


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="allowlist file (default: analysis/tpulint.allow)")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="report allowlisted findings too")
    ap.add_argument("--check-allow", action="store_true",
                    help="fail on allowlist entries whose file::func "
                         "anchor no longer matches the source")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    args = ap.parse_args(argv)

    findings, errors = lint_paths(args.paths)
    allow_errors: List[str] = []
    entries: List[AllowEntry] = []
    if not args.no_allowlist or args.check_allow:
        # --check-allow validates anchors even under --no-allowlist (an
        # audit run must not silently skip the staleness pass)
        entries, allow_errors = load_allowlist(args.allowlist)
    if not args.no_allowlist:
        findings = apply_allowlist(findings, entries)
    if args.check_allow:
        allow_errors += check_allowlist_staleness(entries, args.paths,
                                                  args.allowlist)

    for err in errors + allow_errors:
        print(f"tpulint: error: {err}", file=sys.stderr)
    if not args.no_allowlist:
        for e in entries:
            if not e.used:
                print(f"tpulint: warning: unused allowlist entry "
                      f"{e.render()} (line {e.lineno})", file=sys.stderr)

    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"tpulint: {len(findings)} finding(s)", file=sys.stderr)

    if errors or allow_errors:
        return 2
    return 1 if findings else 0


#: the `tpulint all` stage table: name -> (argv builder, json argv
#: builder or None when the stage has no --json mode). The jax-free
#: stages come first so a broken backend still reports the AST verdicts.
_ALL_STAGES = ("ast", "locks", "resources", "knobs", "hlo", "spmd")
#: stages that lower real jax programs (need the real package + backend)
_JAX_STAGES = ("hlo", "spmd")


def _stage_runner(name: str, pkg: str, as_json: bool):
    """(argv, main) for one aggregate stage — imports lazily so the
    jax-lowering stages load only when actually run."""
    if name == "ast":
        argv = [pkg, "--check-allow"] + (["--json"] if as_json else [])
        return argv, main
    if name == "locks":
        from .locks import main as locks_main
        return [pkg] + (["--json"] if as_json else []), locks_main
    if name == "resources":
        from .resources import main as resources_main
        return [pkg] + (["--json"] if as_json else []), resources_main
    if name == "knobs":
        from .knobs import main as knobs_main
        return (["--json"] if as_json else []), knobs_main
    if name == "hlo":
        from .hlo_check import main as hlo_main
        return [], hlo_main
    if name == "spmd":
        from .spmd_check import main as spmd_main
        return [], spmd_main
    raise ValueError(f"unknown tpulint stage {name!r}")


def main_all(argv: Optional[Sequence[str]] = None,
             package_path: Optional[str] = None) -> int:
    """`scripts/tpulint all`: every analyzer, one exit code.

    With ``--json``, emits ONE machine-readable object
    ``{"stages": {name: {"exit": rc, "findings": [...]} | {"exit": rc,
    "report": {...}} | {"exit": rc, "output": "..."}}, "exit": rc}`` —
    a findings list for the lint stages (ast/locks/resources), an object
    report for knobs, captured text for the program-lowering ones
    (hlo/spmd) — so CI and the refit daemon can consume the flight
    check programmatically."""
    import contextlib
    import io

    ap = argparse.ArgumentParser(prog="tpulint all")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--only", default="",
                    help="comma-separated stage subset of "
                         + ",".join(_ALL_STAGES))
    args = ap.parse_args(argv)
    selected = [s.strip() for s in args.only.split(",") if s.strip()] \
        or list(_ALL_STAGES)
    unknown = [s for s in selected if s not in _ALL_STAGES]
    if unknown:
        print(f"tpulint all: unknown stage(s) {', '.join(unknown)}",
              file=sys.stderr)
        return 2

    pkg = package_path or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    rc = 0
    stages: Dict[str, Dict[str, object]] = {}
    for name in _ALL_STAGES:
        if name not in selected:
            continue
        stage_argv, run = _stage_runner(name, pkg, args.as_json)
        if args.as_json:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                stage_rc = run(stage_argv)
            text = buf.getvalue()
            entry: Dict[str, object] = {"exit": int(stage_rc)}
            try:
                parsed = json.loads(text)
            except ValueError:
                entry["output"] = text
            else:
                # finding-list stages vs object-report stages (knobs)
                key = "findings" if isinstance(parsed, list) else "report"
                entry[key] = parsed
            stages[name] = entry
        else:
            print(f"== tpulint {name} ==", flush=True)
            stage_rc = run(stage_argv)
            print(f"== tpulint {name}: exit {stage_rc} ==", flush=True)
        rc = max(rc, int(stage_rc))
    if args.as_json:
        print(json.dumps({"stages": stages, "exit": rc}, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
