"""Shared AST infrastructure for tpulint rules.

The interesting part is *jit-reachability*: most hazards (host syncs, dtype
drift, tracer branching) are only hazards inside code that runs under a
``jax.jit`` trace. A function is considered jit-reachable when it is

  * decorated with ``jax.jit`` / ``functools.partial(jax.jit, ...)``, or
  * passed to a ``jax.jit(...)`` call anywhere in the package
    (``jitted = jax.jit(step, ...)``), or
  * referenced (by name) from the body of a reachable function — including
    across modules through package-relative imports (``best_split`` in
    ops/split.py is reachable because the jitted growers call it), or
  * nested inside a reachable function (nested defs execute at trace time).

Traced-value tracking is interprocedural: the positional parameters of a
jit ROOT (minus its ``static_argnames``) are traced; for reachable helper
functions a parameter is traced only if some observed call site passes an
expression referencing a traced value (a helper only ever called with
static config — ``_hist_packing(F, B)`` — stays static). Helpers that are
reachable but never directly called (e.g. Pallas kernel bodies invoked
through ``pallas_call``) conservatively default to traced positional
params. Keyword-only parameters are treated as static configuration (this
codebase consistently passes static config after ``*``), locals assigned
from traced expressions become traced, and ``x.shape``/``x.dtype``-style
accesses do NOT taint (static at trace time), nor do ``is``/``is not``
identity tests. Deliberate exceptions carry an entry in the checked-in
allowlist (analysis/tpulint.allow) with a one-line justification.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: names that (re)enter jit when called
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}

#: attribute accesses on a traced value that are static at trace time
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type", "sharding",
                "aval", "at"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str          # "R001".."R005"
    path: str          # posix path as given to the driver
    line: int
    func: str          # enclosing function qualname, or "<module>"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.func}] " \
               f"{self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def string_constants(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def static_argnames_of(call: ast.Call) -> Set[str]:
    """String constants inside a ``static_argnames=...`` keyword."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out.update(string_constants(kw.value))
    return out


def _names_in(node: ast.AST) -> Iterator[str]:
    """Names referenced by an expression, skipping subtrees under static
    attribute accesses (``x.shape[0]`` does not reference ``x`` as a
    traced VALUE) and skipping ``is``/``is not`` identity tests."""
    if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
        return
    if isinstance(node, ast.Compare) and \
            all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return
    if isinstance(node, ast.Name):
        yield node.id
        return
    for child in ast.iter_child_nodes(node):
        yield from _names_in(child)


def expr_references(node: ast.AST, names: Set[str]) -> bool:
    return any(n in names for n in _names_in(node))


def _is_jit_decorator(dec: ast.AST) -> Tuple[bool, Set[str]]:
    """(is_jit, static_argnames) for one decorator node."""
    name = dotted_name(dec)
    if name in JIT_NAMES:
        return True, set()
    if isinstance(dec, ast.Call):
        cname = call_name(dec)
        if cname in JIT_NAMES:
            return True, static_argnames_of(dec)
        if cname in PARTIAL_NAMES and dec.args:
            if dotted_name(dec.args[0]) in JIT_NAMES:
                return True, static_argnames_of(dec)
    return False, set()


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    qualname: str
    module: "ModuleInfo"
    parent: Optional["FunctionInfo"]
    pos_params: Tuple[str, ...]        # posonly + args + vararg
    kwonly_params: Tuple[str, ...]
    jit_decorated: bool = False
    static_argnames: Set[str] = dataclasses.field(default_factory=set)
    # names referenced in the body: plain basenames and (alias, attr) pairs
    refs: Set[str] = dataclasses.field(default_factory=set)
    attr_refs: Set[Tuple[str, str]] = dataclasses.field(default_factory=set)
    _own: Optional[List[ast.AST]] = None

    @property
    def basename(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    def own_nodes(self) -> List[ast.AST]:
        """This function's body nodes, NOT descending into nested defs."""
        if self._own is None:
            out: List[ast.AST] = []
            stack: List[ast.AST] = list(ast.iter_child_nodes(self.node))
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                out.append(n)
                stack.extend(ast.iter_child_nodes(n))
            self._own = out
        return self._own


class ModuleInfo:
    """Parsed module + its function table, imports, and jit roots."""

    def __init__(self, path: str, source: str,
                 dotted: Optional[str] = None):
        self.path = path
        self.dotted = dotted            # e.g. "lightgbm_tpu.ops.split"
        self.tree = ast.parse(source, filename=path)
        self.source_lines = source.splitlines()
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_basename: Dict[str, List[FunctionInfo]] = {}
        # local alias -> (absolute module dotted name, symbol or None)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self._func_of: Optional[Dict[int, str]] = None
        self._collect_imports()
        self._collect_functions(self.tree, parent=None, prefix="")
        self._collect_jit_callsites()

    def func_of(self, node: ast.AST) -> str:
        """Qualname of the function whose body contains ``node`` (for
        Finding attribution), or ``"<module>"`` — shared by the rules so
        each does not rebuild the id->qualname map itself."""
        if self._func_of is None:
            table: Dict[int, str] = {}
            for fn in self.functions.values():
                for n in fn.own_nodes():
                    table[id(n)] = fn.qualname
            self._func_of = table
        return self._func_of.get(id(node), "<module>")

    # -- construction --------------------------------------------------
    def _resolve_relative(self, module: Optional[str], level: int) -> str:
        if level == 0:
            return module or ""
        base = (self.dotted or "").split(".")
        # drop the module's own name, then `level - 1` more packages
        base = base[: max(0, len(base) - level)]
        return ".".join(base + ([module] if module else []))

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        (a.name, None)
            elif isinstance(node, ast.ImportFrom):
                mod = self._resolve_relative(node.module, node.level)
                for a in node.names:
                    self.imports[a.asname or a.name] = (mod, a.name)

    def _collect_functions(self, node: ast.AST,
                           parent: Optional[FunctionInfo],
                           prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                a = child.args
                pos = tuple(p.arg for p in a.posonlyargs + a.args)
                if a.vararg:
                    pos += (a.vararg.arg,)
                kwonly = tuple(p.arg for p in a.kwonlyargs)
                jit, statics = False, set()
                for dec in child.decorator_list:
                    is_jit, s = _is_jit_decorator(dec)
                    if is_jit:
                        jit, statics = True, statics | s
                fn = FunctionInfo(child, qual, self, parent, pos, kwonly,
                                  jit, statics)
                self._collect_refs(fn)
                self.functions[qual] = fn
                self.by_basename.setdefault(child.name, []).append(fn)
                self._collect_functions(child, fn, prefix=f"{qual}.")
            else:
                self._collect_functions(child, parent, prefix)

    def _collect_refs(self, fn: FunctionInfo) -> None:
        for n in fn.own_nodes():
            if isinstance(n, ast.Name):
                fn.refs.add(n.id)
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name):
                fn.attr_refs.add((n.value.id, n.attr))

    def _collect_jit_callsites(self) -> None:
        """``jax.jit(step, static_argnames=...)`` marks ``step`` a root."""
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in JIT_NAMES and node.args):
                continue
            statics = static_argnames_of(node)
            for ref in ast.walk(node.args[0]):
                if isinstance(ref, ast.Name):
                    for fn in self.by_basename.get(ref.id, ()):
                        fn.jit_decorated = True
                        fn.static_argnames |= statics


class PackageInfo:
    """All linted modules + the cross-module jit-reachability closure and
    the interprocedural traced-parameter fixpoint."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.by_dotted = {m.dotted: m for m in modules if m.dotted}
        self.reachable: Set[int] = set()          # id(FunctionInfo)
        self.param_traced: Dict[int, Set[str]] = {}
        self._compute_reachability()
        self._compute_param_tracedness()

    def is_reachable(self, fn: FunctionInfo) -> bool:
        return id(fn) in self.reachable

    def reachable_functions(self, module: ModuleInfo) -> List[FunctionInfo]:
        return [f for f in module.functions.values() if self.is_reachable(f)]

    # -- name resolution ----------------------------------------------
    def _resolve(self, module: ModuleInfo, name: str
                 ) -> List[FunctionInfo]:
        """Functions an imported name may refer to, package-internal only."""
        if name not in module.imports:
            return []
        mod_name, symbol = module.imports[name]
        target = self.by_dotted.get(mod_name)
        if target is None or symbol is None:
            return []
        return [f for f in target.by_basename.get(symbol, ())
                if f.parent is None]

    def _resolve_attr(self, module: ModuleInfo, alias: str, attr: str
                      ) -> List[FunctionInfo]:
        if alias not in module.imports:
            return []
        mod_name, symbol = module.imports[alias]
        if symbol is not None:       # `from x import y; y.attr` — not a call
            return []
        target = self.by_dotted.get(mod_name)
        if target is None:
            return []
        return [f for f in target.by_basename.get(attr, ())
                if f.parent is None]

    def _callees(self, module: ModuleInfo, name: str
                 ) -> List[FunctionInfo]:
        return list(module.by_basename.get(name, ())) \
            + self._resolve(module, name)

    # -- reachability --------------------------------------------------
    def _compute_reachability(self) -> None:
        work: List[FunctionInfo] = []
        for m in self.modules:
            for f in m.functions.values():
                if f.jit_decorated:
                    work.append(f)
        while work:
            fn = work.pop()
            if id(fn) in self.reachable:
                continue
            self.reachable.add(id(fn))
            # nested defs run (and usually trace) with the parent
            for g in fn.module.functions.values():
                if g.parent is fn:
                    work.append(g)
            # same-module references by basename + package-internal imports
            for name in fn.refs:
                work.extend(self._callees(fn.module, name))
            for alias, attr in fn.attr_refs:
                work.extend(self._resolve_attr(fn.module, alias, attr))

    # -- interprocedural traced params ---------------------------------
    def _call_edges(self, fn: FunctionInfo
                    ) -> List[Tuple[FunctionInfo, List[ast.AST],
                                    List[Tuple[str, ast.AST]]]]:
        """(callee, positional arg exprs, keyword arg exprs) per call."""
        edges = []
        # local `name = (a, b, c)` tuple literals, to expand `*name` args
        tuples: Dict[str, ast.Tuple] = {}
        for n in fn.own_nodes():
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Tuple):
                tuples[n.targets[0].id] = n.value
        for node in fn.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            args, keywords = [], node.keywords
            for a in node.args:
                if isinstance(a, ast.Starred):
                    if isinstance(a.value, ast.Name) and \
                            a.value.id in tuples:
                        args.extend(tuples[a.value.id].elts)
                        continue
                    # unknown star-expansion: positional alignment is lost
                    # past this point; stop mapping (under-taints, which the
                    # no-callsite conservative default partially offsets)
                    break
                args.append(a)
            if cname in PARTIAL_NAMES and args:
                target = dotted_name(args[0])
                if target is None:
                    continue
                cname, args = target, args[1:]
            if cname is None:
                continue
            base = cname.rsplit(".", 1)[-1]
            callees = self._callees(fn.module, base) if "." not in cname \
                else []
            if "." in cname:
                head, _, attr = cname.partition(".")
                if "." not in attr:
                    callees = self._resolve_attr(fn.module, head, attr)
            for callee in callees:
                edges.append((callee, args,
                              [(k.arg, k.value) for k in keywords
                               if k.arg is not None]))
        return edges

    def _compute_param_tracedness(self) -> None:
        reachable_fns = [f for m in self.modules
                         for f in m.functions.values()
                         if self.is_reachable(f)]
        has_callsite: Set[int] = set()
        for fn in reachable_fns:
            if fn.jit_decorated:
                self.param_traced[id(fn)] = \
                    set(fn.pos_params) - fn.static_argnames
            else:
                self.param_traced[id(fn)] = set()

        def run_fixpoint() -> None:
            for _ in range(12):
                changed = False
                for fn in reachable_fns:
                    traced = traced_names(fn, self)
                    for callee, args, kwargs in self._call_edges(fn):
                        if not self.is_reachable(callee):
                            continue
                        has_callsite.add(id(callee))
                        if callee.jit_decorated:
                            continue        # roots are pinned
                        tgt = self.param_traced[id(callee)]
                        for i, a in enumerate(args):
                            if i < len(callee.pos_params) and \
                                    expr_references(a, traced):
                                if callee.pos_params[i] not in tgt:
                                    tgt.add(callee.pos_params[i])
                                    changed = True
                        for kname, kval in kwargs:
                            if kname in callee.pos_params and \
                                    expr_references(kval, traced):
                                if kname not in tgt:
                                    tgt.add(kname)
                                    changed = True
                if not changed:
                    break

        run_fixpoint()
        # reachable but never directly called (kernel bodies invoked via
        # pallas_call, functions passed around by reference): conservative
        # default — positional params are traced
        grew = False
        for fn in reachable_fns:
            if not fn.jit_decorated and id(fn) not in has_callsite:
                default = set(fn.pos_params) - fn.static_argnames
                if default - self.param_traced[id(fn)]:
                    self.param_traced[id(fn)] |= default
                    grew = True
        if grew:
            run_fixpoint()


def traced_names(fn: FunctionInfo, package: PackageInfo) -> Set[str]:
    """Names likely bound to traced values inside ``fn``: its traced
    params, traced params of reachable enclosing functions (closure), and
    locals assigned from expressions referencing a traced name."""
    names: Set[str] = set(package.param_traced.get(
        id(fn), set(fn.pos_params) - fn.static_argnames))
    p = fn.parent
    while p is not None:
        if package.is_reachable(p):
            names |= package.param_traced.get(id(p), set())
        p = p.parent
    for _ in range(8):              # bounded fixpoint over local assigns
        grew = False
        for n in fn.own_nodes():
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(n, ast.Assign):
                targets, value = n.targets, n.value
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                targets, value = [n.target], n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets, value = [n.target], n.value
            if value is None or not expr_references(value, names):
                continue
            for t in targets:
                for leaf in _plain_name_targets(t):
                    if leaf not in names:
                        names.add(leaf)
                        grew = True
        if not grew:
            break
    return names


def _plain_name_targets(target: ast.AST) -> Iterator[str]:
    """Plain-name assignment targets only: ``a = ...``, ``a, b = ...``.
    Subscript/attribute stores (``x[i] = ...``) neither taint the base
    nor the index names."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _plain_name_targets(el)
    elif isinstance(target, ast.Starred):
        yield from _plain_name_targets(target.value)


class Rule:
    """Base class; subclasses set ``code``/``title`` and implement check."""
    code = "R000"
    title = ""

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, func: str,
                message: str) -> Finding:
        return Finding(self.code, module.path,
                       getattr(node, "lineno", 0), func, message)
