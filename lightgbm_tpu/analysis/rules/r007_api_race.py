"""R007 — unsynchronized mutation of shared ``Booster``/``Dataset`` state.

The reference shared-locks every C API call (yamc shared mutex,
src/c_api.cpp:163): concurrent predicts are readers, training/mutation is
exclusive. This repo's equivalent is the ``_api_lock`` reader-writer lock
(utils/rwlock.py) with ``@read_locked``/``@write_locked`` on public
methods — and THIS rule is what keeps that discipline from rotting: a new
public method added without a decorator, or a cache fill slipped into a
read-locked method (the ``_device_trees_cache`` race this PR fixes), is a
finding, not a code-review hope.

Scope: classes named ``Booster``/``Dataset`` and any class whose
``__init__`` installs a ``self._api_lock``. Checks, per public method
(no leading underscore, not a dunder, not a property/classmethod/
staticmethod):

  * it carries a ``read_locked`` or ``write_locked`` decorator;
  * if its body assigns/deletes ``self.<attr>`` (including subscript
    stores like ``self.x[i] = v``), the decorator is ``write_locked``.

The runtime half — detecting concurrent unsynchronized access when the
lock is bypassed — is ``analysis/guards.api_race_sanitizer``.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .base import Finding, ModuleInfo, PackageInfo, Rule, dotted_name

_SHARED_CLASS_NAMES = {"Booster", "Dataset"}
_LOCK_DECORATORS = {"read_locked", "write_locked"}
_SKIP_DECORATORS = {"property", "staticmethod", "classmethod",
                    "cached_property"}


def _decorator_basenames(fn: ast.AST) -> List[str]:
    out = []
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(node)
        if name:
            out.append(name.rsplit(".", 1)[-1])
    return out


def _declares_api_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "_api_lock" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    return True
    return False


def _self_mutations(fn: ast.FunctionDef) -> List[str]:
    """Attributes of ``self`` this method assigns/deletes directly (not
    descending into nested defs)."""
    out: List[str] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.Delete):
            targets = n.targets
        for t in targets:
            attr = _self_attr_of(t)
            if attr is not None:
                out.append(attr)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _self_attr_of(target: ast.AST) -> Optional[str]:
    # unwrap tuple targets and subscript stores: self.x[i] = v mutates x
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            a = _self_attr_of(el)
            if a is not None:
                return a
        return None
    while isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and target.value.id == "self":
        return target.attr
    return None


class ApiRaceRule(Rule):
    code = "R007"
    title = "unsynchronized mutation of shared Booster/Dataset state"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            declares = _declares_api_lock(cls)
            if cls.name in _SHARED_CLASS_NAMES and not declares:
                out.append(self.finding(
                    module, cls, cls.name,
                    f"shared API class {cls.name} declares no _api_lock — "
                    "concurrent predict/update race on its state "
                    "(utils/rwlock.RWLock; reference: the C API's shared "
                    "mutex, src/c_api.cpp:163)"))
                continue
            if not (declares or cls.name in _SHARED_CLASS_NAMES):
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if fn.name.startswith("_"):
                    continue
                decos = _decorator_basenames(fn)
                if any(d in _SKIP_DECORATORS for d in decos):
                    continue
                lock = next((d for d in decos if d in _LOCK_DECORATORS),
                            None)
                qual = f"{cls.name}.{fn.name}"
                muts = _self_mutations(fn)
                if lock is None:
                    out.append(self.finding(
                        module, fn, qual,
                        f"public API method '{fn.name}' is not "
                        "read_locked/write_locked — every entry point of a "
                        "shared class holds the rwlock"
                        + (f" (it mutates self.{muts[0]})" if muts else "")))
                elif lock == "read_locked" and muts:
                    out.append(self.finding(
                        module, fn, qual,
                        f"'{fn.name}' mutates self.{muts[0]} under the "
                        "READ lock — concurrent readers interleave the "
                        "write (the _device_trees_cache race); take "
                        "@write_locked"))
        return out
