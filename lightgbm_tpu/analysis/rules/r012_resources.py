"""R012 — resource lifecycle: every acquisition releases on all paths,
every retained-program cache carries a statically visible bound.

Thin adapter over :mod:`..resources` (the interprocedural analyzer):
the whole-package analysis runs once (cached on the package) and each
module's check() returns the findings anchored in that module, exactly
as r011_locks adapts :mod:`..locks`.
"""
from __future__ import annotations

from typing import List

from ..resources import analyze_package
from .base import Finding, ModuleInfo, PackageInfo, Rule


class ResourceLifecycleRule(Rule):
    code = "R012"
    title = ("resource acquired without a guaranteed release / "
             "unbounded retained-program cache")

    def check(self, module: ModuleInfo,
              package: PackageInfo) -> List[Finding]:
        analysis = analyze_package(package)
        return [f for f in analysis.findings if f.path == module.path]
