"""R003 — dtype drift in device code.

Four sub-checks, all scoped to jit-reachable functions:

  * ``np.*`` math/array ops applied to traced values: numpy either raises
    on tracers or silently materializes a trace-time constant, and the
    result re-enters the trace as host data (an implicit f64 promotion on
    many numpy paths). Device code must stay on ``jnp``/``lax``.
    (``np.asarray``/``np.array`` are R001's host-sync territory; this rule
    covers the computational ops.)
  * explicit float64 requests (``jnp.float64``, ``np.float64``,
    ``dtype="float64"``, ``.astype('float64')``): with x64 disabled (the
    default, and the only supported mode on TPU here) jax silently lowers
    these to f32 — the annotation lies; with x64 enabled they double
    memory/VPU cost. Either way it is drift, not intent.
  * int-packing accumulation contract (quantized-gradient histograms): a
    matmul-family call (``einsum``/``dot``/``matmul``/``dot_general``)
    with an int8/int16-cast operand MUST carry
    ``preferred_element_type=...`` — without it the contraction output
    dtype follows the narrow operands and the int32 histogram sums
    silently wrap at +-127 (ops/histogram.py int8 MXU path).
  * dequantize contract: an ``.astype(jnp.float32)`` on a quantized
    histogram (names matching ``qhist``/``quant_hist``/``hist_q``) must
    sit inside a multiply by a ``*scale*`` name — a bare cast yields raw
    code sums, silently off by the per-iteration leaf scale
    (ops/histogram.py dequantize_hist is the sanctioned boundary).
"""
from __future__ import annotations

import ast
import re
from typing import List

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name, expr_references, string_constants,
                   traced_names)

_NP_EXEMPT = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_F64_NAMES = {"np.float64", "numpy.float64", "jnp.float64",
              "jax.numpy.float64"}

_MATMUL_SUFFIXES = ("einsum", "dot", "matmul", "dot_general")
_INT_NARROW = {"int8", "int16"}
_F32_NAMES = {"float32"}
_QHIST_RE = re.compile(r"(q|quant)_?hist|hist_?(q|quant)", re.I)


def _is_int_narrow_cast(node: ast.Call) -> bool:
    """``X.astype(jnp.int8)`` / ``X.astype('int16')`` style calls."""
    name = call_name(node) or ""
    if not name.endswith(".astype") and name != "astype":
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"):
            return False
    for a in node.args:
        if any(s in _INT_NARROW for s in string_constants(a)):
            return True
        for sub in ast.walk(a):
            d = dotted_name(sub)
            if d and d.split(".")[-1] in _INT_NARROW:
                return True
    return False


def _has_int_narrow_cast(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Call) and _is_int_narrow_cast(sub)
               for sub in ast.walk(node))


def _is_f32_astype(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"):
        return False
    for a in node.args:
        if any(s in _F32_NAMES for s in string_constants(a)):
            return True
        for sub in ast.walk(a):
            d = dotted_name(sub)
            if d and d.split(".")[-1] in _F32_NAMES:
                return True
    return False


def _mentions(node: ast.AST, pattern) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and pattern(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and pattern(sub.attr):
            return True
    return False


class DtypeDriftRule(Rule):
    code = "R003"
    title = "dtype drift in device code"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.reachable_functions(module):
            traced = traced_names(fn, package)
            # names locally assigned from int8/int16-cast expressions (the
            # int-packing contract tracks them into matmul operands)
            int_names = set()
            for node in fn.own_nodes():
                if isinstance(node, ast.Assign) \
                        and _has_int_narrow_cast(node.value):
                    for t in node.targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                int_names.add(sub.id)
            # astype(f32) nodes blessed by a sibling *scale* multiply
            scale_ok = set()
            for node in fn.own_nodes():
                if isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Mult):
                    for side, other in ((node.left, node.right),
                                        (node.right, node.left)):
                        if _mentions(other, lambda s: "scale" in s.lower()):
                            scale_ok.update(
                                id(sub) for sub in ast.walk(side)
                                if isinstance(sub, ast.Call))
            for node in fn.own_nodes():
                if isinstance(node, ast.Call):
                    name = call_name(node) or ""
                    if (name.startswith(("np.", "numpy."))
                            and name not in _NP_EXEMPT
                            and any(expr_references(a, traced)
                                    for a in node.args)):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"{name}() on a traced value in device code "
                            "— numpy ops escape the trace (use jnp)"))
                    if name.endswith(".astype") and any(
                            "float64" in c for a in node.args
                            for c in _str_consts(a)):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            "astype('float64') in device code — f64 "
                            "silently lowers to f32 with x64 disabled"))
                    for kw in node.keywords:
                        if kw.arg == "dtype" and (
                                "float64" in _str_consts(kw.value)):
                            out.append(self.finding(
                                module, kw.value, fn.qualname,
                                "dtype='float64' in device code — f64 "
                                "silently lowers to f32 with x64 "
                                "disabled"))
                    # int-packing contract: int8/int16 matmul operands need
                    # preferred_element_type (else the contraction output
                    # narrows to the operand dtype and histogram sums wrap)
                    if name.split(".")[-1] in _MATMUL_SUFFIXES:
                        int_op = any(
                            _has_int_narrow_cast(a)
                            or expr_references(a, int_names)
                            for a in node.args)
                        has_pref = any(kw.arg == "preferred_element_type"
                                       for kw in node.keywords)
                        if int_op and not has_pref:
                            out.append(self.finding(
                                module, node, fn.qualname,
                                f"{name}() with int8/int16 operands and no "
                                "preferred_element_type — the accumulator "
                                "follows the narrow operand dtype and "
                                "histogram sums overflow; pin it to int32 "
                                "(ops/histogram.py int-packing contract)"))
                    # dequantize contract: quantized-histogram casts to f32
                    # must multiply by the leaf scale
                    if (_is_f32_astype(node) and id(node) not in scale_ok
                            and _mentions(node.func.value,
                                          _QHIST_RE.search)):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            "quantized histogram cast to f32 without the "
                            "leaf-scale multiply — raw code sums are off "
                            "by the per-iteration scale; dequantize via "
                            "ops.histogram.dequantize_hist"))
                elif isinstance(node, ast.Attribute):
                    if dotted_name(node) in _F64_NAMES:
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"{dotted_name(node)} in device code — f64 "
                            "silently lowers to f32 with x64 disabled "
                            "(or doubles memory/VPU cost with it on)"))
        return out


def _str_consts(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]
