"""R003 — dtype drift in device code.

Two sub-checks, both scoped to jit-reachable functions:

  * ``np.*`` math/array ops applied to traced values: numpy either raises
    on tracers or silently materializes a trace-time constant, and the
    result re-enters the trace as host data (an implicit f64 promotion on
    many numpy paths). Device code must stay on ``jnp``/``lax``.
    (``np.asarray``/``np.array`` are R001's host-sync territory; this rule
    covers the computational ops.)
  * explicit float64 requests (``jnp.float64``, ``np.float64``,
    ``dtype="float64"``, ``.astype('float64')``): with x64 disabled (the
    default, and the only supported mode on TPU here) jax silently lowers
    these to f32 — the annotation lies; with x64 enabled they double
    memory/VPU cost. Either way it is drift, not intent.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name, expr_references, traced_names)

_NP_EXEMPT = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_F64_NAMES = {"np.float64", "numpy.float64", "jnp.float64",
              "jax.numpy.float64"}


class DtypeDriftRule(Rule):
    code = "R003"
    title = "dtype drift in device code"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.reachable_functions(module):
            traced = traced_names(fn, package)
            for node in fn.own_nodes():
                if isinstance(node, ast.Call):
                    name = call_name(node) or ""
                    if (name.startswith(("np.", "numpy."))
                            and name not in _NP_EXEMPT
                            and any(expr_references(a, traced)
                                    for a in node.args)):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"{name}() on a traced value in device code "
                            "— numpy ops escape the trace (use jnp)"))
                    if name.endswith(".astype") and any(
                            "float64" in c for a in node.args
                            for c in _str_consts(a)):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            "astype('float64') in device code — f64 "
                            "silently lowers to f32 with x64 disabled"))
                    for kw in node.keywords:
                        if kw.arg == "dtype" and (
                                "float64" in _str_consts(kw.value)):
                            out.append(self.finding(
                                module, kw.value, fn.qualname,
                                "dtype='float64' in device code — f64 "
                                "silently lowers to f32 with x64 "
                                "disabled"))
                elif isinstance(node, ast.Attribute):
                    if dotted_name(node) in _F64_NAMES:
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"{dotted_name(node)} in device code — f64 "
                            "silently lowers to f32 with x64 disabled "
                            "(or doubles memory/VPU cost with it on)"))
        return out


def _str_consts(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]
