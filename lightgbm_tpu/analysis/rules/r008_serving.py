"""R008 — serving entry points must bound their queues and their waits.

The resilient-serving contract (serving/): a request path either answers
within its deadline or fails with a structured error — it never parks a
caller on an unbounded queue or an untimed wait. Two hazards rot that
contract silently:

  * an UNBOUNDED queue on a request path (``queue.Queue()`` with no
    maxsize, ``collections.deque()`` with no maxlen, or ``SimpleQueue``
    which cannot be bounded): under a slow tick the queue absorbs every
    incoming request and converts overload into unbounded latency for
    ALL of them — admission control (``tpu_serve_queue_max`` +
    ``ServerOverloaded``) is the load-shedding alternative;
  * a BLOCKING wait with no timeout on the request path (``.get()`` /
    ``.result()`` / ``.wait()`` / ``.join()`` with neither a positional
    timeout nor ``timeout=``, and the producer-side twin ``.put(item)``
    without ``block=False``/``timeout=``): one wedged device dispatch
    then wedges the caller — or a full bounded queue wedges every
    submitter — forever, instead of raising ``ServingTimeout``
    (``tpu_serve_deadline_ms``) or shedding (``ServerOverloaded``);
  * sub-check (c): HOST FEATURIZATION on the serving hot path — a
    ``bin_columns`` / ``value_to_bin`` / ``np.searchsorted`` call in any
    function reachable from a coalescer-tick/serve entry point re-opens
    the per-tick O(rows * features) host sweep the device featurizer
    (ops/device_bin.py, ``tpu_serve_featurize=device``) exists to
    close. The ONE deliberate host binner — the
    ``tpu_serve_featurize=host`` parity/escape hatch behind
    ``GBDT.bin_matrix`` — carries an allowlist anchor.

Scope: code is "serving-scoped" when its module lives under a
``serving`` package/path, its enclosing class matches ``Serv``/
``Coalesc`` (``PredictionServer``, ``MicroBatchCoalescer``, ...), or its
enclosing function is a serving entry (``serve*``/``submit*``/
``enqueue*``). The ONE deliberate blocking wait — the graceful-drain
join in ``coalescer.close`` — carries an allowlist anchor.

``x.get(key)`` (dict-style) and ``wait(deadline)`` (positional timeout)
are not findings; the blocking spellings are — including the evasive
ones: ``get(True)``, ``get(True, None)``, ``result(None)``,
``timeout=None``.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from .base import Finding, ModuleInfo, PackageInfo, Rule, call_name

#: class names that put their methods in serving scope
_CLASS_RE = re.compile(r"Serv|Coalesc")
#: function basenames that are serving entry points on their own
_FUNC_RE = re.compile(r"^(serve|submit|enqueue)", re.I)
#: module path components that put the whole module in serving scope
_MODULE_COMPONENT = "serving"

#: queue constructors and how they are bounded:
#: name -> (bounding parameter, positional index of that parameter)
_QUEUE_CTORS = {
    "Queue": ("maxsize", 0),
    "LifoQueue": ("maxsize", 0),
    "PriorityQueue": ("maxsize", 0),
    "deque": ("maxlen", 1),
}
#: inherently unbounded request containers
_UNBOUNDABLE = {"SimpleQueue"}

#: attribute calls that block forever without a timeout
_BLOCKING_ATTRS = {"get", "result", "wait", "join"}

#: host featurization primitives (sub-check (c)): the per-tick raw->bin
#: host work the device featurizer replaces on serving paths
_FEATURIZE_CALLS = {"bin_columns", "value_to_bin", "searchsorted"}
#: function basenames that are serve/coalescer-tick entry points for the
#: featurize reachability walk (the whole serving/ package seeds too)
_SERVE_ENTRY_RE = re.compile(r"(^|_)serv", re.I)
#: boundaries the featurize walk does NOT cross: training / dataset
#: construction is boot-time work (scripts/serve trains-or-resumes before
#: taking traffic), not per-tick request work — the construct-time binner
#: behind them is legitimate
_PHASE_STOP_RE = re.compile(r"^_?(train|construct|fit)", re.I)


def _timeout_kw(node: ast.Call) -> Optional[ast.AST]:
    return next((kw.value for kw in node.keywords
                 if kw.arg == "timeout"), None)


def _is_none_const(value: Optional[ast.AST]) -> bool:
    return isinstance(value, ast.Constant) and value.value is None


def _put_blocks(node: ast.Call) -> bool:
    """``q.put(item)`` on a FULL bounded queue blocks the submitter
    forever — the producer-side twin of the untimed ``get``. Non-blocking
    forms are fine: ``put_nowait``, ``put(item, False)``,
    ``put(item, block=False)``, or a non-None ``timeout=``."""
    timeout = _timeout_kw(node)
    if timeout is not None:
        return _is_none_const(timeout)      # timeout=None still blocks
    for kw in node.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
            and node.args[1].value is False:
        return False
    return True


def _wait_blocks(node: ast.Call) -> bool:
    """Does this get/result/wait/join call block without a bound?

    ``get``'s first positional is BLOCK (queue API), not a timeout — and
    dict-style ``d.get(key)`` lands in the same slot — so for ``get``
    only the unmistakably blocking forms are findings: no arguments,
    ``get(True)``, ``get(True, None)``, or ``timeout=None``. For
    ``result``/``wait``/``join`` the first positional IS the timeout:
    blocking means no arguments or an explicit None."""
    timeout = _timeout_kw(node)
    if timeout is not None:
        return _is_none_const(timeout)
    if node.func.attr == "get":
        if not node.args:
            return True
        first = node.args[0]
        if isinstance(first, ast.Constant) and first.value is True:
            return len(node.args) < 2 or _is_none_const(node.args[1])
        return False
    if not node.args:
        return True
    return _is_none_const(node.args[0])


def _module_in_scope(module: ModuleInfo) -> bool:
    parts = module.path.replace("\\", "/").split("/")
    names = {p[:-3] if p.endswith(".py") else p for p in parts}
    if _MODULE_COMPONENT in names:
        return True
    dotted = module.dotted or ""
    return f".{_MODULE_COMPONENT}." in f".{dotted}."


def _bound_arg(node: ast.Call, param: str, pos: int) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == param:
            return kw.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _is_unbounded_value(value: Optional[ast.AST]) -> bool:
    """No bound given, or an explicit unbounded sentinel (None, <= 0)."""
    if value is None:
        return True
    if isinstance(value, ast.Constant):
        v = value.value
        if v is None:
            return True
        if isinstance(v, (int, float)) and v <= 0:
            return True
    return False


class ServingContractRule(Rule):
    code = "R008"
    title = "unbounded queue / untimed wait on a serving request path"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        module_scope = _module_in_scope(module)

        def walk(node: ast.AST, qual: str, in_scope: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_qual, child_scope = qual, in_scope
                if isinstance(child, ast.ClassDef):
                    child_qual = (f"{qual}.{child.name}"
                                  if qual != "<module>" else child.name)
                    child_scope = in_scope or bool(
                        _CLASS_RE.search(child.name))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    child_qual = (f"{qual}.{child.name}"
                                  if qual != "<module>" else child.name)
                    child_scope = in_scope or bool(
                        _FUNC_RE.search(child.name))
                elif isinstance(child, ast.Call) and in_scope:
                    self._check_call(module, child, qual, out)
                walk(child, child_qual, child_scope)

        walk(module.tree, "<module>", module_scope)
        out.extend(self._host_featurize_findings(module, package))
        return out

    # -- (c) host featurization reachable from serve entries ----------------
    def _serve_closure(self, package: PackageInfo) -> set:
        """Functions reachable from serving entry points, package-wide.

        Seeds: every function in a ``serving`` module plus any function
        whose basename says serve/serving (``predict_serving``,
        ``_serve_batch``, the endpoint twins). The walk follows the
        jit-reachability name-resolution edges PLUS a package-wide
        basename resolution for method-style attribute calls
        (``inner.bin_matrix(...)`` — serving hands work to Booster/GBDT
        methods through object handles the import-based resolver cannot
        see), and stops AT the featurize primitives — findings anchor at
        their callers, not inside io/binning itself (which legitimately
        owns the construct-time binner)."""
        cached = getattr(package, "_r008_serve_closure", None)
        if cached is not None:
            return cached
        by_basename: dict = {}
        for m in package.modules:
            for f in m.functions.values():
                by_basename.setdefault(f.basename, []).append(f)
        work, seen = [], set()
        for m in package.modules:
            mscope = _module_in_scope(m)
            in_serving_class = set()
            for cls in ast.walk(m.tree):
                if isinstance(cls, ast.ClassDef) and _CLASS_RE.search(
                        cls.name):
                    for sub in ast.walk(cls):
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            in_serving_class.add(id(sub))
            for f in m.functions.values():
                if mscope or _SERVE_ENTRY_RE.search(f.basename) \
                        or id(f.node) in in_serving_class:
                    work.append(f)
        def admit(fns):
            # the walk stops at train/construct entries: boot-time phases
            # own the construct-time binner legitimately
            work.extend(f for f in fns
                        if not _PHASE_STOP_RE.match(f.basename))

        while work:
            fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            for g in fn.module.functions.values():
                if g.parent is fn:
                    work.append(g)
            for name in fn.refs:
                if name.rsplit(".", 1)[-1] in _FEATURIZE_CALLS:
                    continue
                admit(package._callees(fn.module, name))
            for alias, attr in fn.attr_refs:
                if attr in _FEATURIZE_CALLS:
                    continue
                admit(package._resolve_attr(fn.module, alias, attr))
                admit(by_basename.get(attr, ()))
        package._r008_serve_closure = seen
        return seen

    def _host_featurize_findings(self, module: ModuleInfo,
                                 package: PackageInfo) -> List[Finding]:
        out: List[Finding] = []
        closure = self._serve_closure(package)
        for fn in module.functions.values():
            if id(fn) not in closure:
                continue
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                base = (call_name(node) or "").rsplit(".", 1)[-1]
                if base in _FEATURIZE_CALLS:
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"host featurization ({base}) reachable from a "
                        "serve/coalescer-tick entry — every tick pays an "
                        "O(rows*features) host sweep; route through the "
                        "device featurizer (ops/device_bin.py, "
                        "tpu_serve_featurize=device) or anchor the "
                        "deliberate host escape hatch"))
        return out

    def _check_call(self, module: ModuleInfo, node: ast.Call, qual: str,
                    out: List[Finding]) -> None:
        name = call_name(node) or ""
        base = name.rsplit(".", 1)[-1]
        if base in _UNBOUNDABLE:
            out.append(self.finding(
                module, node, qual,
                f"{base} is an unbounded request queue — a slow tick "
                "turns overload into unbounded latency for every queued "
                "request; use a bounded queue with admission control "
                "(tpu_serve_queue_max -> ServerOverloaded)"))
            return
        if base in _QUEUE_CTORS:
            param, pos = _QUEUE_CTORS[base]
            if _is_unbounded_value(_bound_arg(node, param, pos)):
                out.append(self.finding(
                    module, node, qual,
                    f"{base} constructed without a {param} bound on a "
                    "serving path — the request queue must shed load "
                    "(tpu_serve_queue_max -> ServerOverloaded), not "
                    "grow without bound"))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "put" and node.args and \
                _put_blocks(node):
            out.append(self.finding(
                module, node, qual,
                ".put() without block=False/timeout on a serving path "
                "blocks the SUBMITTER forever once the bounded queue "
                "fills — shed at the admission edge instead "
                "(put_nowait -> ServerOverloaded)"))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _BLOCKING_ATTRS:
            if _wait_blocks(node):
                out.append(self.finding(
                    module, node, qual,
                    f".{node.func.attr}() without a timeout on a serving "
                    "path blocks forever when a tick wedges — carry the "
                    "request deadline (tpu_serve_deadline_ms -> "
                    "ServingTimeout); the deliberate graceful-drain join "
                    "needs an allowlist anchor"))
