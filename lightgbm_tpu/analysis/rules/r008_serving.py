"""R008 — serving entry points must bound their queues and their waits.

The resilient-serving contract (serving/): a request path either answers
within its deadline or fails with a structured error — it never parks a
caller on an unbounded queue or an untimed wait. Two hazards rot that
contract silently:

  * an UNBOUNDED queue on a request path (``queue.Queue()`` with no
    maxsize, ``collections.deque()`` with no maxlen, or ``SimpleQueue``
    which cannot be bounded): under a slow tick the queue absorbs every
    incoming request and converts overload into unbounded latency for
    ALL of them — admission control (``tpu_serve_queue_max`` +
    ``ServerOverloaded``) is the load-shedding alternative;
  * a BLOCKING wait with no timeout on the request path (``.get()`` /
    ``.result()`` / ``.wait()`` / ``.join()`` with neither a positional
    timeout nor ``timeout=``, and the producer-side twin ``.put(item)``
    without ``block=False``/``timeout=``): one wedged device dispatch
    then wedges the caller — or a full bounded queue wedges every
    submitter — forever, instead of raising ``ServingTimeout``
    (``tpu_serve_deadline_ms``) or shedding (``ServerOverloaded``).

Scope: code is "serving-scoped" when its module lives under a
``serving`` package/path, its enclosing class matches ``Serv``/
``Coalesc`` (``PredictionServer``, ``MicroBatchCoalescer``, ...), or its
enclosing function is a serving entry (``serve*``/``submit*``/
``enqueue*``). The ONE deliberate blocking wait — the graceful-drain
join in ``coalescer.close`` — carries an allowlist anchor.

``x.get(key)`` (dict-style) and ``wait(deadline)`` (positional timeout)
are not findings; the blocking spellings are — including the evasive
ones: ``get(True)``, ``get(True, None)``, ``result(None)``,
``timeout=None``.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from .base import Finding, ModuleInfo, PackageInfo, Rule, call_name

#: class names that put their methods in serving scope
_CLASS_RE = re.compile(r"Serv|Coalesc")
#: function basenames that are serving entry points on their own
_FUNC_RE = re.compile(r"^(serve|submit|enqueue)", re.I)
#: module path components that put the whole module in serving scope
_MODULE_COMPONENT = "serving"

#: queue constructors and how they are bounded:
#: name -> (bounding parameter, positional index of that parameter)
_QUEUE_CTORS = {
    "Queue": ("maxsize", 0),
    "LifoQueue": ("maxsize", 0),
    "PriorityQueue": ("maxsize", 0),
    "deque": ("maxlen", 1),
}
#: inherently unbounded request containers
_UNBOUNDABLE = {"SimpleQueue"}

#: attribute calls that block forever without a timeout
_BLOCKING_ATTRS = {"get", "result", "wait", "join"}


def _timeout_kw(node: ast.Call) -> Optional[ast.AST]:
    return next((kw.value for kw in node.keywords
                 if kw.arg == "timeout"), None)


def _is_none_const(value: Optional[ast.AST]) -> bool:
    return isinstance(value, ast.Constant) and value.value is None


def _put_blocks(node: ast.Call) -> bool:
    """``q.put(item)`` on a FULL bounded queue blocks the submitter
    forever — the producer-side twin of the untimed ``get``. Non-blocking
    forms are fine: ``put_nowait``, ``put(item, False)``,
    ``put(item, block=False)``, or a non-None ``timeout=``."""
    timeout = _timeout_kw(node)
    if timeout is not None:
        return _is_none_const(timeout)      # timeout=None still blocks
    for kw in node.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
            and node.args[1].value is False:
        return False
    return True


def _wait_blocks(node: ast.Call) -> bool:
    """Does this get/result/wait/join call block without a bound?

    ``get``'s first positional is BLOCK (queue API), not a timeout — and
    dict-style ``d.get(key)`` lands in the same slot — so for ``get``
    only the unmistakably blocking forms are findings: no arguments,
    ``get(True)``, ``get(True, None)``, or ``timeout=None``. For
    ``result``/``wait``/``join`` the first positional IS the timeout:
    blocking means no arguments or an explicit None."""
    timeout = _timeout_kw(node)
    if timeout is not None:
        return _is_none_const(timeout)
    if node.func.attr == "get":
        if not node.args:
            return True
        first = node.args[0]
        if isinstance(first, ast.Constant) and first.value is True:
            return len(node.args) < 2 or _is_none_const(node.args[1])
        return False
    if not node.args:
        return True
    return _is_none_const(node.args[0])


def _module_in_scope(module: ModuleInfo) -> bool:
    parts = module.path.replace("\\", "/").split("/")
    names = {p[:-3] if p.endswith(".py") else p for p in parts}
    if _MODULE_COMPONENT in names:
        return True
    dotted = module.dotted or ""
    return f".{_MODULE_COMPONENT}." in f".{dotted}."


def _bound_arg(node: ast.Call, param: str, pos: int) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == param:
            return kw.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _is_unbounded_value(value: Optional[ast.AST]) -> bool:
    """No bound given, or an explicit unbounded sentinel (None, <= 0)."""
    if value is None:
        return True
    if isinstance(value, ast.Constant):
        v = value.value
        if v is None:
            return True
        if isinstance(v, (int, float)) and v <= 0:
            return True
    return False


class ServingContractRule(Rule):
    code = "R008"
    title = "unbounded queue / untimed wait on a serving request path"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        module_scope = _module_in_scope(module)

        def walk(node: ast.AST, qual: str, in_scope: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_qual, child_scope = qual, in_scope
                if isinstance(child, ast.ClassDef):
                    child_qual = (f"{qual}.{child.name}"
                                  if qual != "<module>" else child.name)
                    child_scope = in_scope or bool(
                        _CLASS_RE.search(child.name))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    child_qual = (f"{qual}.{child.name}"
                                  if qual != "<module>" else child.name)
                    child_scope = in_scope or bool(
                        _FUNC_RE.search(child.name))
                elif isinstance(child, ast.Call) and in_scope:
                    self._check_call(module, child, qual, out)
                walk(child, child_qual, child_scope)

        walk(module.tree, "<module>", module_scope)
        return out

    def _check_call(self, module: ModuleInfo, node: ast.Call, qual: str,
                    out: List[Finding]) -> None:
        name = call_name(node) or ""
        base = name.rsplit(".", 1)[-1]
        if base in _UNBOUNDABLE:
            out.append(self.finding(
                module, node, qual,
                f"{base} is an unbounded request queue — a slow tick "
                "turns overload into unbounded latency for every queued "
                "request; use a bounded queue with admission control "
                "(tpu_serve_queue_max -> ServerOverloaded)"))
            return
        if base in _QUEUE_CTORS:
            param, pos = _QUEUE_CTORS[base]
            if _is_unbounded_value(_bound_arg(node, param, pos)):
                out.append(self.finding(
                    module, node, qual,
                    f"{base} constructed without a {param} bound on a "
                    "serving path — the request queue must shed load "
                    "(tpu_serve_queue_max -> ServerOverloaded), not "
                    "grow without bound"))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "put" and node.args and \
                _put_blocks(node):
            out.append(self.finding(
                module, node, qual,
                ".put() without block=False/timeout on a serving path "
                "blocks the SUBMITTER forever once the bounded queue "
                "fills — shed at the admission edge instead "
                "(put_nowait -> ServerOverloaded)"))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _BLOCKING_ATTRS:
            if _wait_blocks(node):
                out.append(self.finding(
                    module, node, qual,
                    f".{node.func.attr}() without a timeout on a serving "
                    "path blocks forever when a tick wedges — carry the "
                    "request deadline (tpu_serve_deadline_ms -> "
                    "ServingTimeout); the deliberate graceful-drain join "
                    "needs an allowlist anchor"))
