"""R001 — host synchronization inside jit-reachable code.

``float()``, ``.item()``, ``.tolist()``, ``np.asarray``/``np.array`` and
``jax.device_get`` on a traced value force a device->host round trip: under
trace they either raise (``TracerArrayConversionError``) or, worse, silently
bake a trace-time constant into the compiled program; called between jitted
steps they serialize the dispatch pipeline (the tunneled-TPU RTT is ~130ms,
see boosting/gbdt.py stop_check_freq). The gbdt train step and the ops/
growers are the protected hot paths.

Python casts (``float``/``int``/``bool``) are only flagged when an argument
references a traced name — trace-time conversion of host config constants
(e.g. ``float(obj.renew_alpha)`` on a closed-over host object) is fine.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   expr_references, traced_names)

_ALWAYS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready",
                 "copy_to_host_async"}
_TRACED_CASTS = {"float", "int", "bool", "complex",
                 "np.asarray", "np.array", "numpy.asarray", "numpy.array"}


class HostSyncRule(Rule):
    code = "R001"
    title = "host sync in jit-reachable code"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.reachable_functions(module):
            traced = traced_names(fn, package)
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _ALWAYS:
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"{name}() in jit-reachable code forces a "
                        "device->host sync (or bakes a trace-time "
                        "constant)"))
                elif name in _TRACED_CASTS and any(
                        expr_references(a, traced) for a in node.args):
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"{name}() on a traced value in jit-reachable "
                        "code — host sync / TracerArrayConversionError"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _SYNC_METHODS
                      and not (name or "").startswith(("np.", "numpy."))):
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f".{node.func.attr}() in jit-reachable code "
                        "materializes the array on the host"))
        return out
