"""R001 — host synchronization inside jit-reachable code.

``float()``, ``.item()``, ``.tolist()``, ``np.asarray``/``np.array`` and
``jax.device_get`` on a traced value force a device->host round trip: under
trace they either raise (``TracerArrayConversionError``) or, worse, silently
bake a trace-time constant into the compiled program; called between jitted
steps they serialize the dispatch pipeline (the tunneled-TPU RTT is ~130ms,
see boosting/gbdt.py stop_check_freq). The gbdt train step and the ops/
growers are the protected hot paths.

Python casts (``float``/``int``/``bool``) are only flagged when an argument
references a traced name — trace-time conversion of host config constants
(e.g. ``float(obj.renew_alpha)`` on a closed-over host object) is fine.

Two checkpoint-era sub-checks (the snapshot subsystem, io/checkpoint.py):

* file I/O (``open``/``os.fsync``/``pickle.dump``/``np.save``/...) in
  jit-reachable code — a snapshot write reachable from a traced program
  is both a host sync AND a trace-time constant bake; snapshots belong in
  the host training loop, at ``tpu_checkpoint_freq`` ticks;
* any function that BOTH pickles state and writes/fsyncs a file is pinned
  as a **snapshot-writer site** regardless of reachability: such a
  function blocks on a device fetch + fsync wherever it is called from,
  so every call site must be a deliberate tick. The shipped writer
  (``io/checkpoint.py::write_snapshot``) carries the allowlist entry;
  a new unreviewed writer fails tier-1 until justified.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   expr_references, traced_names)

_ALWAYS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready",
                 "copy_to_host_async"}
_TRACED_CASTS = {"float", "int", "bool", "complex",
                 "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
#: file/serialization I/O that must never be reachable from a traced
#: program (each call is a host sync at best, a baked trace-time constant
#: at worst)
_FILE_IO = {"open", "os.fdopen", "os.fsync", "os.replace",
            "pickle.dump", "pickle.dumps",
            "np.save", "np.savez", "numpy.save", "numpy.savez",
            "json.dump"}
#: the snapshot-writer structural signature: serializes state AND syncs
#: it to a file in the same function
_SNAP_SERIALIZE = {"pickle.dump", "pickle.dumps"}
_SNAP_FILE_SINK = {"open", "os.fdopen", "os.fsync"}


class HostSyncRule(Rule):
    code = "R001"
    title = "host sync in jit-reachable code"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.reachable_functions(module):
            traced = traced_names(fn, package)
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _ALWAYS:
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"{name}() in jit-reachable code forces a "
                        "device->host sync (or bakes a trace-time "
                        "constant)"))
                elif name in _FILE_IO:
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"{name}() in jit-reachable code — checkpoint/"
                        "snapshot file I/O is a host sync; snapshot at "
                        "tpu_checkpoint_freq ticks in the host training "
                        "loop (io/checkpoint.py), never under trace"))
                elif name in _TRACED_CASTS and any(
                        expr_references(a, traced) for a in node.args):
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"{name}() on a traced value in jit-reachable "
                        "code — host sync / TracerArrayConversionError"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _SYNC_METHODS
                      and not (name or "").startswith(("np.", "numpy."))):
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f".{node.func.attr}() in jit-reachable code "
                        "materializes the array on the host"))
        out.extend(self._snapshot_writers(module))
        return out

    def _snapshot_writers(self, module: ModuleInfo) -> List[Finding]:
        """Pin every pickle-and-write-to-file function, reachable or not:
        a snapshot writer blocks its caller on serialization + fsync, so
        each one must be a reviewed, deliberate snapshot-tick path (the
        shipped io/checkpoint.py writer is allowlisted)."""
        out: List[Finding] = []
        for fn in module.functions.values():
            serialize = sink = None
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _SNAP_SERIALIZE and serialize is None:
                    serialize = node
                elif name in _SNAP_FILE_SINK and sink is None:
                    sink = node
            if serialize is not None and sink is not None:
                out.append(self.finding(
                    module, serialize, fn.qualname,
                    "snapshot-writer site (pickles state AND writes/"
                    "fsyncs a file): blocks on a host materialization + "
                    "fsync wherever called — keep off the jit hot path; "
                    "the deliberate snapshot tick carries an allowlist "
                    "entry (io/checkpoint.py::write_snapshot)"))
        return out
