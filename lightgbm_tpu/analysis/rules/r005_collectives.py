"""R005 — async-collective byte accounting must use the result shape.

Post-optimization TPU HLO emits collectives in async form: a ``*-start``
op whose output is a tuple ``(operand, result, ...)``. For
``all-reduce-start`` operand and result shapes match, but for
``all-gather-start`` the result is ``num_devices`` times the operand (and
``collective-permute-start`` also carries the payload in the result slot)
— so accounting code that takes the FIRST tuple element under-reports the
transferred bytes (the seed case: parallel/comm_accounting.py, ADVICE r5
#1, where the voting/data ratio in COMM_ACCOUNTING.json would have been
silently wrong the day async all-gathers appear).

Detection: inside a branch guarded by a ``*-start`` test (a string
constant ending in ``-start``), taking the first element of a shapes
collection (``x[:1]`` / ``x[0]``) without any second-element selection
(``x[1]`` / ``x[1:2]``) in the same guarded region means every async
kind is counted by operand shape.

PR 2 widened the collective surface — ``lax.psum_scatter`` lowers to
``reduce-scatter`` (async twin ``reduce-scatter-start``), and async
``-start``/``-done`` pairs appear throughout post-optimization TPU HLO —
so two more accounting hazards are checked:

* **stale inventory**: a collective-kind literal that carries ``-start``
  twins for some kinds but lists a base kind (e.g. ``reduce-scatter``)
  without its ``-start`` twin silently drops that kind's bytes the day
  XLA goes async on it (exactly how psum_scatter traffic would have
  vanished from COMM_ACCOUNTING.json);
* **double counting**: accumulating bytes inside a branch guarded by a
  ``*-done`` test — the ``-done`` op carries no payload of its own, so
  counting both halves of the pair reports every async collective twice.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .base import Finding, ModuleInfo, PackageInfo, Rule

#: base collective opcodes as post-optimization HLO spells them
_BASE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def _collective_token(value: str) -> Optional[str]:
    """The base kind a string denotes, or None if not a collective name."""
    for base in _BASE_KINDS:
        if value in (base, base + "-start", base + "-done"):
            return base
    return None


def _guards_start(test: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, str)
               and n.value.endswith("-start") for n in ast.walk(test))


def _first_second_selects(node: ast.AST
                          ) -> Tuple[Optional[ast.AST], bool]:
    """(first first-element Subscript or None, any second-element select)."""
    first = None
    second = False
    for n in ast.walk(node):
        if not isinstance(n, ast.Subscript):
            continue
        sl = n.slice
        if isinstance(sl, ast.Constant) and sl.value == 0:
            first = first or n
        elif isinstance(sl, ast.Constant) and sl.value == 1:
            second = True
        elif isinstance(sl, ast.Slice):
            lo, hi = sl.lower, sl.upper
            if lo is None and isinstance(hi, ast.Constant) \
                    and hi.value == 1:
                first = first or n
            elif isinstance(lo, ast.Constant) and lo.value == 1:
                second = True
    return first, second


def _guards_done(test: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, str)
               and n.value.endswith("-done") for n in ast.walk(test))


def _accumulates(body: List[ast.AST]) -> Optional[ast.AST]:
    """First byte-accumulation statement in a region (``+=``, ``.append``,
    ``sum(...)``), or None."""
    region = ast.Module(body=body, type_ignores=[])
    for n in ast.walk(region):
        if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add):
            return n
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("append", "add"):
                return n
            if isinstance(n.func, ast.Name) and n.func.id == "sum":
                return n
    return None


class CollectiveAccountingRule(Rule):
    code = "R005"
    title = "async collective accounting shape rules"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        func_of = module.func_of

        has_start_handling = any(
            isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.value.endswith("-start") for n in ast.walk(module.tree))

        for node in ast.walk(module.tree):
            if isinstance(node, ast.If) and _guards_start(node.test):
                first, second = _first_second_selects(
                    ast.Module(body=node.body, type_ignores=[]))
                if first is not None and not second:
                    out.append(self.finding(
                        module, first, func_of(node),
                        "async '*-start' collective counted by its FIRST "
                        "tuple element (the operand) — all-gather-start / "
                        "reduce-scatter-start / collective-permute-start "
                        "must count the result shape (second element) or "
                        "transferred bytes are mis-reported"))
            if isinstance(node, ast.If) and _guards_done(node.test) \
                    and has_start_handling:
                acc = _accumulates(node.body)
                if acc is not None:
                    out.append(self.finding(
                        module, acc, func_of(node),
                        "bytes accumulated under a '*-done' guard — the "
                        "-done half of an async pair carries no payload "
                        "of its own; counting both halves reports every "
                        "async collective twice"))
            if isinstance(node, (ast.Tuple, ast.List)) and \
                    len(node.elts) >= 3:
                values = [e.value for e in node.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
                if len(values) != len(node.elts):
                    continue
                tokens = [_collective_token(v) for v in values]
                if any(t is None for t in tokens):
                    continue           # not a collective inventory
                if not any(v.endswith("-start") for v in values):
                    continue           # sync-only inventory: out of scope
                missing = sorted(
                    v for v in values if _collective_token(v) == v
                    and v + "-start" not in values)
                for base in missing:
                    out.append(self.finding(
                        module, node, func_of(node),
                        f"collective inventory lists '{base}' without its "
                        f"async twin '{base}-start' — post-optimization "
                        "HLO emits the async form (lax.psum_scatter => "
                        "reduce-scatter-start), so its bytes silently "
                        "drop out of the accounting"))
        return out
