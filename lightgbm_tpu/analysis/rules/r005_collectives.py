"""R005 — async-collective byte accounting must use the result shape.

Post-optimization TPU HLO emits collectives in async form: a ``*-start``
op whose output is a tuple ``(operand, result, ...)``. For
``all-reduce-start`` operand and result shapes match, but for
``all-gather-start`` the result is ``num_devices`` times the operand (and
``collective-permute-start`` also carries the payload in the result slot)
— so accounting code that takes the FIRST tuple element under-reports the
transferred bytes (the seed case: parallel/comm_accounting.py, ADVICE r5
#1, where the voting/data ratio in COMM_ACCOUNTING.json would have been
silently wrong the day async all-gathers appear).

Detection: inside a branch guarded by a ``*-start`` test (a string
constant ending in ``-start``), taking the first element of a shapes
collection (``x[:1]`` / ``x[0]``) without any second-element selection
(``x[1]`` / ``x[1:2]``) in the same guarded region means every async
kind is counted by operand shape.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .base import Finding, ModuleInfo, PackageInfo, Rule


def _guards_start(test: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, str)
               and n.value.endswith("-start") for n in ast.walk(test))


def _first_second_selects(node: ast.AST
                          ) -> Tuple[Optional[ast.AST], bool]:
    """(first first-element Subscript or None, any second-element select)."""
    first = None
    second = False
    for n in ast.walk(node):
        if not isinstance(n, ast.Subscript):
            continue
        sl = n.slice
        if isinstance(sl, ast.Constant) and sl.value == 0:
            first = first or n
        elif isinstance(sl, ast.Constant) and sl.value == 1:
            second = True
        elif isinstance(sl, ast.Slice):
            lo, hi = sl.lower, sl.upper
            if lo is None and isinstance(hi, ast.Constant) \
                    and hi.value == 1:
                first = first or n
            elif isinstance(lo, ast.Constant) and lo.value == 1:
                second = True
    return first, second


class CollectiveAccountingRule(Rule):
    code = "R005"
    title = "async collective accounting shape rules"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        func_names = {}
        for fn in module.functions.values():
            for n in fn.own_nodes():
                func_names[id(n)] = fn.qualname
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.If) and _guards_start(node.test)):
                continue
            first, second = _first_second_selects(
                ast.Module(body=node.body, type_ignores=[]))
            if first is not None and not second:
                out.append(self.finding(
                    module, first,
                    func_names.get(id(node), "<module>"),
                    "async '*-start' collective counted by its FIRST "
                    "tuple element (the operand) — all-gather-start / "
                    "collective-permute-start must count the result "
                    "shape (second element) or gathered bytes are "
                    "under-reported"))
        return out
