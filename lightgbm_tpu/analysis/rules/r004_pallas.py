"""R004 — Pallas/Mosaic kernel contract checks.

The fused kernels carry hard contracts that the compiler cannot check for
the caller (ops/fused_split.py module docstring):

  * block sizes must be 32-multiples — Mosaic's DMA checker needs offsets
    provably divisible by the sublane tiling; a literal that is not a
    32-multiple fails at runtime on device only.
  * environment overrides must not flow into a block size raw: the
    automatic derivation rounds to 32 and re-checks the scoped-VMEM
    estimate, and an unvalidated ``int(os.environ[...])`` bypasses both
    (the seed case: LGBM_TPU_FUSED_BS, boosting/gbdt.py — ADVICE r5 #3).
    An assignment whose target looks like a block size and whose value
    reads ``os.environ`` must go through a validating helper (a call with
    "valid" or "round" in its name) or inline ``// 32`` rounding.
  * ``fused_split`` callers must pass ``num_rows`` so the kernel's
    ``pad >= block_size`` contract is enforced statically instead of
    silently clamping rows away (ADVICE r5 #2; the raise lives in
    ops/fused_split.py).
  * batched-M pending rings (round 6, ops/fused_split.py hist_flush):
    a constant ``mbatch`` must keep 8*mbatch within the 128 MXU rows,
    and ``mbatch x block_size`` VMEM residency (bin slots, transposed
    channel slots, and the flush's one-hot/block-diagonal transients,
    evaluated for both the bf16 and int8 channel layouts) must stay
    under the scoped-VMEM ring budget — the arithmetic lives in
    ops/fused_split.py fused_ring_bytes and is evaluated here at the
    minimum 128-byte record width.
  * a kernel that stages histogram blocks into a pending ring (writes
    to a ``pend*`` buffer keyed off ``mbatch``) must drain the
    ``pushes % mbatch`` remainder: without a drain function carrying
    that modulo, the last partial batch is silently dropped and every
    histogram whose block count is not a multiple of K is wrong.
  * bins-on-sublanes layout contracts (round 6): a constant
    ``hist_layout="sublane"`` needs ``num_bins <= 64`` (bins lie along
    sublanes; wider counts cannot group features into the 128 MXU rows),
    and the pending-ring VMEM budget is evaluated under BOTH layouts —
    the sublane layout stages channels row-major, which the VMEM tiling
    pads to the full 128-lane width (a 4-8x larger channel-slot term
    that the ring-bytes formula must charge, ops/fused_split.py
    fused_ring_bytes). The formula takes the RECORD width as its
    ``num_cols`` — under RowLayout.packed4 that width is already the
    nibble-packed one, so packing tightens the bound instead of
    escaping it.
  * pack4 nibble extraction (round 6): a right-shift that selects a
    nibble (``>> 4`` or ``>> ((f & 1) * 4)``-shaped) from a packed bin
    byte must mask the result with ``& 0xF`` — without the mask the
    neighbour feature's high nibble rides along and every downstream
    compare (one-hot, routing predicate) silently mismatches on half
    the rows (ops/fused_split.py bin_col is the canonical site).
  * engine-registry ownership (round 12): histogram-engine selection
    lives in ONE place, ``lightgbm_tpu/engines/`` — the registry that
    the startup microbench autotuner feeds. Outside that package,
    (a) a ``GrowerParams(...)`` / ``._replace(...)`` call setting an
    engine knob (``hist_impl``/``hist_layout``/``hist_mbatch``/
    ``fused_block``) from anything but a registry resolution (a value
    mentioning ``resolved``/``resolution``/``registry``), (b) a
    function choosing between engine-impl constants (assigning or
    returning two or more of ``"xla"``/``"pallas"``/``"fused"``), and
    (c) a histogram call pinning a constant ``impl=``/``layout=`` are
    all findings — a hardcoded engine choice silently bypasses the
    measured per-shape decision AND the user/env override order. The
    one sanctioned escape hatch is ``ops/histogram.py::_resolve_impl``
    (allowlist-anchored): the trace-time per-call-width dispatch that
    still runs when the registry hands ``"auto"`` through
    (``tpu_autotune=off`` / no cached decision).
  * serving-engine contract coverage (round 20): every serving
    ``EngineEntry`` (``id`` starting with ``serve``) must either name an
    HLO contract id (``contracts=("serve_walk",)`` — verified by
    hlo_check.verify_serving_contracts against
    analysis/contracts/<mode>.json) or carry a non-empty
    ``contract_exempt`` justification that names the pinning test
    (``tests/...``). An uncovered serving entry ships a compiled
    program nothing re-verifies — host callbacks or stray collectives
    in the serving path would land silently.
  * quantized-leaf scales must ship their recorded bound (round 20):
    the quantized slab is only safe to serve because
    ``quantize_leaves`` returns an exact max-score-error bound next to
    the scale. An unpack that discards the bound
    (``slab, scale = quantize_leaves(...)`` or a ``_`` third target),
    or a hand-rolled symmetric int8 scale (``amax / 127``) in a
    function that never assigns a ``bound``/``err`` value, serves
    quantized scores with no recorded accuracy contract.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name)

_BLOCK_KWARGS = {"block_size", "bs", "fused_block"}
_MBATCH_KWARGS = {"mbatch", "hist_mbatch"}
_MBATCH_MAX = 16          # 8K <= 128 MXU rows

# engine-registry ownership (sub-checks (a)-(c) in the docstring)
_ENGINE_KWARGS = {"hist_impl", "hist_layout", "hist_mbatch", "fused_block"}
_ENGINE_CONSTS = {"xla", "pallas", "fused"}
_ENGINE_CALL_KWARGS = {"impl", "hist_impl", "layout", "hist_layout"}
_REGISTRY_TOKENS = ("resolv", "registry")


def _is_registry_module(module: ModuleInfo) -> bool:
    """True for the engine-registry package itself (the one place
    engine-selection policy may live)."""
    path = module.path.replace("\\", "/")
    return "/engines/" in path or path.startswith("engines/") \
        or (module.dotted or "").startswith("lightgbm_tpu.engines")


def _mentions_registry(node: ast.AST) -> bool:
    """A value expression sourced from a registry resolution: it
    references a name/attribute/call mentioning ``resolv*``/``registry``
    (``resolved.hist_impl``, ``engine_registry.clamp_fused_block(...)``,
    a local named ``resolved_bs``)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and \
                any(t in n.id.lower() for t in _REGISTRY_TOKENS):
            return True
        if isinstance(n, ast.Attribute) and \
                any(t in n.attr.lower() for t in _REGISTRY_TOKENS):
            return True
    return False


def _target_is_blocky(name: str) -> bool:
    low = name.lower()
    return "block" in low or low in ("bs", "bs_", "fused_bs") \
        or low.endswith("_bs") or low.startswith("bs_")


def _reads_environ(node: ast.AST) -> bool:
    return any(dotted_name(n) == "os.environ"
               for n in ast.walk(node) if isinstance(n, ast.Attribute))


def _has_validation(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = (call_name(n) or "").rsplit(".", 1)[-1].lower()
            if "valid" in name or "round" in name or "clamp" in name:
                return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.FloorDiv) \
                and isinstance(n.right, ast.Constant) and n.right.value == 32:
            return True
    return False


class PallasContractRule(Rule):
    code = "R004"
    title = "Pallas kernel contract checks"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        func_of = _FuncIndex(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(module, node, func_of))
                out.extend(self._check_serving_entry(
                    module, node, func_of))
                if not _is_registry_module(module):
                    out.extend(self._check_engine_kwargs(
                        module, node, func_of))
                    out.extend(self._check_engine_call_consts(
                        module, node, func_of))
            elif isinstance(node, ast.Assign):
                out.extend(self._check_env_assign(module, node, func_of))
                out.extend(self._check_quant_unpack(module, node, func_of))
        for fn in module.functions.values():
            out.extend(self._check_defaults(module, fn))
            out.extend(self._check_quant_scale(module, fn))
            if not _is_registry_module(module):
                out.extend(self._check_engine_chooser(module, fn))
        out.extend(self._check_ring_drain(module))
        out.extend(self._check_nibble_masks(module, func_of))
        return out

    # -- serving-engine contract coverage (round 20) --------------------
    def _check_serving_entry(self, module, node: ast.Call, func_of
                             ) -> List[Finding]:
        """A serving ``EngineEntry`` (id starting with "serve") must name
        an HLO contract id or carry a contract_exempt justification that
        points at the pinning test (a ``tests/`` path); otherwise the
        entry ships a compiled serving program nothing re-verifies."""
        name = (call_name(node) or "").rsplit(".", 1)[-1]
        if name != "EngineEntry":
            return []
        eid = None
        for kw in node.keywords:
            if kw.arg == "id" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                eid = kw.value.value
        if node.args and eid is None \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            eid = node.args[0].value
        if not eid or not eid.startswith("serve"):
            return []
        contracts_ok = exempt_ok = exempt_present = False
        for kw in node.keywords:
            if kw.arg == "contracts":
                if isinstance(kw.value, (ast.Tuple, ast.List)) \
                        and kw.value.elts:
                    contracts_ok = True
                elif not isinstance(kw.value, (ast.Tuple, ast.List)):
                    contracts_ok = True     # computed value: trust it
            elif kw.arg == "contract_exempt":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    exempt_present = bool(kw.value.value.strip())
                    exempt_ok = "tests/" in kw.value.value
                else:
                    exempt_present = exempt_ok = True  # computed: trust
        if contracts_ok or exempt_ok:
            return []
        what = ("its contract_exempt justification does not name the "
                "pinning test (a tests/ path)") if exempt_present else \
               ("it names no HLO contract id and carries no "
                "contract_exempt justification")
        return [self.finding(
            module, node, func_of(node),
            f"serving EngineEntry {eid!r}: {what} — every serving "
            "engine either ships a verified HLO contract "
            "(analysis/contracts/<mode>.json, checked by "
            "verify_serving_contracts) or a contract_exempt string "
            "naming the parity test that pins its output")]

    # -- quantized-leaf recorded bound (round 20) -----------------------
    def _check_quant_unpack(self, module, node: ast.Assign, func_of
                            ) -> List[Finding]:
        """``quantize_leaves`` returns (slab, scale, bound); an unpack
        that drops or discards the bound serves quantized scores with no
        recorded accuracy contract."""
        if not (isinstance(node.value, ast.Call)
                and (call_name(node.value) or "").rsplit(".", 1)[-1]
                == "quantize_leaves"):
            return []
        if len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Tuple):
            return []                      # whole-tuple capture: bound kept
        elts = node.targets[0].elts
        dropped = len(elts) < 3 or (
            isinstance(elts[2], ast.Name) and elts[2].id == "_")
        if not dropped:
            return []
        return [self.finding(
            module, node, func_of(node),
            "quantize_leaves unpack discards the recorded "
            "max-score-error bound — the bound is the accuracy contract "
            "the quantized slab ships (leaf_quant_bound); keep it next "
            "to the scale instead of serving quantized scores blind")]

    def _check_quant_scale(self, module, fn) -> List[Finding]:
        """A hand-rolled symmetric int8 leaf scale (an assignment to a
        ``*scale*`` name whose value divides by 127) in a function that
        never assigns a ``bound``/``err`` value has no recorded error
        bound at all — the seed shape quantize_leaves exists to
        prevent."""
        site = None
        records_bound = False
        for n in fn.own_nodes():
            if not isinstance(n, ast.Assign):
                continue
            names = [t.id for t in n.targets if isinstance(t, ast.Name)]
            names += [e.id for t in n.targets
                      if isinstance(t, ast.Tuple)
                      for e in t.elts if isinstance(e, ast.Name)]
            if any("bound" in m.lower() or "err" in m.lower()
                   for m in names):
                records_bound = True
            if site is None and any("scale" in m.lower() for m in names) \
                    and self._divides_by_127(n.value):
                site = n
        if site is None or records_bound:
            return []
        return [self.finding(
            module, site, fn.qualname,
            "symmetric int8 leaf scale computed without a recorded "
            "error bound: nothing in this function assigns a "
            "bound/err value, so the quantized slab ships with no "
            "accuracy contract — use quantize_leaves (slab, scale, "
            "bound) or record the per-tree worst-case dequantization "
            "error next to the scale")]

    @staticmethod
    def _divides_by_127(value: ast.AST) -> bool:
        for n in ast.walk(value):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Div) \
                    and isinstance(n.right, ast.Constant) \
                    and isinstance(n.right.value, (int, float)) \
                    and float(n.right.value) == 127.0:
                return True
        return False

    # -- engine-registry ownership (round 12) ---------------------------
    def _check_engine_kwargs(self, module, node: ast.Call, func_of
                             ) -> List[Finding]:
        """(a) GrowerParams(hist_*=...) / ._replace(hist_*=...) outside
        lightgbm_tpu/engines must source the value from a registry
        resolution — anything else re-opens a second selection site."""
        name = (call_name(node) or "").rsplit(".", 1)[-1]
        if name not in ("GrowerParams", "_replace"):
            return []
        out: List[Finding] = []
        for kw in node.keywords:
            if kw.arg in _ENGINE_KWARGS and \
                    not _mentions_registry(kw.value):
                out.append(self.finding(
                    module, kw.value, func_of(node),
                    f"{name}({kw.arg}=...) outside lightgbm_tpu/engines "
                    "selects a histogram engine knob away from the "
                    "registry — populate it from a registry.resolve "
                    "Resolution (user > env > autotune cache > default) "
                    "so the measured per-shape decision and the "
                    "override order cannot be bypassed"))
        return out

    def _check_engine_call_consts(self, module, node: ast.Call, func_of
                                  ) -> List[Finding]:
        """(c) a histogram DISPATCH call (histogram_block / histogram —
        the funnels the registry's resolution threads through) pinning
        ``impl=``/``layout=`` to a constant hardcodes an engine choice;
        direct engine-callable calls (pallas_histogram) stay under the
        existing block/sublane contracts."""
        name = (call_name(node) or "").rsplit(".", 1)[-1]
        if name not in ("histogram_block", "histogram"):
            return []
        out: List[Finding] = []
        for kw in node.keywords:
            if kw.arg in _ENGINE_CALL_KWARGS and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str) and \
                    kw.value.value != "auto":
                out.append(self.finding(
                    module, kw.value, func_of(node),
                    f"{name}({kw.arg}={kw.value.value!r}): constant "
                    "engine selection outside lightgbm_tpu/engines — "
                    "thread the registry-resolved value (GrowerParams) "
                    "through instead of pinning the engine at the "
                    "callsite"))
        return out

    def _check_engine_chooser(self, module, fn) -> List[Finding]:
        """(b) a function assigning/returning >= 2 distinct engine-impl
        constants IS an engine-selection policy site; outside the
        registry that policy is unowned (the ops/histogram.py
        _resolve_impl trace-time escape hatch carries the one allowlist
        anchor)."""
        consts = set()
        first = None
        for n in fn.own_nodes():
            vals = []
            if isinstance(n, ast.Return) and n.value is not None:
                vals = [n.value]
            elif isinstance(n, ast.Assign):
                vals = [n.value]
            for v in vals:
                if isinstance(v, ast.IfExp):
                    vals.extend([v.body, v.orelse])
                    continue
                if isinstance(v, ast.Constant) and v.value in _ENGINE_CONSTS:
                    consts.add(v.value)
                    first = first or n
        if len(consts) < 2:
            return []
        return [self.finding(
            module, first or fn.node, fn.qualname,
            f"function selects between engine impls {sorted(consts)} "
            "outside lightgbm_tpu/engines — engine-selection policy "
            "belongs to the registry (engines/registry.py), where the "
            "autotune cache and the user/env override order apply; the "
            "only sanctioned exception is the trace-time "
            "tpu_autotune=off dispatch in ops/histogram.py "
            "_resolve_impl (allowlisted)")]

    def _check_call(self, module, node: ast.Call, func_of) -> List[Finding]:
        name = (call_name(node) or "").rsplit(".", 1)[-1]
        out: List[Finding] = []
        if name not in ("fused_split", "pallas_call", "pallas_histogram"):
            return out
        for kw in node.keywords:
            if kw.arg in _BLOCK_KWARGS and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int) and \
                    kw.value.value % 32 != 0:
                out.append(self.finding(
                    module, kw.value, func_of(node),
                    f"{name}({kw.arg}={kw.value.value}): block sizes "
                    "must be 32-multiples (Mosaic DMA sublane "
                    "alignment)"))
        if name == "fused_split" and not any(
                kw.arg == "num_rows" for kw in node.keywords):
            out.append(self.finding(
                module, node, func_of(node),
                "fused_split call without num_rows= — the "
                "pad >= block_size contract cannot be checked "
                "statically and a short pad silently drops tail rows"))
        out.extend(self._check_sublane(module, node, func_of, name))
        out.extend(self._check_mbatch(module, node, func_of, name))
        return out

    def _check_sublane(self, module, node: ast.Call, func_of,
                       name: str) -> List[Finding]:
        """Constant-foldable bins-on-sublanes block-shape contract: a
        sublane layout with num_bins > 64 cannot group features into the
        128 MXU rows (ops/pallas_histogram.py _SUBLANE_MAX_BINS)."""
        layout = bins = None
        for kw in node.keywords:
            if kw.arg in ("hist_layout", "layout") and \
                    isinstance(kw.value, ast.Constant):
                layout = kw.value.value
            elif kw.arg == "num_bins" and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                bins = kw.value.value
        if layout != "sublane":
            return []
        if bins is None and name == "pallas_histogram" \
                and len(node.args) >= 3 \
                and isinstance(node.args[2], ast.Constant) \
                and isinstance(node.args[2].value, int):
            bins = node.args[2].value
        if bins is None or bins <= 64:
            return []
        return [self.finding(
            module, node, func_of(node),
            f"{name}(hist_layout='sublane', num_bins={bins}): the "
            "bins-on-sublanes layout supports num_bins <= 64 — wider bin "
            "counts leave no room to group features into the 128 MXU "
            "rows (bins lie along sublanes)")]

    def _check_mbatch(self, module, node: ast.Call, func_of,
                      name: str) -> List[Finding]:
        """Constant-foldable batched-M contracts: MXU-row bound + the
        pending ring's scoped-VMEM budget (both channel layouts)."""
        mb = bs = None
        layouts = ("lane",)             # the parameter default
        for kw in node.keywords:
            if kw.arg in ("hist_layout",) and \
                    isinstance(kw.value, ast.Constant):
                # constant layout: charge that layout's formula; a traced/
                # computed layout charges both (conservative)
                layouts = ((kw.value.value,)
                           if kw.value.value in ("lane", "sublane")
                           else ("lane", "sublane"))
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                if kw.arg in _MBATCH_KWARGS:
                    mb = kw.value.value
                elif kw.arg in _BLOCK_KWARGS:
                    bs = kw.value.value
            elif kw.arg == "hist_layout" and \
                    not isinstance(kw.value, ast.Constant):
                layouts = ("lane", "sublane")
        if mb is None:
            return []
        out: List[Finding] = []
        if not 1 <= mb <= _MBATCH_MAX:
            out.append(self.finding(
                module, node, func_of(node),
                f"{name}(mbatch={mb}): the batched-M depth must stay in "
                f"[1, {_MBATCH_MAX}] — 8*mbatch output rows must fit the "
                "128 MXU rows (ops/fused_split.py hist_flush)"))
            return out
        if name == "fused_split" and bs is not None:
            from ...ops.fused_split import (_VMEM_RING_BUDGET,
                                            fused_ring_bytes)
            # minimum 128-byte record width (packed4 layouts are NARROWER,
            # so this floor covers them); evaluated for both channel
            # dtypes AND both register layouts — the sublane layout's
            # row-major channel slots pad to 128 lanes and must be charged
            worst = max(
                fused_ring_bytes(bs, 128, mb, quant=q, hist_layout=hl)
                for q in (False, True) for hl in layouts)
            if worst > _VMEM_RING_BUDGET:
                out.append(self.finding(
                    module, node, func_of(node),
                    f"{name}(block_size={bs}, mbatch={mb}): the pending "
                    f"ring needs >= {worst >> 20}MB of scoped VMEM "
                    f"(budget {_VMEM_RING_BUDGET >> 20}MB) even at the "
                    "minimum record width — derive the block size via "
                    "fused_block_cap(num_cols, mbatch)"))
        return out

    def _check_ring_drain(self, module) -> List[Finding]:
        """A kernel that stages histogram blocks into a pending ring
        (writes a ``pend*`` buffer keyed off ``mbatch``) must drain the
        ``pushes % mbatch`` remainder somewhere in the module: a drain
        function carrying ``lax.rem(_, mbatch)`` / ``_ % mbatch``."""
        stagers = []
        has_drain = False
        for fname, fn in module.functions.items():
            writes_pend = any(
                isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id.startswith("pend")
                    for t in n.targets)
                for n in ast.walk(fn.node))
            uses_mbatch = any(
                isinstance(n, ast.Name) and n.id in _MBATCH_KWARGS
                for n in ast.walk(fn.node))
            if writes_pend and uses_mbatch:
                stagers.append(fn)
            if "drain" in fname.lower() and self._has_mbatch_rem(fn.node):
                has_drain = True
        if not stagers or has_drain:
            return []
        fn = stagers[0]
        return [self.finding(
            module, fn.node, fn.qualname,
            "pending-ring staging without a remainder drain: no 'drain' "
            "function computes pushes % mbatch, so the last partial "
            "batch of staged histogram blocks is silently dropped "
            "whenever the block count is not a multiple of mbatch")]

    # names whose reads plausibly hold a PACKED bin byte (two features
    # per byte): the detector scopes to these so unrelated bit twiddling
    # (word-index shifts, radix unpacks) stays out of view
    _PACKY = ("pack", "nibble", "byte")

    def _check_nibble_masks(self, module, func_of) -> List[Finding]:
        """pack4 unpack sites must mask: ``X >> 4`` (or the dynamic
        ``X >> ((f & 1) * 4)`` form) on a packed bin byte without an
        ``& 0xF`` around it leaves the neighbour feature's nibble in the
        result — flagged unless the shift sits under a BitAnd with 15."""
        parents = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.RShift)):
                continue
            if not self._is_nibble_shift(node.right):
                continue
            if not self._touches_packed(module, node, parents):
                continue
            if self._masked_with_0xf(node, parents):
                continue
            out.append(self.finding(
                module, node, func_of(node),
                "pack4 nibble extract without the & 0xF mask: the shift "
                "selects a nibble from a packed bin byte, but the "
                "neighbour feature's nibble survives in the high bits — "
                "every downstream bin compare silently mismatches "
                "(mask the result with & 0xF)"))
        return out

    @staticmethod
    def _is_nibble_shift(rhs: ast.AST) -> bool:
        """Shift amounts that select a nibble: the constant 4, or an
        expression multiplying by 4 (the ``(f & 1) * 4`` dynamic form)."""
        if isinstance(rhs, ast.Constant):
            return rhs.value == 4
        for n in ast.walk(rhs):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
                for side in (n.left, n.right):
                    if isinstance(side, ast.Constant) and side.value == 4:
                        return True
        return False

    def _touches_packed(self, module, node: ast.BinOp, parents) -> bool:
        """Scope: the shifted value's name mentions a packed-byte source,
        or the enclosing function is a pack4 helper."""
        for n in ast.walk(node.left):
            if isinstance(n, ast.Name) and \
                    any(t in n.id.lower() for t in self._PACKY):
                return True
            if isinstance(n, ast.Attribute) and \
                    any(t in n.attr.lower() for t in self._PACKY):
                return True
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(t in cur.name.lower()
                            for t in ("pack", "nibble", "bin_col")):
                return True
        return False

    @staticmethod
    def _masked_with_0xf(node: ast.AST, parents) -> bool:
        """True when an ancestor BitAnd masks with 15 (`& 0xF`, including
        the dtype-wrapped `& jnp.uint8(0x0F)` form)."""
        def is_0xf(n: ast.AST) -> bool:
            if isinstance(n, ast.Constant) and n.value == 15:
                return True
            return (isinstance(n, ast.Call) and len(n.args) == 1
                    and isinstance(n.args[0], ast.Constant)
                    and n.args[0].value == 15)

        cur = node
        while cur in parents:
            parent = parents[cur]
            if isinstance(parent, ast.BinOp) and \
                    isinstance(parent.op, ast.BitAnd) and \
                    (is_0xf(parent.left) or is_0xf(parent.right)):
                return True
            if not isinstance(parent, (ast.BinOp, ast.UnaryOp)):
                break
            cur = parent
        return False

    @staticmethod
    def _has_mbatch_rem(fn_node: ast.AST) -> bool:
        for n in ast.walk(fn_node):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod) \
                    and isinstance(n.right, ast.Name) \
                    and n.right.id in _MBATCH_KWARGS:
                return True
            if isinstance(n, ast.Call) and \
                    (call_name(n) or "").endswith("rem") and \
                    len(n.args) == 2 and isinstance(n.args[1], ast.Name) \
                    and n.args[1].id in _MBATCH_KWARGS:
                return True
        return False

    def _check_env_assign(self, module, node: ast.Assign, func_of
                          ) -> List[Finding]:
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(_target_is_blocky(t) for t in targets):
            return []
        if not _reads_environ(node.value) or _has_validation(node.value):
            return []
        return [self.finding(
            module, node, func_of(node),
            f"block size '{targets[0]}' taken raw from os.environ — "
            "round to a 32-multiple and re-check the scoped-VMEM "
            "estimate before accepting an override")]

    def _check_defaults(self, module, fn) -> List[Finding]:
        out: List[Finding] = []
        args = fn.node.args
        pos = args.posonlyargs + args.args
        defaults = [None] * (len(pos) - len(args.defaults)) \
            + list(args.defaults)
        pairs = list(zip(pos, defaults)) \
            + list(zip(args.kwonlyargs, args.kw_defaults))
        for param, default in pairs:
            if param.arg in _BLOCK_KWARGS and \
                    isinstance(default, ast.Constant) and \
                    isinstance(default.value, int) and \
                    default.value % 32 != 0:
                out.append(self.finding(
                    module, default, fn.qualname,
                    f"default {param.arg}={default.value} is not a "
                    "32-multiple (Mosaic DMA sublane alignment)"))
        return out


class _FuncIndex:
    """Map an AST node to its enclosing function qualname (by line span)."""

    def __init__(self, module: ModuleInfo):
        self.spans = []
        for fn in module.functions.values():
            end = getattr(fn.node, "end_lineno", fn.node.lineno)
            self.spans.append((fn.node.lineno, end, fn.qualname))
        self.spans.sort(key=lambda s: (s[0], -s[1]))

    def __call__(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        best = "<module>"
        for lo, hi, qual in self.spans:
            if lo <= line <= hi:
                best = qual            # innermost wins (sorted outer-first)
        return best
