"""R010 — rank-divergent control flow reaching a collective.

The pod deadlock nobody can debug from a stack trace: collectives are
rendezvous points, so every rank must execute the SAME collective
sequence (kind, order, count). The reference enforces this by design —
one fixed per-rank schedule built at InitTrain (``src/network/``) and no
rank-conditional Network calls anywhere in the training loop. In our
world the hazard is Python-level: a branch or loop bound fed by a
*rank-dependent read* (``jax.process_index()``, an env rank variable
like ``LIGHTGBM_TPU_PROCESS_ID``, ``infer_process_id``) that guards a
collective call means rank 0 arrives at a rendezvous its peers never
join — the pod hangs until the watchdog (or the operator) kills it.
Inside jit the same read is a trace-time Python int, so each rank would
compile a DIFFERENT program: statically undetectable from any single
rank's HLO, which is exactly why spmd_check's per-module schedule check
(the HLO half of this lint) cannot catch it and the AST must.

Findings:

* a branch whose test is rank-tainted and whose arms contain UNMATCHED
  collective call counts (one arm syncs, the other does not — or a
  rank-guarded early ``return``/``raise`` skips collectives later in the
  function);
* a ``for``/``while`` whose iteration count is rank-tainted with a
  collective in the body (ranks disagree on how many times they join).

Matched arms are legal and common (every rank syncs, then branches on
the result) — the reference's own discipline, and gather_metadata's
"validation is itself a collective" pattern here. ``jax.process_count()``
is also treated as a rank read (a half-configured launch makes it
rank-varying), EXCEPT the ubiquitous distributed-at-all guard
(``process_count() <= 1`` and friends against literal 0/1/2), which is
uniform whenever a collective could rendezvous at all.

The collective vocabulary reuses R006's axis-primitive set (minus the
local-only axis queries) plus the host-side comm helpers
(``process_allgather``, ``sync_barrier``, ``kv_allgather``, ...); the
schedule framing matches the R005/spmd_check collective inventory.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name)
from .r006_axis import _AXIS_CALLS

#: collective rendezvous calls — R006's axis primitives minus the
#: local-only queries, plus the host-level comm funnels of parallel/
_COMM_CALLS = (_AXIS_CALLS - {"axis_index", "axis_size"}) | {
    "all_to_all", "process_allgather", "sync_global_devices",
    "sync_barrier", "kv_allgather", "wait_at_barrier",
    "broadcast_one_to_all", "gather_metadata", "pool_bin_sample"}

#: rank-dependent read calls (basename match)
_RANK_CALLS = {"process_index", "infer_process_id"}
#: uniform-unless-misconfigured: counted as a rank read, but the
#: distributed-at-all literal guard is exempt (see _trivial_count_guard)
_COUNT_CALLS = {"process_count"}

#: env-var name fragments that assign ranks
_RANK_ENV_MARKERS = ("PROCESS_ID", "RANK", "TASK_INDEX", "TASK_ID",
                     "WORKER_ID")


def _is_rank_env_read(node: ast.Call) -> bool:
    """``os.environ.get("...RANK...")`` / ``os.getenv(...)`` reads."""
    cname = call_name(node) or ""
    if not (cname.endswith("environ.get") or cname.endswith("getenv")):
        return False
    return any(isinstance(a, ast.Constant) and isinstance(a.value, str)
               and any(m in a.value.upper() for m in _RANK_ENV_MARKERS)
               for a in node.args)


def _rank_source_kind(node: ast.AST) -> Optional[str]:
    """What rank-dependent read an expression node is, if any."""
    if isinstance(node, ast.Subscript):
        # os.environ["...RANK..."]
        base = dotted_name(node.value) or ""
        if base.endswith("environ") and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and any(m in node.slice.value.upper()
                        for m in _RANK_ENV_MARKERS):
            return f"environ[{node.slice.value!r}]"
        return None
    if not isinstance(node, ast.Call):
        return None
    base = (call_name(node) or "").rsplit(".", 1)[-1]
    if base in _RANK_CALLS:
        return f"{base}()"
    if base in _COUNT_CALLS:
        return f"{base}()"
    if _is_rank_env_read(node):
        key = next((a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)), "?")
        return f"environ.get({key!r})"
    return None


def _count_only(node: ast.AST) -> bool:
    """True when every rank read under ``node`` is a process_count."""
    saw = False
    for n in ast.walk(node):
        kind = _rank_source_kind(n)
        if kind is None:
            continue
        if not kind.startswith(tuple(_COUNT_CALLS)):
            return False
        saw = True
    return saw


def _trivial_count_guard(test: ast.AST, tainted: Set[str]) -> bool:
    """The distributed-at-all guard: ``process_count() <= 1`` (or a name
    bound to it) compared against literal 0/1/2, with no OTHER rank
    taint in the test. Uniform by construction — when ranks could
    disagree on it, there is no 2-rank rendezvous to deadlock."""
    if not isinstance(test, ast.Compare) or len(test.comparators) != 1:
        return False
    lit = test.comparators[0]
    if not (isinstance(lit, ast.Constant) and lit.value in (0, 1, 2)):
        return False
    left = test.left
    if _count_only(left):
        return True
    return isinstance(left, ast.Name) and left.id in tainted \
        and tainted_kind(left.id, tainted) == "count"


#: marker suffix so taint provenance survives the name set
def tainted_kind(name: str, tainted: Set[str]) -> str:
    return "count" if f"{name}\0count" in tainted else "rank"


def _collect_taint(fn) -> Tuple[Set[str], List[Tuple[ast.AST, str]]]:
    """(tainted local names, direct rank-read expression sites).

    Names are tagged with provenance: a ``name\\0count`` twin marks a
    process_count-only binding (eligible for the trivial-guard
    exemption); everything else is genuinely rank-varying."""
    tainted: Set[str] = set()
    for n in fn.own_nodes():
        if not isinstance(n, ast.Assign) or not n.targets:
            continue
        kinds = {k for sub in ast.walk(n.value)
                 for k in ([_rank_source_kind(sub)] if
                           _rank_source_kind(sub) else [])}
        if not kinds:
            continue
        count_only = all(k.startswith(tuple(_COUNT_CALLS)) for k in kinds)
        for t in n.targets:
            if isinstance(t, ast.Name):
                tainted.add(t.id)
                if count_only:
                    tainted.add(f"{t.id}\0count")
    # bounded propagation through local arithmetic
    for _ in range(4):
        grew = False
        for n in fn.own_nodes():
            if not isinstance(n, ast.Assign) or not n.targets:
                continue
            if not any(isinstance(s, ast.Name) and s.id in tainted
                       for s in ast.walk(n.value)):
                continue
            count_only = all(
                f"{s.id}\0count" in tainted
                for s in ast.walk(n.value)
                if isinstance(s, ast.Name) and s.id in tainted)
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id not in tainted:
                    tainted.add(t.id)
                    if count_only:
                        tainted.add(f"{t.id}\0count")
                    grew = True
        if not grew:
            break
    return tainted, []


def _references_taint(node: ast.AST, tainted: Set[str]) -> Optional[str]:
    """The rank source an expression carries, or None."""
    for n in ast.walk(node):
        kind = _rank_source_kind(n)
        if kind is not None:
            return kind
        if isinstance(n, ast.Name) and n.id in tainted:
            return f"'{n.id}' (bound from a rank read)"
    return None


def _collectives_in(nodes: List[ast.AST]) -> List[Tuple[ast.AST, str]]:
    """Collective call sites in a statement list, NOT descending into
    nested function definitions (those do not run at branch time)."""
    out: List[Tuple[ast.AST, str]] = []
    stack: List[ast.AST] = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            base = (call_name(n) or "").rsplit(".", 1)[-1]
            if base in _COMM_CALLS:
                out.append((n, base))
        stack.extend(ast.iter_child_nodes(n))
    out.sort(key=lambda e: getattr(e[0], "lineno", 0))
    return out


def _exits(nodes: List[ast.AST]) -> bool:
    """Does a statement list unconditionally leave the function body
    (top-level return/raise/continue/break)?"""
    return any(isinstance(n, (ast.Return, ast.Raise, ast.Continue,
                              ast.Break)) for n in nodes)


class CollectiveDivergenceRule(Rule):
    code = "R010"
    title = "rank-divergent control flow reaching a collective"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        func_of = module.func_of
        for fn in module.functions.values():
            tainted, _ = _collect_taint(fn)
            fn_collectives = _collectives_in(
                [n for n in ast.iter_child_nodes(fn.node)])
            for node in fn.own_nodes():
                if isinstance(node, ast.If):
                    out.extend(self._check_if(
                        module, fn, node, tainted, fn_collectives,
                        func_of))
                elif isinstance(node, (ast.For, ast.While)):
                    out.extend(self._check_loop(
                        module, fn, node, tainted, func_of))
        return out

    def _check_if(self, module, fn, node: ast.If, tainted: Set[str],
                  fn_collectives, func_of) -> List[Finding]:
        if _trivial_count_guard(node.test, tainted):
            return []
        src = _references_taint(node.test, tainted)
        if src is None:
            return []
        body = _collectives_in(node.body)
        orelse = _collectives_in(node.orelse)
        if [k for _, k in body] != [k for _, k in orelse]:
            arm = body[0] if body else orelse[0]
            return [self.finding(
                module, arm[0], func_of(node),
                f"collective '{arm[1]}' is guarded by rank-dependent "
                f"{src}: the branch arms run unmatched collective "
                f"sequences ({[k for _, k in body]} vs "
                f"{[k for _, k in orelse]}), so ranks taking different "
                "arms rendezvous at different collectives and the pod "
                "deadlocks — every rank must run the SAME schedule "
                "(sync first, branch on the gathered result; reference "
                "src/network/ fixed per-rank schedule)")]
        if (_exits(node.body) != _exits(node.orelse)) or \
                (_exits(node.body) and not node.orelse):
            later = [(n, k) for n, k in fn_collectives
                     if getattr(n, "lineno", 0) >
                     getattr(node, "end_lineno", node.lineno)]
            if later and not body:
                n, k = later[0]
                return [self.finding(
                    module, node, func_of(node),
                    f"rank-dependent {src} guards an early exit, but "
                    f"collective '{k}' (line {n.lineno}) runs later in "
                    f"{fn.qualname} — the exiting rank never joins it "
                    "and its peers block forever; sync before "
                    "rank-conditional exits (or make the exit "
                    "collective, like gather_metadata's shape checks)")]
        return []

    def _check_loop(self, module, fn, node, tainted: Set[str],
                    func_of) -> List[Finding]:
        bound = node.iter if isinstance(node, ast.For) else node.test
        src = _references_taint(bound, tainted)
        if src is None or (isinstance(node, ast.While)
                           and _trivial_count_guard(node.test, tainted)):
            return []
        body = _collectives_in(node.body)
        if not body:
            return []
        n, k = body[0]
        what = "iteration count" if isinstance(node, ast.For) \
            else "loop condition"
        return [self.finding(
            module, n, func_of(node),
            f"collective '{k}' inside a loop whose {what} depends on "
            f"rank-dependent {src} — ranks disagree on how many times "
            "they join the rendezvous and the pod deadlocks on the "
            "extra round; loop bounds that reach a collective must be "
            "rank-uniform (gather the bound first)")]
