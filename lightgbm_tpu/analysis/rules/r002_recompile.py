"""R002 — recompilation hazards.

Five sub-checks:

  (a) ``jax.jit(...)`` called inside a loop — a fresh jitted callable (and
      a fresh compile-cache entry) per iteration; hoist the jit out of the
      loop or cache the wrapper.
  (b) an argument declared in ``static_argnames`` whose default value is a
      mutable literal (list/dict/set) — unhashable statics raise at call
      time, and per-call fresh objects defeat the compile cache even when
      hashable.
  (c) a Python ``if``/``while`` branching on a traced value inside
      jit-reachable code — under trace this raises
      ``TracerBoolConversionError``; outside it forces a host sync per
      call. Branching on *declared static* arguments is deliberate jax
      style and is not flagged (statics are excluded from the traced set).
      ``is None`` / ``is not None`` tests are identity checks on the
      Python level and are ignored.
  (d) a serving entry point (function whose name mentions
      predict/infer/serve) passing request-derived data into a jitted
      callable WITHOUT bucket padding: the jit key then carries the raw
      request shape and every distinct batch size compiles a fresh
      program (the 26-97s serving stalls BENCH_SHAPES.json recorded
      before the bucketed engine). Values are cleared by flowing through
      a call whose name mentions bucket/pad/tile/shard (e.g.
      ``_pad_request_to_bucket``, ``np.pad``); deliberately unbucketed
      reference paths carry an allowlist anchor.
  (e) a leaf-count- or depth-derived value (``num_leaves``/``max_leaves``/
      ``max_depth`` names, attributes, or config ``.get`` reads) entering
      the grower-step jit key — a ``GrowerParams`` construction or a
      ``grower_params._replace`` update's ``num_leaves=``/``max_depth=``
      keywords, or the arguments of a jitted step/grow callable — WITHOUT
      flowing through a rung/bucket-named mapping (``leaf_rung``,
      ``depth_rung``, ``bucketed_tree_shape``): the step program is then
      keyed on the exact tree shape and every (num_leaves, max_depth)
      pair lowers a fresh program (the 35-97 s training warmups
      BENCH_SHAPES.json recorded before the bucketed step ladder). A
      rung/bucket-named mapping function returning the raw leaf/depth
      value is flagged too — that IS the deliberate exact-keyed escape
      hatch (``tpu_step_buckets=off``) and carries an allowlist anchor.
"""
from __future__ import annotations

import ast
import re
from typing import List, Set

from .base import (Finding, ModuleInfo, PackageInfo, Rule, JIT_NAMES,
                   _plain_name_targets, call_name, expr_references,
                   traced_names)


def _bool_context_traced(test: ast.AST, traced) -> bool:
    """Does evaluating ``test`` call __bool__ on a traced name?

    Uses the STATIC_ATTRS-aware reference walk: ``x.shape[0] > 4`` is a
    static trace-time branch even when ``x`` is traced."""
    if isinstance(test, ast.Name):
        return test.id in traced
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _bool_context_traced(test.operand, traced)
    if isinstance(test, ast.BoolOp):
        return any(_bool_context_traced(v, traced) for v in test.values)
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return False
        return any(expr_references(sub, traced)
                   for sub in [test.left] + list(test.comparators))
    return False


class RecompileRule(Rule):
    code = "R002"
    title = "recompilation hazards"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._jit_in_loop(module))
        out.extend(self._unhashable_static_defaults(module))
        out.extend(self._tracer_branches(module, package))
        out.extend(self._unbucketed_entry_shapes(module, package))
        out.extend(self._unbucketed_step_keys(module, package))
        return out

    # (a) ------------------------------------------------------------
    def _jit_in_loop(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []

        def walk(node: ast.AST, func: str, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_func = func
                child_loop = in_loop
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_func = f"{func}.{child.name}" \
                        if func != "<module>" else child.name
                    child_loop = False      # new call frame resets loop ctx
                elif isinstance(child, (ast.For, ast.While)):
                    child_loop = True
                elif (isinstance(child, ast.Call)
                      and call_name(child) in JIT_NAMES and in_loop):
                    out.append(self.finding(
                        module, child, func,
                        "jax.jit called inside a loop — compiles a fresh "
                        "callable per iteration; hoist or cache it"))
                walk(child, child_func, child_loop)

        walk(module.tree, "<module>", False)
        return out

    # (b) ------------------------------------------------------------
    def _unhashable_static_defaults(self, module: ModuleInfo
                                    ) -> List[Finding]:
        out: List[Finding] = []
        for fn in module.functions.values():
            if not fn.static_argnames:
                continue
            args = fn.node.args
            pos = args.posonlyargs + args.args
            defaults = [None] * (len(pos) - len(args.defaults)) \
                + list(args.defaults)
            pairs = list(zip(pos, defaults)) \
                + list(zip(args.kwonlyargs, args.kw_defaults))
            for param, default in pairs:
                if param.arg not in fn.static_argnames or default is None:
                    continue
                if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(default, ast.Call)
                        and call_name(default) in ("list", "dict", "set")):
                    out.append(self.finding(
                        module, default, fn.qualname,
                        f"static arg '{param.arg}' has an unhashable "
                        "mutable default — raises at call time and "
                        "defeats the jit cache"))
        return out

    # (c) ------------------------------------------------------------
    def _tracer_branches(self, module: ModuleInfo, package: PackageInfo
                         ) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.reachable_functions(module):
            traced = traced_names(fn, package)
            for node in fn.own_nodes():
                if isinstance(node, (ast.If, ast.While)) and \
                        _bool_context_traced(node.test, traced):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(self.finding(
                        module, node, fn.qualname,
                        f"Python `{kind}` on a traced value — "
                        "TracerBoolConversionError under trace (use "
                        "jnp.where/lax.cond), or a per-call host sync "
                        "and recompile hazard outside it"))
        return out

    # (d) ------------------------------------------------------------
    _ENTRY_RE = re.compile(r"predict|infer|serve", re.I)
    _BUCKET_RE = re.compile(r"bucket|pad|tile|shard", re.I)

    def _jit_callee(self, module: ModuleInfo, package: PackageInfo,
                    node: ast.Call) -> bool:
        """Does this call invoke a jit-compiled package function?"""
        name = call_name(node)
        if name is None:
            return False
        base = name.rsplit(".", 1)[-1]
        return any(f.jit_decorated
                   for f in package._callees(module, base))

    def _unbucketed_entry_shapes(self, module: ModuleInfo,
                                 package: PackageInfo) -> List[Finding]:
        out: List[Finding] = []
        for fn in module.functions.values():
            if fn.jit_decorated or not self._ENTRY_RE.search(fn.basename):
                continue
            # taint = values carrying the raw request size: the entry's
            # own parameters, plus locals derived from them — cleared by
            # assignment from a bucket/pad-named call
            tainted: Set[str] = {p for p in fn.pos_params + fn.kwonly_params
                                 if p not in ("self", "cls")}

            def clears(expr: ast.AST) -> bool:
                return any(isinstance(c, ast.Call)
                           and (call_name(c) or "")
                           and self._BUCKET_RE.search(call_name(c))
                           for c in ast.walk(expr))

            # own_nodes is DFS order; the taint walk needs SOURCE order so
            # a clearing assignment upstream of the call actually clears
            ordered = sorted(fn.own_nodes(),
                             key=lambda n: (getattr(n, "lineno", 0),
                                            getattr(n, "col_offset", 0)))
            for node in ordered:
                if isinstance(node, ast.Assign) and \
                        all(isinstance(t, ast.Name) for t in node.targets):
                    names = [t.id for t in node.targets]
                    if clears(node.value):
                        tainted.difference_update(names)
                    elif expr_references(node.value, tainted):
                        tainted.update(names)
                    else:
                        tainted.difference_update(names)
                elif isinstance(node, ast.Call) and \
                        self._jit_callee(module, package, node):
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        if expr_references(arg, tainted) and \
                                not clears(arg):
                            out.append(self.finding(
                                module, node, fn.qualname,
                                "jit entry fed request-derived data "
                                "without bucket padding — the compiled "
                                "program is keyed on the raw request "
                                "shape and every distinct batch size "
                                "recompiles; pad to a bucket ladder "
                                "first (ops/predict.py bucket_rows)"))
                            break
        return out

    # (e) ------------------------------------------------------------
    #: names/attributes that carry a raw tree-shape budget
    _LEAFDEPTH_RE = re.compile(
        r"num_leaves|max_leaves|num_leaf|leaf_count|max_depth", re.I)
    #: calls that map a raw budget onto the step ladder
    _RUNG_RE = re.compile(r"rung|bucket", re.I)
    #: jitted callables that are grower steps
    _STEP_CALLEE_RE = re.compile(r"step|grow", re.I)

    def _rung_clears(self, expr: ast.AST) -> bool:
        """Does ``expr`` contain a rung/bucket-named mapping call?"""
        return any(isinstance(c, ast.Call)
                   and (call_name(c) or "")
                   and self._RUNG_RE.search(call_name(c))
                   for c in ast.walk(expr))

    def _leafdepth_refs(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """Does ``expr`` reference a raw leaf-count/depth value?

        True for names in the taint set or matching the leaf/depth
        pattern, ``obj.max_depth``-style attributes, and config reads
        (``cfg.get("num_leaves", ...)``) — except inside a rung/bucket-
        named mapping call, whose result is a ladder key, not a raw
        budget."""
        def walk(n: ast.AST) -> bool:
            if isinstance(n, ast.Call):
                cname = call_name(n) or ""
                if cname and self._RUNG_RE.search(cname):
                    return False          # mapped: the subtree is clean
                if cname.rsplit(".", 1)[-1] == "get" and n.args and \
                        isinstance(n.args[0], ast.Constant) and \
                        isinstance(n.args[0].value, str) and \
                        self._LEAFDEPTH_RE.search(n.args[0].value):
                    return True
            if isinstance(n, ast.Name):
                return n.id in tainted \
                    or bool(self._LEAFDEPTH_RE.search(n.id))
            if isinstance(n, ast.Attribute) and \
                    self._LEAFDEPTH_RE.search(n.attr):
                return True
            return any(walk(c) for c in ast.iter_child_nodes(n))
        return walk(expr)

    def _unbucketed_step_keys(self, module: ModuleInfo,
                              package: PackageInfo) -> List[Finding]:
        out: List[Finding] = []
        for fn in module.functions.values():
            tainted: Set[str] = set()
            is_mapping_fn = bool(self._RUNG_RE.search(fn.basename))
            # SOURCE order, like sub-check (d): a rung-mapping assignment
            # upstream of the key construction actually clears
            ordered = sorted(fn.own_nodes(),
                             key=lambda n: (getattr(n, "lineno", 0),
                                            getattr(n, "col_offset", 0)))
            for node in ordered:
                if isinstance(node, ast.Assign):
                    names = [leaf for t in node.targets
                             for leaf in _plain_name_targets(t)]
                    if self._rung_clears(node.value):
                        tainted.difference_update(names)
                    elif self._leafdepth_refs(node.value, tainted):
                        tainted.update(names)
                    else:
                        tainted.difference_update(names)
                elif isinstance(node, ast.Return) and is_mapping_fn:
                    # a rung/bucket mapping passing the raw budget through
                    # IS the exact-keyed escape hatch — deliberate parity
                    # paths (tpu_step_buckets=off) carry an allowlist anchor
                    v = node.value
                    rets = [v] if isinstance(v, ast.Name) else \
                        [e for e in v.elts if isinstance(e, ast.Name)] \
                        if isinstance(v, ast.Tuple) else []
                    if any(e.id in tainted
                           or self._LEAFDEPTH_RE.search(e.id)
                           for e in rets):
                        out.append(self.finding(
                            module, node, fn.qualname,
                            "rung/bucket mapping returns the raw "
                            "leaf/depth budget — the exact-keyed escape "
                            "hatch compiles one step program per "
                            "(num_leaves, max_depth) pair; deliberate "
                            "parity paths (tpu_step_buckets=off) need an "
                            "allowlist anchor"))
                elif isinstance(node, ast.Call):
                    cname = call_name(node) or ""
                    base = cname.rsplit(".", 1)[-1]
                    if base == "GrowerParams" or (
                            "grower_params" in cname and base == "_replace"):
                        for kw in node.keywords:
                            if kw.arg in ("num_leaves", "max_depth") and \
                                    self._leafdepth_refs(kw.value, tainted):
                                out.append(self.finding(
                                    module, node, fn.qualname,
                                    f"grower-step jit key takes the raw "
                                    f"'{kw.arg}' — every (num_leaves, "
                                    "max_depth) pair lowers a fresh step "
                                    "program; map it through the bucketed "
                                    "ladder first (ops/grower.py "
                                    "leaf_rung/depth_rung, "
                                    "gbdt.bucketed_tree_shape)"))
                                break
                    elif self._STEP_CALLEE_RE.search(base) and \
                            any(f.jit_decorated
                                for f in package._callees(module, base)):
                        for arg in list(node.args) + \
                                [kw.value for kw in node.keywords]:
                            if self._leafdepth_refs(arg, tainted) and \
                                    not self._rung_clears(arg):
                                out.append(self.finding(
                                    module, node, fn.qualname,
                                    "jitted grower step fed a raw "
                                    "leaf/depth budget — the compiled "
                                    "program is keyed on the exact tree "
                                    "shape and every budget recompiles; "
                                    "key on the rung and pass the budget "
                                    "as a traced scalar (ops/grower.py "
                                    "leaf_rung/depth_rung)"))
                                break
        return out
