"""R011 — concurrency flight check (lock order & blocking under locks).

Thin rule adapter over :mod:`lightgbm_tpu.analysis.locks`: the whole
package is analyzed once (cached on the ``PackageInfo``, like the R008
serving closure), then each module's ``check`` returns the slice of
findings anchored in that module. See locks.py for the model: discovered
locks, held-set traversal, interprocedural acquisition/blocking facts
with witness chains, and the four finding classes (order cycles,
blocking-under-lock, read->write upgrades, cv-wait-outside-loop).
"""
from __future__ import annotations

from typing import List

from ..locks import analyze_package
from .base import Finding, ModuleInfo, PackageInfo, Rule


class LockOrderRule(Rule):
    code = "R011"
    title = "lock-order & blocking-call concurrency flight check"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        analysis = analyze_package(package)
        return [f for f in analysis.findings if f.path == module.path]
