"""R006 — shard_map/collective axis-name consistency.

Two hazards around the mesh boundary:

* ``lax.psum(x, "axis")`` (and psum_scatter / all_gather / ppermute /
  axis_index / pmean / pmax / pmin) with an axis name that no mesh in the
  package declares: under ``shard_map`` this is a NameError at trace time
  on the multi-chip path only — the serial CPU tests never execute it, so
  a typo ships. The rule resolves names through module constants and
  package-relative imports (``DATA_AXIS`` in parallel/mesh.py), and skips
  dynamic expressions (``gp.axis_name``).

* host readback of a sharded value without a gather:
  ``np.asarray(x)`` / ``float(x)`` on an array that was explicitly
  ``jax.device_put`` with a non-replicated sharding reads back only via
  an implicit cross-device gather — on multi-host meshes the array is
  not fully addressable and this RAISES; on single-host it hides the
  gather cost inside numpy. The gather must be explicit
  (``jax.device_get`` / ``multihost.to_host`` / ``process_allgather``)
  so it is visible and portable.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name, string_constants)

#: collective/axis primitives whose axis argument must name a mesh axis
_AXIS_CALLS = {"psum", "psum_scatter", "pmean", "pmax", "pmin",
               "all_gather", "ppermute", "pshuffle", "axis_index",
               "axis_size", "pbroadcast"}
#: the axis argument position (after the value operand(s))
_AXIS_ARG_POS = {"axis_index": 0, "axis_size": 0}

#: calls whose string arguments declare mesh axis names
_DECL_CALLS = {"Mesh", "make_mesh", "PartitionSpec", "P", "NamedSharding",
               "AxisType"}

#: readback funnels for the sharded-value sub-check
_READBACK = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "np.ascontiguousarray", "np.asanyarray", "float", "int",
             "memoryview"}
#: an explicit gather: reassigning through these clears the taint
_GATHERS = {"jax.device_get", "device_get", "to_host", "process_allgather"}


def _module_str_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


class AxisNameRule(Rule):
    code = "R006"
    title = "shard_map/collective axis-name consistency"

    def __init__(self):
        # the vocabulary depends only on the package; check() runs once
        # per module, so cache it or the pass walks every AST per module
        self._vocab_for: Optional[int] = None
        self._vocab: Set[str] = set()

    # -- axis vocabulary ----------------------------------------------------
    def _vocabulary(self, package: PackageInfo) -> Set[str]:
        if self._vocab_for == id(package):
            return self._vocab
        vocab: Set[str] = set()
        for m in package.modules:
            consts = _module_str_constants(m.tree)
            for name, value in consts.items():
                if "AXIS" in name.upper():
                    vocab.add(value)
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                cname = (call_name(node) or "").rsplit(".", 1)[-1]
                if cname in _DECL_CALLS:
                    vocab.update(string_constants(node))
                    for ref in ast.walk(node):
                        if isinstance(ref, ast.Name) and ref.id in consts:
                            vocab.add(consts[ref.id])
        self._vocab_for = id(package)
        self._vocab = vocab
        return vocab

    def _resolve_axis(self, expr: ast.AST, module: ModuleInfo,
                      package: PackageInfo) -> List[Optional[str]]:
        """Axis-name strings an expression denotes; [None] = dynamic."""
        if isinstance(expr, ast.Constant):
            return [expr.value] if isinstance(expr.value, str) else [None]
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[Optional[str]] = []
            for el in expr.elts:
                out.extend(self._resolve_axis(el, module, package))
            return out
        if isinstance(expr, ast.Name):
            local = _module_str_constants(module.tree)
            if expr.id in local:
                return [local[expr.id]]
            if expr.id in module.imports:
                mod_name, symbol = module.imports[expr.id]
                target = package.by_dotted.get(mod_name)
                if target is not None and symbol is not None:
                    remote = _module_str_constants(target.tree)
                    if symbol in remote:
                        return [remote[symbol]]
        return [None]   # attribute access / call result: dynamic, skip

    def _check_axis_names(self, module: ModuleInfo, package: PackageInfo,
                          vocab: Set[str], func_of) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node) or ""
            base = cname.rsplit(".", 1)[-1]
            if base not in _AXIS_CALLS:
                continue
            pos = _AXIS_ARG_POS.get(base, 1)
            axis_expr = None
            for kw in node.keywords:
                # only the axis NAME keyword — `axis=`/`scatter_dimension=`
                # on all_gather/psum_scatter is an integer dimension, and
                # matching it would mask a typo'd positional axis name
                if kw.arg == "axis_name":
                    axis_expr = kw.value
            if axis_expr is None and len(node.args) > pos:
                axis_expr = node.args[pos]
            if axis_expr is None:
                continue
            for axis in self._resolve_axis(axis_expr, module, package):
                if axis is not None and axis not in vocab:
                    out.append(self.finding(
                        module, node, func_of(node),
                        f"{base}() over axis '{axis}', but no mesh in the "
                        f"package declares it (known axes: "
                        f"{sorted(vocab) or 'none'}) — trace-time NameError "
                        "on the multi-chip path only"))
        return out

    # -- sharded readback ---------------------------------------------------
    def _check_sharded_readback(self, module: ModuleInfo,
                                func_of) -> List[Finding]:
        out: List[Finding] = []
        for fn in module.functions.values():
            # replay the function in source order: a sharded device_put
            # taints its target name, any other reassignment (e.g. through
            # jax.device_get) clears it, a readback call on a tainted name
            # is the finding
            events = []                    # (lineno, kind, payload)
            for n in fn.own_nodes():
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    events.append((n.lineno, "assign", n))
                elif isinstance(n, ast.Call) and call_name(n) in _READBACK \
                        and n.args and isinstance(n.args[0], ast.Name):
                    events.append((n.lineno, "read", n))
            sharded: Dict[str, int] = {}
            # at equal lines, reads run before the assignment they feed
            # (`v = np.asarray(v)` reads the sharded v)
            for _, kind, n in sorted(events,
                                     key=lambda e: (e[0], e[1] != "read")):
                if kind == "assign":
                    tgt = n.targets[0].id
                    if self._is_sharded_put(n.value):
                        sharded[tgt] = n.lineno
                    else:
                        sharded.pop(tgt, None)
                elif n.args[0].id in sharded:
                    out.append(self.finding(
                        module, n, fn.qualname,
                        f"{call_name(n)}() reads back '{n.args[0].id}', "
                        "which was device_put with a non-replicated "
                        "sharding — on a multi-host mesh the array is not "
                        "fully addressable and this raises; gather "
                        "explicitly (jax.device_get / multihost.to_host / "
                        "process_allgather) first"))
        return out

    @staticmethod
    def _is_sharded_put(value: ast.AST) -> bool:
        if not (isinstance(value, ast.Call)
                and (call_name(value) or "").endswith("device_put")
                and len(value.args) >= 2):
            return False
        spec = value.args[1]
        for n in ast.walk(spec):
            if not isinstance(n, ast.Call):
                continue
            base = (call_name(n) or "").rsplit(".", 1)[-1].lower()
            if "sharding" not in base or "replicat" in base:
                continue
            if base == "namedsharding":
                # NamedSharding(mesh, P()) with an axis-free spec is fully
                # replicated — the documented-safe readback case
                pspec = next(
                    (c for c in ast.walk(n) if c is not n
                     and isinstance(c, ast.Call)
                     and (call_name(c) or "").rsplit(".", 1)[-1]
                     in ("P", "PartitionSpec")), None)
                if pspec is not None and not pspec.keywords and all(
                        isinstance(a, ast.Constant) and a.value is None
                        for a in pspec.args):
                    continue
            return True
        return False

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        func_of = module.func_of
        vocab = self._vocabulary(package)
        # no mesh declared anywhere in the linted set (e.g. a single-file
        # lint of a helper module): axis names can't be validated — only
        # the readback sub-check applies
        axis = (self._check_axis_names(module, package, vocab, func_of)
                if vocab else [])
        return axis + self._check_sharded_readback(module, func_of)
