"""R009 — host-clock timing around async device dispatch.

jax dispatch is asynchronous: a jitted call returns as soon as the work is
enqueued, so ``t1 - t0`` around it measures DISPATCH, not device time —
off by orders of magnitude, silently. The honest options are (a) time at
a declared tick site where the host genuinely blocks (a flush, a
materializing ``np.asarray``, an explicit ``block_until_ready``), or
(b) let the profiler do it (obs/spans.py: phase-named device traces under
``tpu_trace_dir``).

Two checks:

* **(a) timing in jit-reachable code**: any host-clock read
  (``time.time``/``perf_counter``/``monotonic``/``process_time``/
  ``timeit.default_timer``, alias-aware) inside a jit-reachable function
  is a finding — under trace it bakes a trace-time constant; between
  dispatches it lies. So is the manual span-close pattern
  (``s = span(...)`` then ``s.stop()``/``.close()``/``.__exit__()``):
  obs spans in traced code must be ``with``-scoped named scopes, never
  hand-timed.
* **(b) tick-site pinning** (any function, reachable or not): a function
  that reads a host clock AND dispatches device work (a call whose name
  contains ``step``/``train``/``predict``/``serve``/``grow``) without
  ``block_until_ready`` in the same body is timing async dispatch. The
  declared tick sites — ``Booster.update``'s metrics tick,
  ``warm_predict_ladder``'s warmup stats, and the sampled
  collective-wait timer (``obs/ranks.py``), all of which knowingly
  measure the host loop — carry allowlist anchors; a new unreviewed
  timing site fails tier-1 until justified.
* **(c) trace analytics off the hot path**: ``obs/tracing.py`` parses
  profiler artifacts — a pure post-run analysis. Importing it (module-
  or function-level) anywhere a jit-reachable function lives puts a
  protobuf walk within reach of the training hot path; the analytics
  must stay in post-run code (engine's post-session emit, scripts/obs,
  bench's ledger step).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .base import (Finding, ModuleInfo, PackageInfo, Rule, call_name,
                   dotted_name)

#: host-clock reads (module attr names); time.sleep is NOT a clock read
_TIME_ATTRS = {"time", "perf_counter", "perf_counter_ns", "monotonic",
               "monotonic_ns", "process_time", "process_time_ns"}
_TIMEIT_ATTRS = {"default_timer"}

#: call-name fragments that mean "this dispatches device work here"
_DISPATCHY = ("step", "train", "predict", "serve", "grow")

#: manual span-close spellings (the with-statement form never matches)
_SPAN_CLOSERS = {"stop", "end", "close", "__exit__"}

#: blocking materializers that make host timing honest in the same body
_BLOCKERS = {"block_until_ready"}

#: trace-parse analytics modules that must stay off the hot path (c)
_TRACE_MODULES = ("lightgbm_tpu.obs.tracing",)


def _is_trace_import(mod: str, sym: Optional[str]) -> bool:
    """Does an import entry resolve to the trace-analytics module?
    Covers ``import lightgbm_tpu.obs.tracing``, ``from
    lightgbm_tpu.obs import tracing``, relative ``from ..obs import
    tracing`` (resolved), and ``from ..obs.tracing import X``."""
    if mod in _TRACE_MODULES or mod.endswith(".obs.tracing"):
        return True
    return sym == "tracing" and (mod == "obs" or mod.endswith(".obs"))


def _is_clock_call(module: ModuleInfo, node: ast.Call) -> Optional[str]:
    """The canonical clock name for a Call node, or None."""
    name = call_name(node)
    if name is None:
        return None
    if "." in name:
        head, _, attr = name.partition(".")
        if "." in attr:
            return None
        target = module.imports.get(head)
        if target is None and head in ("time", "timeit"):
            target = (head, None)
        if target is None or target[1] is not None:
            return None
        mod = target[0]
        if mod == "time" and attr in _TIME_ATTRS:
            return f"time.{attr}"
        if mod == "timeit" and attr in _TIMEIT_ATTRS:
            return f"timeit.{attr}"
        return None
    target = module.imports.get(name)
    if target is None:
        return None
    mod, sym = target
    if mod == "time" and sym in _TIME_ATTRS:
        return f"time.{sym}"
    if mod == "timeit" and sym in _TIMEIT_ATTRS:
        return f"timeit.{sym}"
    return None


def _span_locals(fn) -> Set[str]:
    """Local names assigned from a ``span(...)`` call."""
    out: Set[str] = set()
    for n in fn.own_nodes():
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            cname = call_name(n.value)
            if cname and cname.rsplit(".", 1)[-1] == "span":
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _dispatchy_call(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name is None:
        return None
    base = name.rsplit(".", 1)[-1].lower()
    if any(frag in base for frag in _DISPATCHY):
        return name
    return None


class TimingRule(Rule):
    code = "R009"
    title = "host-clock timing around async dispatch"

    def check(self, module: ModuleInfo, package: PackageInfo
              ) -> List[Finding]:
        out: List[Finding] = []
        reachable = {id(f) for f in package.reachable_functions(module)}
        # (c) trace-parse analytics imported into a module that contains
        # jit-reachable code: the xplane walk must stay post-run
        if reachable and not (module.dotted or "").endswith("obs.tracing"):
            for node in ast.walk(module.tree):
                names = ()
                if isinstance(node, ast.Import):
                    names = [(a.name, None) for a in node.names]
                elif isinstance(node, ast.ImportFrom):
                    mod = module._resolve_relative(node.module, node.level)
                    names = [(mod, a.name) for a in node.names]
                for mod, sym in names:
                    if sym is not None and sym.isupper():
                        # an ALL-CAPS constant (SPAN_TAXONOMY) is shared
                        # vocabulary, not parse machinery
                        continue
                    if _is_trace_import(mod, sym):
                        out.append(self.finding(
                            module, node, module.func_of(node),
                            "trace-parse analytics (obs.tracing) "
                            "imported into a module with jit-reachable "
                            "code: artifact parsing is post-run only — "
                            "move the import to the post-session emit "
                            "path (engine), scripts/obs, or the bench "
                            "ledger step"))
        for fn in module.functions.values():
            jit_reachable = id(fn) in reachable
            spans = _span_locals(fn)
            clock_node = None
            clock_name = None
            dispatch_name = None
            blocked = False
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                cname = _is_clock_call(module, node)
                if cname is not None:
                    if clock_node is None:
                        clock_node, clock_name = node, cname
                    if jit_reachable:
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"{cname}() in jit-reachable code: async "
                            "dispatch makes host timing a lie (and under "
                            "trace it bakes a constant); time at a "
                            "declared tick site or use obs/spans device "
                            "traces (tpu_trace_dir)"))
                    continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SPAN_CLOSERS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in spans:
                    if jit_reachable:
                        out.append(self.finding(
                            module, node, fn.qualname,
                            f"manual span close "
                            f"(.{node.func.attr}() on a span(...) local) "
                            "in jit-reachable code: spans under trace "
                            "must be with-scoped named scopes; host "
                            "timing here measures dispatch, not device "
                            "work"))
                    continue
                name = call_name(node)
                if name is not None and \
                        name.rsplit(".", 1)[-1] in _BLOCKERS:
                    blocked = True
                    continue
                if dispatch_name is None:
                    dispatch_name = _dispatchy_call(node)
            # (b) tick-site pinning: clock + dispatch, no blocker
            if not jit_reachable and clock_node is not None \
                    and dispatch_name is not None and not blocked:
                out.append(self.finding(
                    module, clock_node, fn.qualname,
                    f"{clock_name}() times around {dispatch_name}() "
                    "without block_until_ready: async dispatch makes the "
                    "measurement a lie. Declared tick sites (the "
                    "Booster.update metrics tick, warm_predict_ladder) "
                    "carry allowlist anchors; block, or move the timing "
                    "to a tick site / the device trace"))
        return out
