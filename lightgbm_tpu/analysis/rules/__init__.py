"""tpulint rule registry. Each rule encodes one class of repo-specific
hazard; see the individual modules for the rationale and seed cases."""
from .base import Finding, ModuleInfo, PackageInfo, Rule
from .r001_host_sync import HostSyncRule
from .r002_recompile import RecompileRule
from .r003_dtype import DtypeDriftRule
from .r004_pallas import PallasContractRule
from .r005_collectives import CollectiveAccountingRule
from .r006_axis import AxisNameRule
from .r007_api_race import ApiRaceRule
from .r008_serving import ServingContractRule
from .r009_timing import TimingRule
from .r010_divergence import CollectiveDivergenceRule
from .r011_locks import LockOrderRule
from .r012_resources import ResourceLifecycleRule

ALL_RULES = (HostSyncRule, RecompileRule, DtypeDriftRule,
             PallasContractRule, CollectiveAccountingRule,
             AxisNameRule, ApiRaceRule, ServingContractRule, TimingRule,
             CollectiveDivergenceRule, LockOrderRule,
             ResourceLifecycleRule)

__all__ = ["Finding", "ModuleInfo", "PackageInfo", "Rule", "ALL_RULES",
           "HostSyncRule", "RecompileRule", "DtypeDriftRule",
           "PallasContractRule", "CollectiveAccountingRule",
           "AxisNameRule", "ApiRaceRule", "ServingContractRule",
           "TimingRule", "CollectiveDivergenceRule", "LockOrderRule",
           "ResourceLifecycleRule"]
