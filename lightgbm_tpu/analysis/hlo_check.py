"""hlo_check — post-lowering contract verification of the step programs.

PR 1's tpulint checks hazard *patterns* in Python source; the runtime
guards check *behavior* counters. This pass closes the remaining gap: the
claims the repo makes about its COMPILED programs — which collectives a
learner mode is allowed to emit (reduce-scatter, not a full-histogram
all-reduce, when ``tpu_hist_scatter`` is on), that the jitted step moves
zero bytes between host and device, that every integer histogram
contraction carries ``preferred_element_type=int32`` (an s8 dot that
keeps an s8 accumulator silently wraps at ±127), and that the program
stays byte-for-byte stable across iterations (recompile detection at the
HLO level, not just the event counter) — were previously asserted by
hand-read HLO. Here they are **contract files**
(``analysis/contracts/*.json``), one per learner mode, verified
mechanically against the lowered text on any backend (the tier-1 gate
runs on CPU; the same programs are what dryrun_multichip records into
COMM_ACCOUNTING.json).

Contract schema (one JSON object per mode)::

    {
      "mode": "data_scatter",
      "description": "...",
      "params":  {...},          # Booster params reproducing the program
      "num_devices": 8,          # mesh size the program was lowered for
      "program": "compact_step_k0",   # key in GBDT._comm_hlo
      "collectives": {
        "allow":   ["reduce-scatter", "all-gather", "all-reduce"],
        "require": ["reduce-scatter"],
        "max_bytes": {"all-reduce": 16, ...}   # per-kind byte budgets
      },
      "forbid_host_ops": true,   # no infeed/outfeed/send/recv/callbacks
      "int_dot_s32": true,       # narrow-int dots must accumulate in s32
      "require_integer_dot": false,  # quant mode: the int path must be live
      "stable_fingerprint": true,
      "measured": {...},         # collective_bytes() at generation time —
                                 #   scripts/verify_contracts.py diffs this
      "measured_baseline": {...},# overlap modes only: the overlap=off
                                 #   lowering's accounting — every kind's
                                 #   bytes must MATCH "measured" (overlap
                                 #   hides latency, never adds traffic)
      "memory": {                # per-mesh static per-chip HBM budget
        "8": {"budget_bytes": ..., "estimate_bytes": ...,   # (ISSUE 15;
              "headroom_bytes": ..., ...}},                 # memory.py walk)
      "spmd": {                  # per-mesh collective inventory+schedule
        "4": {"collectives": [...],          # recorded by scripts/tpulint
              "schedule": [[kind, B], ...]}} # spmd --update (spmd_check.py)
    }

The harness half (``capture_mode``) trains a tiny Booster with
``LGBM_TPU_COMM_ACCOUNTING=1`` so ``boosting/gbdt.py`` records the
compiled step text (and re-lowers on any argument-signature change —
``_comm_hlo_history``); it imports jax lazily so the checking half stays
importable from ``scripts/tpulint``'s backend-free stub.

CLI: ``scripts/tpulint hlo [--update] [mode ...]``; tier-1 runs the same
gate in tests/test_hlo_check.py.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence

from . import memory
from .hlo import (HOST_CUSTOM_CALL_MARKERS, HOST_OPS, INT_NARROW,
                  collective_bytes, fingerprint, parse_instructions)

_CUSTOM_CALL_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')

CONTRACTS_DIR = os.path.join(os.path.dirname(__file__), "contracts")

#: integer element types an MXU-friendly accumulator may use
_INT_ACCUM = ("s32", "s64", "u32", "u64")
_INT_ALL = INT_NARROW + _INT_ACCUM


@dataclasses.dataclass(frozen=True)
class ContractFinding:
    contract: str
    check: str        # collectives | host-ops | int-dot | fingerprint | ...
    message: str

    def render(self) -> str:
        return f"[{self.contract}] {self.check}: {self.message}"


# ---------------------------------------------------------------------------
# mode templates: the static half of each contract. `params` must rebuild the
# exact steady-state step program; measured budgets are filled by --update.
# ---------------------------------------------------------------------------
_BASE = {"objective": "binary", "num_leaves": 7, "max_bin": 15,
         "min_data_in_leaf": 2, "verbosity": -1}

MODE_TEMPLATES: Dict[str, dict] = {
    "serial_compact": {
        "description": "single-chip compact grower: a pure on-device step "
                       "— no collectives, no host traffic",
        "params": dict(_BASE, tpu_grower="compact"),
        "num_devices": 1,
        "program": "compact_step_k0",
        "require": [],
        "require_integer_dot": False,
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
    "data_scatter": {
        "description": "data-parallel compact grower with the feature-axis "
                       "reduce-scatter histogram reduction "
                       "(tpu_hist_scatter): the full-histogram all-reduce "
                       "is budgeted down to the best-split sync bytes",
        "params": dict(_BASE, tpu_grower="compact", tree_learner="data",
                       tpu_hist_scatter="on"),
        "num_devices": 8,
        "program": "compact_step_k0",
        "require": ["reduce-scatter"],
        "require_integer_dot": False,
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
    "voting": {
        "description": "voting-parallel learner (PV-Tree): top-k elected "
                       "histograms reduce, so collective bytes stay far "
                       "below the full-F data-parallel exchange",
        "params": dict(_BASE, tree_learner="voting", top_k=2),
        "num_devices": 8,
        "program": "step",
        "require": ["all-reduce"],
        "require_integer_dot": False,
        "problem": {"n": 509, "f": 64, "seed": 1},
    },
    "quant_int8": {
        "description": "quantized-gradient int8 histogram pipeline: every "
                       "narrow-int contraction must accumulate in int32 "
                       "(preferred_element_type) and the integer dot path "
                       "must actually be live",
        "params": dict(_BASE, tpu_grower="compact", use_quantized_grad=True,
                       num_grad_quant_bins=16, quant_train_renew_leaf=True),
        "num_devices": 1,
        "program": "compact_step_k0",
        "require": [],
        "require_integer_dot": True,
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
    # -- engine-registry entry contracts (engines/registry.py) ----------
    # One contract per non-exempt registry entry, the entry id in the
    # filename (registry_contract_findings enumerates the coverage):
    # a new engine entry cannot land without either a contract here or
    # a justified contract_exempt on the entry. xla_lane pins the
    # registry's fully-concretized serial program — every engine knob
    # explicit (no "auto" left for the trace-time dispatch), autotune
    # off — so a drift in how the registry threads its resolution into
    # GrowerParams shows up as contract drift, not just a perf change.
    "xla_lane": {
        "description": "engine-registry entry xla_lane: the chunked "
                       "one-hot einsum engine with every knob "
                       "concretized through registry.resolve "
                       "(tpu_hist_impl=xla, lane layout, batched-M 8, "
                       "tpu_autotune=off) on the serial compact step — "
                       "no collectives, no host traffic",
        "params": dict(_BASE, tpu_grower="compact", tpu_hist_impl="xla",
                       tpu_hist_layout="lane", tpu_hist_mbatch=8,
                       tpu_autotune="off"),
        "num_devices": 1,
        "program": "compact_step_k0",
        "require": [],
        "require_integer_dot": False,
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
    # -- async histogram-collective overlap (tpu_hist_overlap) ----------
    # The overlap modes carry a ``baseline_params`` override: --update
    # captures the overlap=off program too and records its accounting as
    # ``measured_baseline``; check_overlap_parity then fails the gate if
    # ANY collective kind moves different bytes with overlap on — overlap
    # hides latency, it never adds traffic (only the collective COUNT may
    # grow: one reduce per feature group instead of one for the slab).
    # ``async_twins`` admits the corresponding ``-start`` ops with the
    # same byte budgets: the CPU backend lowers the group collectives
    # synchronously (measured start-bytes 0), an async backend splits
    # each into a -start/-done pair that overlaps the next group's
    # contraction — the same schedule freedom the grouping exists for.
    "data_scatter_overlap": {
        "description": "data-parallel compact grower, reduce-scatter "
                       "histograms, tpu_hist_overlap=on: the owned "
                       "feature slice reduces in 2 groups, each group's "
                       "collective issued while the next group still "
                       "contracts — byte budgets identical to the "
                       "single-collective baseline, only the count grows",
        "params": dict(_BASE, tpu_grower="compact", tree_learner="data",
                       tpu_hist_scatter="on", tpu_hist_overlap="on"),
        "baseline_params": {"tpu_hist_overlap": "off"},
        "num_devices": 8,
        "program": "compact_step_k0",
        "require": ["reduce-scatter"],
        "require_integer_dot": False,
        "async_twins": True,
        # 16 features / 8 shards = 2 owned columns per shard — the
        # smallest problem where the 2-group split is live
        "problem": {"n": 509, "f": 16, "seed": 0},
    },
    "voting_overlap": {
        "description": "voting-parallel learner, tpu_hist_overlap=on: the "
                       "2k elected histograms reduce in 2 groups, one "
                       "cross-shard all-reduce per group pipelined under "
                       "the next group's gather — same elected bytes as "
                       "the single all-reduce baseline",
        "params": dict(_BASE, tree_learner="voting", top_k=2,
                       tpu_hist_overlap="on"),
        "baseline_params": {"tpu_hist_overlap": "off"},
        "num_devices": 8,
        "program": "step",
        "require": ["all-reduce"],
        "require_integer_dot": False,
        "async_twins": True,
        "problem": {"n": 509, "f": 64, "seed": 1},
    },
}

MODES = tuple(MODE_TEMPLATES)

# ---------------------------------------------------------------------------
# serving-engine contracts (engines/registry.SERVING_ENTRIES): the predict
# program each serving engine compiles, lowered AOT at a ladder rung
# (GBDT.aot_lower_serving) instead of comm-captured from a training step.
# One file per non-exempt serving entry, the entry id in the filename —
# registry_contract_findings enumerates the coverage exactly like the
# histogram entries. serve_qleaf is exempt: it shares these two programs'
# shapes (only the leaf-slab dtype narrows) and is pinned by its RECORDED
# error bound + tests/test_level_engine.py instead.
# ---------------------------------------------------------------------------
_SERVE_BASE = dict(_BASE, tpu_autotune="off", max_depth=5)

SERVING_TEMPLATES: Dict[str, dict] = {
    "serve_walk": {
        "description": "serving engine serve_walk: the depth-batched "
                       "pointer walk (predict_raw_batched) at the "
                       "smallest ladder rung — per depth step one packed "
                       "node-record gather + one bin gather, no "
                       "collectives, no host traffic",
        "engine": "walk",
        "params": dict(_SERVE_BASE, tpu_predict_engine="walk"),
        "program": "predict_raw_batched",
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
    "serve_level": {
        "description": "serving engine serve_level: the level-order heap "
                       "relayout (predict_raw_level) at the smallest "
                       "ladder rung — depth step d reads the contiguous "
                       "[Tb, 2^d] slab of the complete-binary-heap "
                       "records, unrolled over the exact tree depth",
        "engine": "level",
        "params": dict(_SERVE_BASE, tpu_predict_engine="level"),
        "program": "predict_raw_level",
        "problem": {"n": 509, "f": 8, "seed": 0},
    },
}

SERVING_MODES = tuple(SERVING_TEMPLATES)


def contract_path(mode: str) -> str:
    return os.path.join(CONTRACTS_DIR, f"{mode}.json")


def load_contract(mode: str) -> dict:
    with open(contract_path(mode)) as fh:
        return json.load(fh)


# The XLA memory estimate for the same program differs across XLA builds
# and host layouts (padding/fusion decisions shift estimate_bytes by
# ~30%), so the drift fingerprint keeps only the *contracted* quantities
# — the sticky budget and the exact argument/output byte counts — and
# drops the estimate-derived fields. check_memory still enforces
# estimate <= budget against the LIVE lowering, so a real regression
# fails the gate; it just no longer fails tier-1 on a host change.
_MEM_ESTIMATE_KEYS = ("estimate_bytes", "headroom_bytes")


def drift_fingerprint(contract: dict) -> dict:
    """A copy of ``contract`` with host-dependent memory-estimate fields
    normalized out, for byte-exact drift comparison."""
    out = dict(contract)
    mem = contract.get("memory")
    if isinstance(mem, dict):
        out["memory"] = {
            nd: {k: v for k, v in blk.items()
                 if k not in _MEM_ESTIMATE_KEYS}
            if isinstance(blk, dict) else blk
            for nd, blk in mem.items()}
    return out


# ---------------------------------------------------------------------------
# checking half (pure text; no jax)
# ---------------------------------------------------------------------------
def check_collectives(hlo_text: str, contract: dict) -> List[ContractFinding]:
    name = contract["mode"]
    spec = contract.get("collectives", {})
    allow = set(spec.get("allow", []))
    require = set(spec.get("require", []))
    budgets = spec.get("max_bytes", {})
    acct = collective_bytes(hlo_text)
    out: List[ContractFinding] = []
    observed = {k: v for k, v in acct.items()
                if k not in ("total", "count") and v > 0}
    for kind, nbytes in sorted(observed.items()):
        if kind not in allow:
            out.append(ContractFinding(
                name, "collectives",
                f"forbidden collective '{kind}' ({nbytes} B) in the step "
                f"program — allowed inventory: {sorted(allow) or 'none'}. "
                "If the learner's comm protocol deliberately changed, "
                "regenerate contracts (scripts/verify_contracts.py "
                "--update) and justify in the PR"))
        elif kind in budgets and nbytes > budgets[kind]:
            out.append(ContractFinding(
                name, "collectives",
                f"'{kind}' moves {nbytes} B > budget {budgets[kind]} B — "
                "e.g. a histogram all-reduce reappearing next to the "
                "reduce-scatter path doubles cross-chip traffic silently"))
    for kind in sorted(require - set(observed)):
        out.append(ContractFinding(
            name, "collectives",
            f"required collective '{kind}' is missing — the mode's "
            "comm-reduction claim (README/COMM_ACCOUNTING.json) no longer "
            "holds for this program"))
    return out


def check_host_ops(hlo_text: str, contract: dict) -> List[ContractFinding]:
    if not contract.get("forbid_host_ops", True):
        return []
    name = contract["mode"]
    out: List[ContractFinding] = []
    for instr in parse_instructions(hlo_text):
        if instr.opcode in HOST_OPS:
            out.append(ContractFinding(
                name, "host-ops",
                f"'{instr.opcode}' at HLO line {instr.line}: the jitted "
                "step must keep a 0-d2h steady state — host traffic here "
                "serializes every iteration on the transfer"))
        elif instr.opcode == "custom-call":
            # match the TARGET only — the raw line also carries metadata
            # like source_file=".../site-packages/jax/..." whose 'python'
            # substring would false-positive on every benign custom-call
            m = _CUSTOM_CALL_TARGET_RE.search(instr.raw)
            target = (m.group(1) if m else "").lower()
            if any(marker in target for marker in HOST_CUSTOM_CALL_MARKERS):
                out.append(ContractFinding(
                    name, "host-ops",
                    f"host-callback custom-call '{target}' at HLO line "
                    f"{instr.line}: a Python callback inside the step "
                    "program round-trips to the host every iteration"))
    return out


def check_int_dots(hlo_text: str, contract: dict) -> List[ContractFinding]:
    name = contract["mode"]
    out: List[ContractFinding] = []
    saw_integer_dot = False
    for instr in parse_instructions(hlo_text):
        if instr.opcode != "dot":
            continue
        op_dtypes = [d for d, _ in instr.operand_shapes]
        res_dtypes = [d for d, _ in instr.result_shapes]
        if op_dtypes and all(d in _INT_ALL for d in op_dtypes) \
                and all(d in _INT_ACCUM for d in res_dtypes):
            saw_integer_dot = True
        if contract.get("int_dot_s32", True):
            narrow = [d for d in op_dtypes + res_dtypes if d in INT_NARROW]
            if narrow and not all(d in _INT_ACCUM for d in res_dtypes):
                out.append(ContractFinding(
                    name, "int-dot",
                    f"dot at HLO line {instr.line} contracts "
                    f"{'/'.join(op_dtypes)} into {'/'.join(res_dtypes)} — "
                    "an int8/int16 matmul without "
                    "preferred_element_type=int32 wraps its sums at the "
                    "narrow-type bound (ops/histogram.py contract)"))
    if contract.get("require_integer_dot") and not saw_integer_dot:
        out.append(ContractFinding(
            name, "int-dot",
            "no integer-accumulating dot found — the quantized int8 "
            "histogram path is not live in this program (fell back to the "
            "dequantized f32 shim?)"))
    return out


def check_overlap_parity(contract: dict,
                         measured: Optional[dict] = None
                         ) -> List[ContractFinding]:
    """Overlap never adds traffic: with ``measured_baseline`` present
    (the overlap=off lowering of the same mode), every collective kind
    must move exactly the bytes the baseline moves — grouping a
    histogram reduce splits ONE collective into N, it must not grow,
    shrink, or re-route what crosses the links. The collective COUNT is
    exempt (one reduce per feature group IS the mechanism).

    ``measured`` is the LIVE capture's accounting (verify_mode passes
    it); without it the check degrades to diffing the two stored fields
    of the checked-in contract, which cannot see current-lowering
    drift."""
    base = contract.get("measured_baseline")
    if not base:
        return []
    name = contract["mode"]
    cur = measured if measured is not None \
        else contract.get("measured", {})
    out: List[ContractFinding] = []
    # "total" is the sum of the kinds — diffing it too would report every
    # drift twice
    for kind in sorted((set(base) | set(cur)) - {"count", "total"}):
        if cur.get(kind, 0) != base.get(kind, 0):
            out.append(ContractFinding(
                name, "overlap-bytes",
                f"'{kind}' moves {cur.get(kind, 0)} B with overlap on vs "
                f"{base.get(kind, 0)} B in the overlap=off baseline — "
                "tpu_hist_overlap must hide collective latency without "
                "changing collective traffic (same addends per element, "
                "same bytes per link)"))
    return out


def check_fingerprint(history: Sequence[str],
                      contract: dict) -> List[ContractFinding]:
    name = contract["mode"]
    if not contract.get("stable_fingerprint", True) or len(history) <= 1:
        return []
    prints = [fingerprint(t) for t in history]
    detail = ("identical program re-lowered (argument signature changed)"
              if len(set(prints)) == 1 else
              f"program CHANGED across lowerings: {prints}")
    return [ContractFinding(
        name, "fingerprint",
        f"step program was lowered {len(history)} times during the "
        f"steady-state run — {detail}. A stable step must compile once; "
        "a shape/dtype/static-arg flip after warmup recompiles every "
        "change (guards.compile_counter sees the event, this names the "
        "program)")]


def check_memory(hlo_text: str, contract: dict) -> List[ContractFinding]:
    """Native-mesh memory budget: the contract's ``memory`` block (ISSUE
    15) records a per-chip peak-HBM budget + estimate per mesh key; the
    mode's own lowering is checked against its native mesh here (the
    flight meshes are spmd_check's job). An estimate above budget is a
    memory regression; budgets only move by deliberate edit."""
    name = contract["mode"]
    key = str(contract.get("num_devices", 1))
    block = contract.get("memory", {}).get(key)
    if not block:
        return []
    est = memory.estimate(hlo_text)
    budget = int(block["budget_bytes"])
    if est.peak_bytes <= budget:
        return []
    top = ", ".join(f"{n}={memory.render_bytes(b)}"
                    for n, b in est.largest[:3])
    return [ContractFinding(
        name, "memory",
        f"mesh {key}: static per-chip peak "
        f"{memory.render_bytes(est.peak_bytes)} exceeds the recorded "
        f"{memory.render_bytes(budget)} budget (largest buffers: {top}) "
        "— the step program's resident footprint regressed; shrink it "
        "or raise budget_bytes deliberately (scripts/tpulint spmd "
        "--update keeps budgets sticky)")]


def check_hlo(hlo_text: str, contract: dict) -> List[ContractFinding]:
    """All single-program checks against one contract."""
    return (check_collectives(hlo_text, contract)
            + check_host_ops(hlo_text, contract)
            + check_int_dots(hlo_text, contract)
            + check_memory(hlo_text, contract))


def registry_contract_findings(entries=None,
                               serving_entries=None
                               ) -> List[ContractFinding]:
    """Per-registry-entry contract coverage (engines/registry.py).

    Every engine entry must either name contracts — known modes with a
    checked-in file, at least one filename carrying the entry id — or
    carry a ``contract_exempt`` justification. For histogram entries the
    exemption is only admissible for TPU-only engines (``requires_tpu``):
    the CPU contract harness cannot lower Mosaic kernels, everything
    else MUST be pinned. Serving entries (SERVING_ENTRIES) additionally
    admit an exemption that names the parity test pinning them (a
    ``tests/`` path in the justification) — serve_qleaf shares the
    walk/level program shapes and is pinned by its recorded error bound
    instead of a third identical contract. A new engine cannot land
    without one or the other (tier-1 runs this via
    scripts/verify_contracts.py and tests/test_hlo_check.py)."""
    if entries is None:
        from ..engines.registry import ENTRIES as entries
        if serving_entries is None:
            from ..engines.registry import \
                SERVING_ENTRIES as serving_entries
    serving = tuple(serving_entries or ())
    known_modes = set(MODE_TEMPLATES) | set(SERVING_TEMPLATES)
    out: List[ContractFinding] = []
    for entry in tuple(entries) + serving:
        is_serving = entry in serving
        if entry.contract_exempt:
            admissible = entry.requires_tpu or (
                is_serving and "tests/" in entry.contract_exempt)
            if not admissible:
                out.append(ContractFinding(
                    entry.id, "registry",
                    "contract_exempt is only admissible for TPU-only "
                    "engines (the CPU harness cannot lower Mosaic "
                    "kernels) or for serving entries whose exemption "
                    "names the tests/ parity file pinning them; "
                    "otherwise check in a contract "
                    "(scripts/verify_contracts.py --update)"))
            continue
        if not entry.contracts:
            out.append(ContractFinding(
                entry.id, "registry",
                "registry entry has neither an HLO contract nor a "
                "contract_exempt justification — a new engine cannot "
                "land unpinned; add a MODE_TEMPLATE + contract file "
                "named after the entry id and regenerate "
                "(scripts/verify_contracts.py --update)"))
            continue
        if not any(entry.id in mode for mode in entry.contracts):
            out.append(ContractFinding(
                entry.id, "registry",
                f"none of its contracts {list(entry.contracts)} carry "
                "the entry id in the filename — per-entry enumeration "
                "needs the id visible in analysis/contracts/"))
        for mode in entry.contracts:
            if mode not in known_modes:
                out.append(ContractFinding(
                    entry.id, "registry",
                    f"contract mode '{mode}' has no MODE_TEMPLATE or "
                    "SERVING_TEMPLATE — the harness cannot regenerate "
                    "or verify it"))
            elif not os.path.exists(contract_path(mode)):
                out.append(ContractFinding(
                    entry.id, "registry",
                    f"contract file {contract_path(mode)} is missing — "
                    "run scripts/verify_contracts.py --update"))
            else:
                # per-entry mesh enumeration (ISSUE 15): each contract
                # must carry a verified memory block for every mesh the
                # entry declares
                have = set(load_contract(mode).get("memory", {}))
                for mesh in getattr(entry, "meshes", ()):
                    if mesh not in have:
                        out.append(ContractFinding(
                            entry.id, "registry",
                            f"contract '{mode}' has no memory block "
                            f"for declared mesh '{mesh}' (have "
                            f"{sorted(have) or 'none'}) — regenerate "
                            "(scripts/verify_contracts.py --update, or "
                            "scripts/tpulint spmd --update for flight "
                            "meshes)"))
    return out


# ---------------------------------------------------------------------------
# harness half (imports jax + the package lazily)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class CapturedMode:
    mode: str
    program: str
    hlo_text: str
    history: List[str]
    all_programs: Dict[str, str]
    #: the trained GBDT — spmd_check's AOT-relowering hooks
    #: (aot_lower_program / flight_row_dims) hang off it
    gbdt: object = None


def _tiny_problem(n: int, f: int, seed: int):
    import numpy as np
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = ((X[:, 0] + 0.5 * X[:, 1] + 0.2 * rng.randn(n)) > 0)
    return X, y.astype(np.float64)


def capture_mode(mode: str, template: Optional[dict] = None,
                 iterations: int = 4) -> CapturedMode:
    """Train a tiny Booster in ``mode`` and return its step-program HLO.

    Requires an initialized jax backend with >= the mode's device count
    (the tier-1 conftest provisions 8 virtual CPU devices; the CLI path
    sets XLA_FLAGS before first import).
    """
    import jax

    import lightgbm_tpu as lgb

    t = template or MODE_TEMPLATES[mode]
    platform = jax.devices()[0].platform
    if platform != "cpu":
        # the checked-in contracts are CPU lowerings; diffing a TPU/GPU
        # program against them would report meaningless drift
        raise RuntimeError(
            f"hlo_check contracts are CPU-backend lowerings, but this "
            f"process's jax backend is '{platform}' — run via "
            "scripts/tpulint hlo (which forces the CPU platform before "
            "jax initializes)")
    need = t.get("num_devices", 1)
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"mode '{mode}' needs {need} devices, have "
            f"{len(jax.devices())} (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count)")
    X, y = _tiny_problem(**t["problem"])
    prev = os.environ.get("LGBM_TPU_COMM_ACCOUNTING")
    os.environ["LGBM_TPU_COMM_ACCOUNTING"] = "1"
    try:
        bst = lgb.Booster(dict(t["params"]), lgb.Dataset(X, label=y))
        for _ in range(iterations):
            bst.update()
    finally:
        if prev is None:
            os.environ.pop("LGBM_TPU_COMM_ACCOUNTING", None)
        else:
            os.environ["LGBM_TPU_COMM_ACCOUNTING"] = prev
    g = bst._gbdt
    key = t["program"]
    if key not in g._comm_hlo:
        raise RuntimeError(
            f"mode '{mode}': step program '{key}' was not captured "
            f"(have {sorted(g._comm_hlo)}) — the learner dispatched a "
            "different step path than the contract expects")
    return CapturedMode(mode, key, g._comm_hlo[key],
                        list(g._comm_hlo_history.get(key, [])),
                        dict(g._comm_hlo), gbdt=g)


def verify_mode(mode: str, contract: Optional[dict] = None,
                captured: Optional[CapturedMode] = None
                ) -> List[ContractFinding]:
    """Lower the mode's program and verify it against its contract."""
    contract = contract or load_contract(mode)
    captured = captured or capture_mode(mode)
    findings = check_hlo(captured.hlo_text, contract)
    findings += check_fingerprint(captured.history, contract)
    # parity against the CURRENT lowering, not the contract's own stored
    # measurement — a backend upgrade that reshapes the overlap
    # collectives must fail this gate, not wait for --update
    findings += check_overlap_parity(
        contract, measured=collective_bytes(captured.hlo_text))
    return findings


def build_contract(mode: str, captured: Optional[CapturedMode] = None
                   ) -> dict:
    """Measure the mode's program and emit its contract dict (--update)."""
    t = MODE_TEMPLATES[mode]
    captured = captured or capture_mode(mode)
    acct = collective_bytes(captured.hlo_text)
    observed = sorted(k for k, v in acct.items()
                      if k not in ("total", "count") and v > 0)
    budgets = {k: acct[k] for k in observed}
    if t.get("async_twins"):
        # admit the -start half of each observed collective at the same
        # byte budget: async backends split every group reduce into a
        # -start/-done pair (the overlap the grouping exists for); the
        # sync CPU lowering just never uses the allowance
        for k in observed:
            if not k.endswith("-start"):
                budgets.setdefault(f"{k}-start", acct[k])
    contract = {
        "mode": mode,
        "description": t["description"],
        "params": t["params"],
        "num_devices": t["num_devices"],
        "program": t["program"],
        "collectives": {
            "allow": sorted(budgets),
            "require": list(t["require"]),
            "max_bytes": budgets,
        },
        "forbid_host_ops": True,
        "int_dot_s32": True,
        "require_integer_dot": bool(t["require_integer_dot"]),
        "stable_fingerprint": True,
        "measured": {k: v for k, v in sorted(acct.items())},
    }
    if "baseline_params" in t:
        bt = dict(t, params=dict(t["params"], **t["baseline_params"]))
        base_cap = capture_mode(mode, bt)
        contract["measured_baseline"] = {
            k: v for k, v in sorted(collective_bytes(
                base_cap.hlo_text).items())}
    # memory block (ISSUE 15): the native-mesh per-chip budget+estimate,
    # with any previously recorded budget kept STICKY and any additional
    # mesh keys (the spmd flight matrix) and spmd schedule blocks
    # preserved verbatim — those are re-recorded by scripts/tpulint
    # spmd --update, not here
    prior: dict = {}
    if os.path.exists(contract_path(mode)):
        prior = load_contract(mode)
    native = str(t["num_devices"])
    mem = dict(prior.get("memory", {}))
    mem[native] = memory.contract_block(
        captured.hlo_text, prior=prior.get("memory", {}).get(native))
    contract["memory"] = mem
    if "spmd" in prior:
        contract["spmd"] = prior["spmd"]
    return contract


def capture_serving(mode: str) -> str:
    """Train a tiny Booster and AOT-lower ``mode``'s serving-engine
    predict program at the smallest ladder rung (GBDT.aot_lower_serving
    — abstract inputs, nothing transferred). Returns the compiled HLO
    text. CPU-backend only, like :func:`capture_mode`."""
    import jax

    import lightgbm_tpu as lgb

    t = SERVING_TEMPLATES[mode]
    platform = jax.devices()[0].platform
    if platform != "cpu":
        raise RuntimeError(
            f"serving contracts are CPU-backend lowerings, but this "
            f"process's jax backend is '{platform}' — run via "
            "scripts/tpulint hlo")
    X, y = _tiny_problem(**t["problem"])
    bst = lgb.Booster(dict(t["params"]), lgb.Dataset(X, label=y))
    for _ in range(4):
        bst.update()
    return bst._gbdt.aot_lower_serving(t["engine"]).compile().as_text()


def build_serving_contract(mode: str, hlo_text: Optional[str] = None
                           ) -> dict:
    """Measure a serving engine's program and emit its contract dict.

    Same checking schema as the step-program contracts (collectives
    inventory — empty: a single-chip serving dispatch must move zero
    cross-chip bytes — host ops, int-dot accumulators, sticky memory
    budget); ``stable_fingerprint`` is off because the program is
    lowered AOT once, not captured across iterations."""
    t = SERVING_TEMPLATES[mode]
    hlo_text = hlo_text if hlo_text is not None else capture_serving(mode)
    acct = collective_bytes(hlo_text)
    prior: dict = {}
    if os.path.exists(contract_path(mode)):
        prior = load_contract(mode)
    return {
        "mode": mode,
        "description": t["description"],
        "params": t["params"],
        "engine": t["engine"],
        "num_devices": 1,
        "program": t["program"],
        "collectives": {"allow": [], "require": [], "max_bytes": {}},
        "forbid_host_ops": True,
        "int_dot_s32": True,
        "require_integer_dot": False,
        "stable_fingerprint": False,
        "measured": {k: v for k, v in sorted(acct.items())},
        "memory": {"1": memory.contract_block(
            hlo_text, prior=prior.get("memory", {}).get("1"))},
    }


def verify_serving_contracts(modes: Sequence[str] = SERVING_MODES,
                             update: bool = False,
                             check_drift: bool = True
                             ) -> List[ContractFinding]:
    """The serving half of the contract gate: every serving engine's
    program re-lowered and verified (or re-recorded with ``update``)
    against ``analysis/contracts/serve_*.json``."""
    findings: List[ContractFinding] = []
    for mode in modes:
        hlo_text = capture_serving(mode)
        fresh = build_serving_contract(mode, hlo_text)
        if update:
            os.makedirs(CONTRACTS_DIR, exist_ok=True)
            with open(contract_path(mode), "w") as fh:
                json.dump(fresh, fh, indent=1, sort_keys=True)
                fh.write("\n")
        if not os.path.exists(contract_path(mode)):
            findings.append(ContractFinding(
                mode, "missing",
                f"no checked-in contract at {contract_path(mode)} — run "
                "scripts/verify_contracts.py --update"))
            continue
        contract = load_contract(mode)
        findings += check_hlo(hlo_text, contract)
        fresh_fp = drift_fingerprint(fresh)
        contract_fp = drift_fingerprint(contract)
        if check_drift and not update and fresh_fp != contract_fp:
            drift = sorted(k for k in set(fresh_fp) | set(contract_fp)
                           if fresh_fp.get(k) != contract_fp.get(k))
            findings.append(ContractFinding(
                mode, "drift",
                f"regenerated serving contract differs from the "
                f"checked-in file in {drift} — the engine's program "
                "shape drifted; if intended, rerun "
                "scripts/verify_contracts.py --update and review the "
                "diff"))
    return findings


def verify_contracts(modes: Sequence[str] = MODES, update: bool = False,
                     check_drift: bool = True) -> List[ContractFinding]:
    """The full gate: every registry entry covered, every mode verified,
    and the regenerated measurement diffed against the checked-in
    contract (silent comm-shape drift fails tier-1; ``update=True``
    rewrites the files instead)."""
    findings: List[ContractFinding] = []
    for mode in modes:
        captured = capture_mode(mode)
        fresh = build_contract(mode, captured)
        if update:
            os.makedirs(CONTRACTS_DIR, exist_ok=True)
            with open(contract_path(mode), "w") as fh:
                json.dump(fresh, fh, indent=1, sort_keys=True)
                fh.write("\n")
        if not os.path.exists(contract_path(mode)):
            findings.append(ContractFinding(
                mode, "missing",
                f"no checked-in contract at {contract_path(mode)} — run "
                "scripts/verify_contracts.py --update"))
            continue
        contract = load_contract(mode)
        findings += verify_mode(mode, contract, captured)
        fresh_fp = drift_fingerprint(fresh)
        contract_fp = drift_fingerprint(contract)
        if check_drift and not update and fresh_fp != contract_fp:
            drift = sorted(k for k in set(fresh_fp) | set(contract_fp)
                           if fresh_fp.get(k) != contract_fp.get(k))
            findings.append(ContractFinding(
                mode, "drift",
                f"regenerated contract differs from the checked-in file "
                f"in {drift} — comm/program shape drifted; if intended, "
                "rerun scripts/verify_contracts.py --update and review "
                "the diff"))
    # the serving-engine programs ride the same gate (their modes are
    # SERVING_TEMPLATES, captured via aot_lower_serving)
    findings += verify_serving_contracts(update=update,
                                         check_drift=check_drift)
    # per-registry-entry coverage AFTER the update loop, so --update can
    # create a new entry's contract file in the same invocation
    findings += registry_contract_findings()
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI body for ``scripts/tpulint hlo`` / scripts/verify_contracts.py.

    Must run before jax initializes a backend elsewhere in the process:
    it forces the CPU platform with enough virtual devices for every
    requested mode.
    """
    import argparse
    ap = argparse.ArgumentParser(
        prog="tpulint hlo",
        description="verify the learner-mode HLO contracts on the CPU "
                    "backend (no TPU required)")
    ap.add_argument("modes", nargs="*", default=list(MODES),
                    help=f"modes to verify (default: all of {list(MODES)})")
    ap.add_argument("--update", action="store_true",
                    help="regenerate analysis/contracts/*.json from the "
                         "current lowering instead of failing on drift")
    args = ap.parse_args(argv)
    modes = args.modes or list(MODES)
    unknown = [m for m in modes if m not in MODE_TEMPLATES]
    if unknown:
        print(f"hlo_check: unknown mode(s) {unknown}; "
              f"known: {list(MODES)}")
        return 2

    # jax reads JAX_PLATFORMS/XLA_FLAGS at IMPORT time, and importing this
    # module already pulled the package (and jax) in — so the pre-import
    # env lives in ONE place, scripts/tpulint's hlo branch (which
    # scripts/verify_contracts.py execs). Here only the post-import
    # platform override remains (the same move as tests/conftest.py); the
    # virtual device count cannot be raised after backend init, so
    # capture_mode raises an actionable error if too few devices exist.
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass   # backend already initialized elsewhere; device check below

    findings = verify_contracts(modes, update=args.update)
    for f in findings:
        print(f.render())
    if args.update and not findings:
        print(f"hlo_check: contracts regenerated for {list(modes)}")
    if not findings:
        print(f"hlo_check: {len(modes)} contract(s) verified clean")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
