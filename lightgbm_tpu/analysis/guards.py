"""Runtime guard rails: recompile and host-transfer assertions.

The static pass (analysis/tpulint.py) catches hazard *patterns*; these
guards catch the *behavior* — they wrap a steady-state region (e.g. 5
post-warmup boosting iterations) and fail loudly if jax compiles anything
or an array is materialized on the host inside it.

``compile_counter``
    Counts compilations via ``jax.monitoring`` duration events.
    ``lowerings`` (jaxpr->MLIR) increments on every in-memory cache miss —
    including ones served by the persistent compilation cache, which skips
    only the backend compile — so it is the honest "did jit re-trace"
    signal. ``backend_compiles`` counts actual XLA compiles. Counts are
    also keyed by the active ``compile_phase()`` (train step / predict
    warmup / serving) in ``by_phase``, and a process-lifetime listener
    (``install_global_compile_listener``) feeds the same attribution to
    the obs/ metrics plane and the flight recorder.

``no_host_transfers``
    Patches the Python-level host-materialization funnels on
    ``jax.Array`` (``_value``, ``__array__``, ``item``, ``tolist``,
    ``__float__``/``__int__``/``__bool__``/``__index__``) to raise
    ``HostTransferError`` at the offending call site, and additionally
    arms ``jax.transfer_guard_device_to_host("disallow")``, which is
    enforced natively on real device backends.

    ``np.asarray(arr)`` on the CPU backend reaches the buffer zero-copy
    through the C-level buffer protocol WITHOUT touching any ``jax.Array``
    method — so the numpy entry points themselves
    (``np.asarray``/``np.array``/``np.ascontiguousarray``/
    ``np.asanyarray``) are wrapped too: a ``jax.Array`` as the top-level
    argument raises inside the guard. Residual caveat: a direct C-level
    consumer (``memoryview(arr)``, third-party C extensions taking the
    buffer) still bypasses Python entirely — only the native transfer
    guard on TPU and the static pass (R001) see those.

``api_race_sanitizer``
    The runtime half of tpulint R007: while armed, every
    ``@read_locked``/``@write_locked`` public ``Booster``/``Dataset``
    method reports entry/exit (from *inside* the lock, utils/rwlock.py),
    and any overlap — a writer concurrent with anything, on the same
    object, from another thread — is recorded as a race. A correctly
    locked program records nothing; a bypassed or missing lock (the
    seeded mutation in tests/test_concurrency.py) lights it up.

All are plain context managers usable directly or as pytest fixtures
(wired in tests/conftest.py).
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
from jax import monitoring

from ..utils import rwlock as _rwlock

_LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

#: jax.Array methods/properties through which host materialization funnels
_FUNNELS = ("_value", "__array__", "item", "tolist", "__float__",
            "__int__", "__bool__", "__index__", "__complex__")

#: numpy entry points that can materialize a CPU-backend jax.Array
#: zero-copy via the C buffer protocol, bypassing every patched method
_NP_FUNNELS = ("asarray", "array", "ascontiguousarray", "asanyarray")


class HostTransferError(AssertionError):
    """An array was materialized on the host inside a guarded region."""


#: thread-local compile-phase stack (jax compiles synchronously on the
#: calling thread, so the phase at event time attributes the compile)
_phase_local = threading.local()

#: phase recorded when no compile_phase() scope is active
DEFAULT_PHASE = "other"


def current_compile_phase() -> str:
    stack = getattr(_phase_local, "stack", None)
    return stack[-1] if stack else DEFAULT_PHASE


@contextlib.contextmanager
def compile_phase(name: str) -> Iterator[None]:
    """Attribute compile events inside the block to ``name``.

    The phase key behind ``CompileCount.by_phase`` and the metrics
    plane: ``train_step`` wraps boosting iterations, ``predict_warmup``
    wraps the serving-ladder warm, ``serving`` wraps coalescer ticks —
    so a BENCH row (or a flight dump) says WHERE a compile happened
    instead of reporting one global count. Nests; the innermost wins."""
    stack = getattr(_phase_local, "stack", None)
    if stack is None:
        stack = _phase_local.stack = []
    stack.append(str(name))
    try:
        yield
    finally:
        stack.pop()


@dataclasses.dataclass
class CompileCount:
    lowerings: int = 0
    backend_compiles: int = 0
    #: phase -> {"lowerings": n, "backend_compiles": m} (see compile_phase)
    by_phase: dict = dataclasses.field(default_factory=dict)

    def bump(self, kind: str, phase: str) -> None:
        setattr(self, kind, getattr(self, kind) + 1)
        slot = self.by_phase.setdefault(
            phase, {"lowerings": 0, "backend_compiles": 0})
        slot[kind] += 1

    def snapshot(self) -> dict:
        return {"lowerings": self.lowerings,
                "backend_compiles": self.backend_compiles,
                "by_phase": {p: dict(v) for p, v in self.by_phase.items()}}

    def assert_no_compiles(self, what: str = "guarded region") -> None:
        if self.lowerings or self.backend_compiles:
            raise AssertionError(
                f"{what}: expected zero recompilations, saw "
                f"{self.lowerings} lowering(s) and "
                f"{self.backend_compiles} backend compile(s) — a shape, "
                "dtype, or static-arg value changed after warmup "
                f"(by phase: {self.by_phase})")


@contextlib.contextmanager
def _monitoring_listener(callback, register, unregister_name: str):
    """Register a jax.monitoring listener for the duration of the block.

    On exit the listener is deactivated (it stops forwarding to
    ``callback``) and best-effort unregistered via the private
    ``jax._src.monitoring`` API — the public unregister landed after
    0.4.37, and a deactivated listener staying registered is harmless."""
    state = {"active": True}

    def _listener(*args, **kw) -> None:
        if state["active"]:
            callback(*args, **kw)

    register(_listener)
    try:
        yield
    finally:
        state["active"] = False
        try:
            from jax._src import monitoring as _mon
            getattr(_mon, unregister_name)(_listener)
        except Exception:
            pass


@contextlib.contextmanager
def compile_counter() -> Iterator[CompileCount]:
    """Count jit compilations inside the ``with`` block.

    Usage::

        with compile_counter() as cc:
            for _ in range(5):
                bst.update()
        cc.assert_no_compiles("post-warmup boosting")
    """
    counts = CompileCount()

    def _on_event(event: str, duration_secs: float = 0.0, **kw) -> None:
        if event == _LOWER_EVENT:
            counts.bump("lowerings", current_compile_phase())
        elif event == _BACKEND_EVENT:
            counts.bump("backend_compiles", current_compile_phase())

    with _monitoring_listener(
            _on_event, monitoring.register_event_duration_secs_listener,
            "_unregister_event_duration_listener_by_callback"):
        yield counts


@dataclasses.dataclass
class CacheCount:
    """Persistent-compile-cache lookups observed inside a guarded region."""
    requests: int = 0
    hits: int = 0

    @property
    def misses(self) -> int:
        return self.requests - self.hits


@contextlib.contextmanager
def cache_counter() -> Iterator[CacheCount]:
    """Count persistent-compilation-cache lookups inside the ``with`` block.

    ``requests`` counts backend compiles that consulted the cache
    (``/jax/compilation_cache/compile_requests_use_cache``), ``hits`` the
    ones served from it. A warm cache (``tpu_compile_cache_dir`` pointed
    at a previous run's directory, fresh process) shows hits == requests:
    lowering still happens, the XLA backend compile is skipped. Counts
    stay zero when no cache dir is configured."""
    counts = CacheCount()

    def _on_event(event: str, **kw) -> None:
        if event == _CACHE_REQUEST_EVENT:
            # jax emits the request event on EVERY backend compile, cache
            # dir or not — only count consultations of a real cache, so
            # cache-disabled runs read 0/0 instead of all-miss
            if jax.config.jax_compilation_cache_dir:
                counts.requests += 1
        elif event == _CACHE_HIT_EVENT:
            counts.hits += 1

    with _monitoring_listener(_on_event, monitoring.register_event_listener,
                              "_unregister_event_listener_by_callback"):
        yield counts


def configure_compile_cache(cache_dir) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    The ``tpu_compile_cache_dir`` wiring: resumed/checkpointed runs and
    repeated bench rounds relower but skip every backend compile whose
    fingerprint is already on disk. The size/compile-time admission
    thresholds are zeroed so every step program qualifies (the default
    1 s floor would reject most CPU-backend programs). Changing the
    directory after a compile already ran re-arms jax's once-per-task
    cache-enable decision via ``reset_cache``. Returns True when a cache
    directory is active, False for an empty/unset path (no-op)."""
    path = str(cache_dir or "").strip()
    if not path:
        return False
    # thresholds zero unconditionally: the dir may already be set (e.g.
    # via JAX_COMPILATION_CACHE_DIR) with the 1 s admission floor intact,
    # which would silently reject most CPU-backend step programs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if jax.config.jax_compilation_cache_dir != path:
        jax.config.update("jax_compilation_cache_dir", path)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()  # drop the cached is-cache-used decision
        except Exception:
            pass
    return True


# -- process-lifetime compile accounting (the obs/ metrics plane) ----------
#: cumulative phase-keyed counts, fed by ONE permanently-registered
#: listener (install_global_compile_listener); the metrics stream emits
#: these as cumulative snapshots so any two records diff cleanly
_global_compiles = CompileCount()
_global_cache = CacheCount()
_global_listener_installed = False
_global_mu = threading.Lock()


def install_global_compile_listener() -> None:
    """Register the always-on compile/cache listeners (idempotent).

    Unlike :func:`compile_counter` (a scoped guard), this feeds the
    process-lifetime counters behind :func:`phase_compile_counts` and
    records each compile into the flight recorder, phase-keyed — so a
    post-mortem dump shows WHAT compiled right before a death, and the
    metrics plane reports attribution without any guard being armed.
    Cost: one python callback per compile event (compiles are rare by
    contract — the whole repo is built around zero steady-state
    compiles)."""
    global _global_listener_installed
    with _global_mu:
        if _global_listener_installed:
            return
        _global_listener_installed = True

    def _on_duration(event: str, duration_secs: float = 0.0, **kw) -> None:
        kind = None
        if event == _LOWER_EVENT:
            kind = "lowerings"
        elif event == _BACKEND_EVENT:
            kind = "backend_compiles"
        if kind is None:
            return
        phase = current_compile_phase()
        with _global_mu:
            _global_compiles.bump(kind, phase)
        from ..obs import flight
        flight.note("compile", kind=kind, phase=phase,
                    seconds=round(float(duration_secs), 4))

    def _on_event(event: str, **kw) -> None:
        if event == _CACHE_REQUEST_EVENT:
            if jax.config.jax_compilation_cache_dir:
                with _global_mu:
                    _global_cache.requests += 1
        elif event == _CACHE_HIT_EVENT:
            with _global_mu:
                _global_cache.hits += 1

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)


def phase_compile_counts() -> dict:
    """Cumulative process-lifetime compile counts, phase-keyed (zeros
    until :func:`install_global_compile_listener` ran)."""
    with _global_mu:
        return _global_compiles.snapshot()


def global_cache_counts() -> dict:
    """Cumulative persistent-compile-cache counters (same caveat)."""
    with _global_mu:
        return {"requests": _global_cache.requests,
                "hits": _global_cache.hits,
                "misses": _global_cache.misses}


#: shared device-enumeration probe state: a wedged backend pins exactly
#: ONE blocked thread process-wide (periodic readiness polling reuses the
#: in-flight enumeration), and once a backend has come up enumeration is
#: jax's cached lookup, called inline with no thread at all
_device_probe = {"mu": threading.Lock(), "thread": None, "box": None,
                 "initialized": False}


def device_healthcheck(deadline_s: float = 5.0) -> dict:
    """Device-reachability probe for serving health endpoints.

    Returns ``{"ok", "platform", "device_count", "error"}`` without ever
    raising — and without ever HANGING: ``jax.devices()`` on a fresh
    process synchronously initializes the backend, which on a wedged TPU
    runtime blocks for the full init timeout (the BENCH_r05 death mode;
    on some hosts plugin discovery never returns at all). The first
    enumeration therefore runs in a single SHARED daemon thread waited
    on for ``deadline_s``: a blown deadline reports ``ok: False``, and
    every later probe re-waits on the SAME blocked thread instead of
    leaking one watchdog worker per poll. After one successful
    enumeration the backend is cached and the probe calls inline.
    ``deadline_s <= 0`` disables the watchdog (may block)."""

    def _summarize(devices):
        if not devices:
            return {"ok": False, "platform": None, "device_count": 0,
                    "error": "device enumeration returned an empty list"}
        _device_probe["initialized"] = True
        return {"ok": True, "platform": devices[0].platform,
                "device_count": len(devices), "error": None}

    def _failure(err):
        msg = str(err).splitlines()[0][:200] if str(err) else repr(err)
        return {"ok": False, "platform": None, "device_count": 0,
                "error": msg}

    if _device_probe["initialized"] or not deadline_s or deadline_s <= 0:
        try:
            return _summarize(jax.devices())
        except Exception as err:  # noqa: BLE001 - probe must not raise
            return _failure(err)
    with _device_probe["mu"]:
        thread, box = _device_probe["thread"], _device_probe["box"]
        if thread is None or not thread.is_alive():
            # no probe in flight (fresh, or the last one finished and was
            # consumed): start one
            box = {"done": threading.Event()}

            def _enumerate(b=box):
                try:
                    b["devices"] = jax.devices()
                except BaseException as err:  # noqa: BLE001 - reported
                    b["error"] = err
                finally:
                    b["done"].set()

            thread = threading.Thread(target=_enumerate, daemon=True,
                                      name="lgbm-tpu-device-probe")
            _device_probe["thread"], _device_probe["box"] = thread, box
            thread.start()
    if not box["done"].wait(deadline_s):
        return {"ok": False, "platform": None, "device_count": 0,
                "error": f"device enumeration still blocked after "
                         f"{deadline_s:.0f}s (backend init wedged)"}
    if "error" in box:
        return _failure(box["error"])
    return _summarize(box.get("devices"))


@contextlib.contextmanager
def no_host_transfers() -> Iterator[None]:
    """Raise ``HostTransferError`` on any device->host materialization.

    See the module docstring for the CPU buffer-protocol caveat.
    """
    from jax._src import array as _array_mod

    cls = _array_mod.ArrayImpl
    saved = {}

    def _wrap(name, orig):
        if isinstance(orig, property):
            @property
            def guard_prop(self):
                raise HostTransferError(
                    f"jax.Array.{name} materialized an array on the host "
                    "inside a no_host_transfers() region")
            return guard_prop

        def guard(self, *a, **k):
            raise HostTransferError(
                f"jax.Array.{name}() materialized an array on the host "
                "inside a no_host_transfers() region")
        return guard

    for name in _FUNNELS:
        orig = getattr(cls, name, None)
        if orig is None:
            continue
        saved[name] = orig
        setattr(cls, name, _wrap(name, orig))

    # the np.asarray buffer-protocol path materializes the array without
    # calling ANY jax.Array method on CPU; guard the numpy entry points
    # for direct jax.Array arguments (nested containers still route
    # through the patched __array__ above)
    import numpy as _np

    def _np_wrap(name, orig):
        def guard(a, *args, **kw):
            if isinstance(a, cls):
                raise HostTransferError(
                    f"np.{name}() materialized a jax.Array on the host "
                    "inside a no_host_transfers() region (C buffer-protocol "
                    "path)")
            return orig(a, *args, **kw)
        return guard

    np_saved = {}
    for name in _NP_FUNNELS:
        orig = getattr(_np, name, None)
        if orig is None:  # pragma: no cover - numpy always has these
            continue
        np_saved[name] = orig
        setattr(_np, name, _np_wrap(name, orig))
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        for name, orig in saved.items():
            setattr(cls, name, orig)
        for name, orig in np_saved.items():
            setattr(_np, name, orig)


class ApiRaceError(AssertionError):
    """Unsynchronized concurrent access to a shared API object."""


class ApiRaceSanitizer:
    """Detector for concurrent unsynchronized ``Booster``/``Dataset`` use.

    Holds a table of (object, thread) -> current access kind, fed by the
    rwlock decorators. Because the hooks run while the API lock is held,
    a working lock admits no overlap; overlaps therefore mean the lock
    was bypassed, replaced, or a method skipped its decorator. Detector
    mode records races in ``.races`` without blocking the offending
    thread; ``raise_on_race=True`` turns the first overlap into an
    immediate ``ApiRaceError`` at the second accessor's call site.
    """

    def __init__(self, raise_on_race: bool = False):
        self.races: List[str] = []
        self.raise_on_race = raise_on_race
        self._mu = threading.Lock()
        # id(obj) -> {thread_id: [kind, depth, method]}
        self._held = {}

    def enter(self, obj, kind: str, method: str):
        me = threading.get_ident()
        key = id(obj)
        with self._mu:
            holds = self._held.setdefault(key, {})
            mine = holds.get(me)
            if mine is not None:
                mine[1] += 1            # same-thread nesting is not a race
                return (key, me)
            clash = next(
                (f"{type(obj).__name__}.{method} [{kind}] in thread {me} "
                 f"overlaps {type(obj).__name__}.{m} [{k}] in thread {t}"
                 for t, (k, _, m) in holds.items()
                 if kind == "write" or k == "write"), None)
            if clash is not None:
                self.races.append(clash)
                if self.raise_on_race:
                    # the access does not proceed (the wrapper's exit_ is
                    # never reached), so do NOT register the hold — a
                    # phantom entry would indict every later accessor
                    raise ApiRaceError(clash)
            holds[me] = [kind, 1, method]
            return (key, me)

    def exit_(self, token) -> None:
        key, me = token
        with self._mu:
            holds = self._held.get(key, {})
            mine = holds.get(me)
            if mine is None:
                return
            mine[1] -= 1
            if mine[1] <= 0:
                del holds[me]

    def assert_no_races(self, what: str = "guarded region") -> None:
        if self.races:
            raise ApiRaceError(
                f"{what}: {len(self.races)} unsynchronized concurrent "
                "API access(es):\n  " + "\n  ".join(self.races[:10]))


@contextlib.contextmanager
def api_race_sanitizer(raise_on_race: bool = False
                       ) -> Iterator[ApiRaceSanitizer]:
    """Arm the API race detector for the ``with`` block.

    Usage::

        with api_race_sanitizer() as san:
            ... threads hammering booster.predict()/update() ...
        san.assert_no_races("concurrent predict")
    """
    san = ApiRaceSanitizer(raise_on_race=raise_on_race)
    prev = _rwlock.get_sanitizer()
    _rwlock.set_sanitizer(san)
    try:
        yield san
    finally:
        _rwlock.set_sanitizer(prev)


@contextlib.contextmanager
def steady_state_guard(what: str = "guarded region"
                       ) -> Iterator[CompileCount]:
    """Combined guard: zero recompiles AND zero host transfers.

    Asserts on clean exit; an exception from the body propagates as-is.
    """
    with compile_counter() as counts:
        with no_host_transfers():
            yield counts
    counts.assert_no_compiles(what)


# ---------------------------------------------------------------------------
# lock-order witness — the runtime half of tpulint R011


class LockOrderError(AssertionError):
    """A lock-order cycle was observed across threads at runtime."""


def _witness_stack(skip: int = 2, depth: int = 12) -> Tuple[str, ...]:
    """Cheap ``file.py:line`` stack (innermost first), skipping the
    witness/lock machinery frames — captured on every outer acquisition,
    so no ``traceback`` formatting."""
    frames: List[str] = []
    try:
        f = sys._getframe(skip)
    except ValueError:              # pragma: no cover - shallow stack
        return ()
    while f is not None and len(frames) < depth:
        fname = f.f_code.co_filename
        base = os.path.basename(fname)
        if base not in ("threading.py", "rwlock.py", "guards.py"):
            frames.append(f"{base}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    return tuple(frames)


class LockOrderWitness:
    """Per-thread held-lock stacks merged into a global order graph.

    Locks are identified by their *creation site* name, not instance id:
    every ``ServeFuture._mu`` is the same node, so a per-request lock
    family cannot spuriously self-cycle (same-name pairs are skipped —
    they are either re-entrant or independent instances), while a real
    A->B / B->A inversion between two lock families is caught no matter
    which instances exhibit it. Each first-seen edge keeps the acquiring
    thread's stacks for both locks; a cycle closing in the graph records
    the full loop with both witness stacks and fails
    ``assert_no_cycles``.
    """

    def __init__(self):
        # a RAW lock, created before lock_witness() patches the factories
        self._mu = threading.Lock()
        # thread id -> [(id(obj), name, side, stack), ...]
        self._held: Dict[int, List[tuple]] = {}
        # (held name, acquired name) -> (held stack, acquired stack)
        self.edges: Dict[Tuple[str, str], Tuple[Tuple[str, ...],
                                                Tuple[str, ...]]] = {}
        self.cycles: List[str] = []
        self.acquires = 0

    # -- hooks (called by rwlock + the patched stdlib factories) -------
    def note_acquire(self, obj, name: str, side: str) -> None:
        me = threading.get_ident()
        stack = _witness_stack()
        with self._mu:
            self.acquires += 1
            held = self._held.setdefault(me, [])
            for _hid, hname, _hside, hstack in held:
                if hname == name:
                    continue        # same family: re-entrant/per-instance
                if (hname, name) not in self.edges:
                    self.edges[(hname, name)] = (hstack, stack)
                    if self._reaches(name, hname):
                        self._record_cycle(hname, name)
            held.append((id(obj), name, side, stack))

    def note_release(self, obj) -> None:
        me = threading.get_ident()
        with self._mu:
            held = self._held.get(me, ())
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] == id(obj):
                    del held[i]
                    return

    # -- cycle machinery (callers hold self._mu) -----------------------
    def _reaches(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            node = frontier.pop()
            for (a, b) in self.edges:
                if a == node and b not in seen:
                    if b == dst:
                        return True
                    seen.add(b)
                    frontier.append(b)
        return False

    def _path(self, src: str, dst: str) -> List[str]:
        prev: Dict[str, str] = {}
        frontier = [src]
        seen = {src}
        while frontier:
            node = frontier.pop(0)
            for (a, b) in self.edges:
                if a == node and b not in seen:
                    prev[b] = a
                    if b == dst:
                        path = [dst]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    seen.add(b)
                    frontier.append(b)
        return [src, dst]           # pragma: no cover - _reaches said yes

    def _record_cycle(self, hname: str, name: str) -> None:
        loop = [hname] + self._path(name, hname)
        lines = [f"lock-order cycle observed: "
                 f"{' -> '.join([hname, name])} closes "
                 f"{' -> '.join(loop)}"]
        for a, b in zip(loop, loop[1:]):
            hstack, astack = self.edges.get((a, b), ((), ()))
            lines.append(f"  edge {a} -> {b}:")
            lines.append(f"    {a} held at: "
                         + (" <- ".join(hstack[:6]) or "<?>"))
            lines.append(f"    {b} acquired at: "
                         + (" <- ".join(astack[:6]) or "<?>"))
        self.cycles.append("\n".join(lines))

    def assert_no_cycles(self, what: str = "guarded region") -> None:
        if self.cycles:
            raise LockOrderError(
                f"{what}: {len(self.cycles)} lock-order cycle(s) "
                "observed:\n" + "\n".join(self.cycles[:4]))


class _WitnessedLock:
    """threading.Lock wrapper reporting outer acquire/release."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            w = _active_lock_witness
            if w is not None:
                w.note_acquire(self, self._name, "excl")
        return ok

    def release(self) -> None:
        w = _active_lock_witness
        if w is not None:
            w.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, attr):
        # Condition() wires _release_save/_acquire_restore/_is_owned
        # straight to the inner lock: cv.wait() releases without a
        # witness note, so the held entry persists while the thread is
        # BLOCKED in wait — it records no edges there, harmless
        return getattr(self._inner, attr)


class _WitnessedRLock(_WitnessedLock):
    """Re-entrant variant: only depth 0<->1 transitions are noted."""

    def __init__(self, inner, name: str):
        super().__init__(inner, name)
        self._local = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            depth = getattr(self._local, "depth", 0)
            self._local.depth = depth + 1
            if depth == 0:
                w = _active_lock_witness
                if w is not None:
                    w.note_acquire(self, self._name, "excl")
        return ok

    def release(self) -> None:
        depth = getattr(self._local, "depth", 1)
        self._local.depth = depth - 1
        if depth == 1:
            w = _active_lock_witness
            if w is not None:
                w.note_release(self)
        self._inner.release()


#: the armed witness; wrappers outliving the block (daemon threads still
#: holding references) go quiet once this resets to None
_active_lock_witness: Optional[LockOrderWitness] = None


@contextlib.contextmanager
def lock_witness() -> Iterator[LockOrderWitness]:
    """Arm the runtime lock-order witness for the ``with`` block.

    Patches the ``threading.Lock``/``threading.RLock`` factories so
    locks *created inside the block* report outer acquisitions with
    their creation site as the graph node name (``Condition()`` picks up
    the patched RLock automatically), and arms the RWLock/Mutex hooks in
    utils/rwlock.py for the API locks and ``GBDT._trees_mu`` (those
    report at their own level, so their internals — and any lock created
    from rwlock.py or this module — stay unwrapped). Pre-existing stdlib
    locks are invisible; construct the server/registry under the witness.

    Usage::

        with lock_witness() as w:
            ... threads hammering serve()/deploy()/save_checkpoint() ...
        w.assert_no_cycles("16-thread serving")
    """
    global _active_lock_witness
    w = LockOrderWitness()
    saved_lock, saved_rlock = threading.Lock, threading.RLock

    def _site() -> str:
        f = sys._getframe(2)        # the factory's caller
        while f is not None and \
                os.path.basename(f.f_code.co_filename) == "threading.py":
            f = f.f_back
        if f is None:               # pragma: no cover - always has one
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"

    def make_lock():
        site = _site()
        inner = saved_lock()
        if site.startswith(("rwlock.py", "guards.py")):
            return inner            # witnessed at the RWLock/Mutex level
        return _WitnessedLock(inner, f"Lock@{site}")

    def make_rlock():
        site = _site()
        inner = saved_rlock()
        if site.startswith(("rwlock.py", "guards.py")):
            return inner
        return _WitnessedRLock(inner, f"RLock@{site}")

    prev_rw = _rwlock.get_witness()
    threading.Lock = make_lock
    threading.RLock = make_rlock
    _rwlock.set_witness(w)
    _active_lock_witness = w
    try:
        yield w
    finally:
        _active_lock_witness = None
        _rwlock.set_witness(prev_rw)
        threading.Lock = saved_lock
        threading.RLock = saved_rlock


# ======================================================================
# resource-leak witness — the runtime half of tpulint R012, exactly as
# lock_witness is the runtime half of R011

class ResourceLeakError(AssertionError):
    """A guarded scope exited with live resources it did not enter with."""


#: thread-name prefixes of deliberate process-lifetime holds (anchored
#: in tpulint.allow on the static side): the shared device probe and the
#: multihost deadline watchdog, which outlives its scope BY DESIGN when
#: a deadline fires
_WITNESS_THREAD_EXEMPT = ("lgbm-tpu-device-probe", "lgbm-tpu-watchdog")

#: extra jit/program-cache size probes: callables returning an int; the
#: witness sums them into the ``jit_cache`` delta (drift's accumulator
#: factories register lazily below — register yours if you add a keyed
#: program cache, and make it pass R012's bound check first)
_witness_cache_probes: List[Callable[[], int]] = []


def register_witness_cache_probe(probe: Callable[[], int]) -> None:
    _witness_cache_probes.append(probe)


def _witness_threads() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.is_alive() and t.ident is not None
            and not t.name.startswith(_WITNESS_THREAD_EXEMPT)}


def _witness_fds() -> Optional[frozenset]:
    try:
        return frozenset(os.listdir("/proc/self/fd"))
    except OSError:                 # pragma: no cover - non-procfs OS
        return None


def _witness_sessions() -> int:
    spans = sys.modules.get("lightgbm_tpu.obs.spans")
    return int(spans.active_sessions()) if spans is not None else 0


def _witness_jit_cache() -> int:
    total = 0
    # only modules ALREADY imported are probed: the witness must never
    # be the thing that pulls a subsystem (and its compiles) in
    drift = sys.modules.get("lightgbm_tpu.obs.drift")
    if drift is not None:
        for name in ("_bin_accum_fn", "_score_accum_fn"):
            fn = getattr(drift, name, None)
            if fn is not None and hasattr(fn, "cache_info"):
                total += int(fn.cache_info().currsize)
    for probe in _witness_cache_probes:
        try:
            total += int(probe())
        except Exception:           # noqa: BLE001 - probes must not kill
            pass
    return total


class ResourceWitness:
    """Snapshot of live resources at arm time; ``assert_no_leaks``
    re-snapshots (polling, releases are asynchronous — a shutdown
    serve_forever thread takes a poll interval to exit) and raises
    ResourceLeakError naming every thread/fd/session/cache delta."""

    def __init__(self):
        self._base_threads = _witness_threads()
        self._base_fds = _witness_fds()
        self._base_sessions = _witness_sessions()
        self._base_jit_cache = _witness_jit_cache()

    def deltas(self) -> Dict[str, object]:
        """Current growth over the baseline (leaked thread NAMES, new fd
        count, session and cache-size deltas); empty dict == clean."""
        out: Dict[str, object] = {}
        threads = _witness_threads()
        leaked = [name for ident, name in threads.items()
                  if ident not in self._base_threads]
        if leaked:
            out["threads"] = sorted(leaked)
        fds = _witness_fds()
        if fds is not None and self._base_fds is not None:
            grown = len(fds - self._base_fds) - \
                len(self._base_fds - fds)
            if grown > 0:
                out["fds"] = grown
        sessions = _witness_sessions() - self._base_sessions
        if sessions > 0:
            out["sessions"] = sessions
        cache = _witness_jit_cache() - self._base_jit_cache
        if cache > 0:
            out["jit_cache"] = cache
        return out

    def assert_no_leaks(self, what: str = "guarded scope",
                        settle_s: float = 5.0) -> None:
        deadline = time.monotonic() + float(settle_s)
        deltas = self.deltas()
        while deltas and time.monotonic() < deadline:
            time.sleep(0.05)
            deltas = self.deltas()
        if deltas:
            parts = []
            if "threads" in deltas:
                parts.append("live threads not in the baseline: "
                             + ", ".join(deltas["threads"]))
            if "fds" in deltas:
                parts.append(f"{deltas['fds']} more open fd(s)")
            if "sessions" in deltas:
                parts.append(f"{deltas['sessions']} still-entered trace "
                             "session(s)")
            if "jit_cache" in deltas:
                parts.append(f"retained-program caches grew by "
                             f"{deltas['jit_cache']} entries")
            raise ResourceLeakError(
                f"resource leak across {what}: " + "; ".join(parts)
                + ". Every acquisition must release on ALL paths "
                "(tpulint R012) — close/join/stop in a finally, or fix "
                "the owner's close() to be release-complete.")


@contextlib.contextmanager
def resource_witness() -> Iterator[ResourceWitness]:
    """Arm the resource-leak witness for the ``with`` block.

    The dynamic complement of ``scripts/tpulint resources`` (R012):
    snapshots live threads, open fds, entered trace sessions, and
    retained-program cache sizes at entry; ``assert_no_leaks`` proves
    the scope gave everything back. Warm caches and construct
    long-lived fixtures BEFORE arming — the witness measures the scope,
    not process history.

    Usage::

        with resource_witness() as w:
            server = PredictionServer(bst)
            ... kill/hang chaos ...
            server.close()
        w.assert_no_leaks("serving chaos drill")
    """
    yield ResourceWitness()
