"""memory — static peak-HBM estimation from compiled HLO text.

The pod go/no-go question ("does the full 13.2M x 4228 Allstate step fit
16 GiB per chip on 8 chips?") is answerable WITHOUT hardware: after SPMD
partitioning the compiled module's shapes are already per-shard, so a
buffer-liveness walk over the entry computation bounds the per-chip HBM
the program needs. The reference budgets the same way by hand — its
docs/Experiments.rst trains the full Allstate in ~1 GB RAM per rank
because the bin matrix is the only O(rows) resident — here the walk is
mechanical and runs in tier-1 on the CPU lowering of the SAME program
(shapes, shardings and donation are backend-independent facts of the
partitioned module; only the scheduler's transient packing differs).

Model (deliberately simple, exact on the fixtures in
tests/test_spmd_check.py, conservative on real programs):

* every entry-computation instruction allocates its result bytes at its
  program position, EXCEPT the view ops (tuple / get-tuple-element /
  bitcast), ``while`` and ``conditional`` — a while's carried tuple is
  updated in place by XLA, so its result aliases its operand's buffers
  rather than doubling them (the dominant correction for the train
  step, whose tree loop carries the multi-GiB work/scratch pair), and a
  conditional's result aliases its branch operands' buffers the same
  way (at most one branch runs; XLA emits an explicit ``copy`` — which
  we count — whenever it cannot alias);
* a buffer is live from its defining instruction through its last use;
  parameters are live for the entire program (their buffers belong to
  the caller and cannot be reused without donation);
* donated parameters (``input_output_alias``) stay live to the end —
  their buffer IS the output — and the aliased output instruction
  allocates nothing (XLA writes it in place);
* the ROOT's buffers are live through the end (they are the result);
* called computations (``while`` bodies, ``call``/``conditional``
  targets) add their own internal peak at the call site — parameters
  excluded, those alias the caller's operand buffers, and an in-place
  update of a parameter slot (same byte count, e.g. the
  dynamic-update-slice a branch applies to the carried work array)
  reuses that slot's caller buffer rather than allocating. Fusion
  computations are NOT descended into: a fusion's intermediates live in
  registers/scratch by construction, its output is the fusion
  instruction's own result buffer.

Peak = max over program positions of the live-byte sum. This
over-estimates real HBM when XLA's buffer assignment reuses a dead
buffer's allocation for a same-sized new one mid-program (we free at
last use too, but do not model cross-buffer slot reuse beyond that) and
under-estimates nothing structural — which is the right polarity for a
go/no-go gate.

Dependency-light like the rest of analysis/: plain text, no jax, so
``scripts/tpulint spmd`` runs it anywhere.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .hlo import (Computation, Instruction, input_output_aliases,
                  parse_computations)

#: result-is-a-view opcodes: no fresh allocation. ``while`` belongs
#: here because XLA updates the carried tuple in place (operand and
#: result shapes are required to match); its result aliases its operand.
#: ``conditional`` aliases its branch operands: one branch runs, its
#: result shares the operand buffers (an explicit ``copy`` appears in
#: the HLO wherever XLA cannot alias, and copies ARE counted).
_NO_ALLOC = ("tuple", "get-tuple-element", "bitcast", "after-all",
             "add-dependency", "while", "conditional")

#: single-operand view ops whose result IS the operand's buffer(s)
_VIEW_OF_FIRST = ("get-tuple-element", "bitcast", "add-dependency",
                  "while")

#: opcodes whose attrs name computations that execute at the call site
_CALL_ATTRS = ("to_apply=", "body=", "condition=", "branch_computations=")


@dataclasses.dataclass
class MemoryEstimate:
    """Static per-chip memory picture of one compiled program."""
    peak_bytes: int                  # max live bytes at any program point
    argument_bytes: int              # entry parameter buffers
    output_bytes: int                # ROOT buffers (donated bytes excluded)
    largest: List[Tuple[str, int]]   # top buffers by size, for attribution

    def to_json(self) -> dict:
        return {"peak_bytes": self.peak_bytes,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "largest": [list(kv) for kv in self.largest]}


def _called_names(instr: Instruction) -> List[str]:
    """Computation names an instruction executes (while/call/conditional)."""
    out: List[str] = []
    for attr in _CALL_ATTRS:
        at = instr.raw.find(attr)
        if at < 0:
            continue
        rest = instr.raw[at + len(attr):]
        if rest.startswith("{"):
            rest = rest[1:rest.find("}")]
        else:
            rest = rest.split(",", 1)[0]
        for tok in rest.split(","):
            tok = tok.strip().lstrip("%")
            if tok:
                out.append(tok)
    return out


def _alias_roots(comp: Computation, inplace: Dict[str, int],
                 carry_body: bool) -> Dict[str, Tuple[str, ...]]:
    """Each name -> the allocation-root buffer name(s) it views.

    View ops (get-tuple-element / bitcast / while) resolve to their
    operand's roots; ``tuple`` aggregates every operand's roots.
    ``inplace`` maps update-in-place roots (donated parameters, while
    carry slots) to their byte size: an instruction consuming such a
    root and producing the SAME byte count is an in-place update — its
    result IS that buffer (XLA's donation/while-carry aliasing; when it
    cannot alias, it inserts a copy and the live set still holds one
    version, which is what this models). Under ``carry_body`` (walking a
    computation a ``while`` executes), each get-tuple-element of the
    carry parameter becomes its own in-place root — the per-slot caller
    buffers the body updates.
    """
    mapping: Dict[str, Tuple[str, ...]] = {}
    params = {i.name for i in comp.instructions if i.opcode == "parameter"}

    def of(name: str) -> Tuple[str, ...]:
        return mapping.get(name, (name,))

    for instr in comp.instructions:
        if carry_body and instr.opcode == "get-tuple-element" \
                and instr.operand_names \
                and instr.operand_names[0] in params:
            mapping[instr.name] = (instr.name,)
            inplace[instr.name] = instr.result_bytes
            continue
        if instr.opcode in _VIEW_OF_FIRST and instr.operand_names:
            mapping[instr.name] = of(instr.operand_names[0])
            continue
        if instr.opcode in ("tuple", "conditional"):
            # tuple: aggregate view of every operand. conditional: the
            # result aliases whichever branch operand ran — union both
            # (liveness merges; at most one version exists at runtime).
            roots: List[str] = []
            for op in instr.operand_names:
                roots.extend(of(op))
            mapping[instr.name] = tuple(dict.fromkeys(roots))
            continue
        tgt = None
        for op in instr.operand_names:
            for r in of(op):
                if inplace.get(r) == instr.result_bytes:
                    tgt = r
                    break
            if tgt:
                break
        mapping[instr.name] = (tgt,) if tgt else (instr.name,)
    return mapping


def _walk(comp: Computation, by_name: Dict[str, Computation],
          cache: Dict[Tuple[str, bool], int], *,
          zero_alloc: Set[str] = frozenset(),
          pinned: Set[str] = frozenset(),
          inplace: Optional[Dict[str, int]] = None,
          carry_body: bool = False, initial_live: int = 0,
          stack: Tuple[str, ...] = ()
          ) -> Tuple[int, Dict[str, int]]:
    """Liveness walk over one computation.

    Returns ``(peak_bytes, effective_size_by_name)``. ``zero_alloc``
    names allocate nothing (donation-aliased outputs); ``pinned`` names
    are never freed (donated parameters). ROOT buffers are never freed.
    Liveness is tracked on allocation ROOTS, so a buffer viewed through
    tuple/get-tuple-element/while chains stays live as long as any view
    of it is still used, and in-place updates of donated/carried
    buffers (see :func:`_alias_roots`) allocate nothing.
    """
    roots = _alias_roots(comp, dict(inplace or {}), carry_body)
    eff: Dict[str, int] = {}
    for instr in comp.instructions:
        if instr.opcode == "parameter" or instr.opcode in _NO_ALLOC \
                or instr.name in zero_alloc \
                or roots.get(instr.name) != (instr.name,):
            eff[instr.name] = 0
        else:
            eff[instr.name] = instr.result_bytes
    # last use per ROOT: any reference to any view of the root counts
    ends: Dict[str, int] = {}
    for idx, instr in enumerate(comp.instructions):
        for r in roots.get(instr.name, (instr.name,)):
            ends[r] = idx
        for op in instr.operand_names:
            for r in roots.get(op, (op,)):
                ends[r] = idx
    root = comp.root
    immortal = set(pinned)
    if root is not None:
        immortal.update(roots.get(root.name, (root.name,)))
    freed_at: Dict[int, int] = {}
    for instr in comp.instructions:
        if instr.name in immortal or not eff[instr.name]:
            continue
        end = ends.get(instr.name)
        if end is not None:
            freed_at[end] = freed_at.get(end, 0) + eff[instr.name]
    live = initial_live
    peak = live
    for idx, instr in enumerate(comp.instructions):
        called = 0
        if instr.opcode != "fusion":
            for name in _called_names(instr):
                sub = by_name.get(name)
                if sub is not None:
                    # every called computation's parameters alias the
                    # caller's operand buffers, so same-size updates of
                    # a parameter slot are in-place there (carry_body)
                    # — while bodies, conditional branches and call
                    # targets alike
                    called = max(called, _transient(
                        sub, by_name, cache, stack + (comp.name,),
                        carry_body=True))
        live += eff[instr.name]
        peak = max(peak, live + called)
        live -= freed_at.get(idx, 0)
    return peak, eff


def _transient(comp: Computation, by_name: Dict[str, Computation],
               cache: Dict[Tuple[str, bool], int],
               stack: Tuple[str, ...] = (), carry_body: bool = False
               ) -> int:
    """Internal peak of a called computation (its parameters alias the
    caller's operand buffers, so they count nothing here)."""
    key = (comp.name, carry_body)
    if key in cache:
        return cache[key]
    if comp.name in stack:      # defensive: HLO computations cannot recurse
        return 0
    peak, _ = _walk(comp, by_name, cache, stack=stack,
                    carry_body=carry_body)
    cache[key] = peak
    return peak


def estimate(hlo_text: str, top: int = 8) -> MemoryEstimate:
    """Peak-HBM estimate of a compiled module's entry computation."""
    comps = parse_computations(hlo_text)
    by_name = {c.name: c for c in comps}
    entry = next((c for c in comps if c.is_entry), None)
    if entry is None or not entry.instructions:
        return MemoryEstimate(0, 0, 0, [])
    aliases = input_output_aliases(hlo_text)
    root = entry.root
    params: Dict[int, Instruction] = {}
    for instr in entry.instructions:
        if instr.opcode == "parameter":
            num = instr.raw.rsplit("parameter(", 1)[-1].split(")", 1)[0]
            try:
                params[int(num)] = instr
            except ValueError:
                pass
    donated = {params[p].name for p in aliases.values() if p in params}
    # output instructions whose buffer reuses a donated input: the root
    # itself for a non-tuple alias ({}), else the root's n-th operand
    aliased_out: Set[str] = set()
    if root is not None:
        for out_idx in aliases:
            if not out_idx:
                aliased_out.add(root.name)
            elif root.opcode == "tuple" and out_idx[0] < len(
                    root.operand_names):
                aliased_out.add(root.operand_names[out_idx[0]])

    arg_bytes = sum(p.result_bytes for p in params.values())
    cache: Dict[Tuple[str, bool], int] = {}
    inplace = {params[p].name: params[p].result_bytes
               for p in aliases.values() if p in params}
    peak, eff = _walk(entry, by_name, cache, zero_alloc=aliased_out,
                      pinned=donated, inplace=inplace,
                      initial_live=arg_bytes)
    sizes = {p.name: p.result_bytes for p in params.values()}
    sizes.update({n: b for n, b in eff.items() if b})
    out_bytes = 0
    if root is not None:
        if root.opcode == "tuple":
            out_bytes = sum(
                sizes.get(op, 0) for op in root.operand_names
                if op not in donated)
        elif root.name not in aliased_out:
            out_bytes = root.result_bytes
    largest = sorted(sizes.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
    return MemoryEstimate(peak, arg_bytes, out_bytes, largest)


def render_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


#: default headroom over a fresh estimate when no budget was recorded
BUDGET_SLACK = 1.25
_BUDGET_QUANTUM = 4096


def default_budget(peak_bytes: int) -> int:
    raw = int(peak_bytes * BUDGET_SLACK)
    return -(-raw // _BUDGET_QUANTUM) * _BUDGET_QUANTUM


def contract_block(hlo_text: str, budget_bytes: Optional[int] = None,
                   prior: Optional[dict] = None) -> dict:
    """One contract ``memory[mesh]`` block (hlo_check/spmd_check schema).

    Budgets are STICKY: an existing recorded budget is kept verbatim —
    an estimate growing past it fails ``check`` until a human raises it
    deliberately — else ``budget_bytes`` (the go/no-go gates' hard
    caps), else the fresh estimate plus default slack."""
    est = estimate(hlo_text)
    budget = int((prior or {}).get("budget_bytes")
                 or budget_bytes or default_budget(est.peak_bytes))
    return {
        "budget_bytes": budget,
        "estimate_bytes": est.peak_bytes,
        "headroom_bytes": budget - est.peak_bytes,
        "argument_bytes": est.argument_bytes,
        "output_bytes": est.output_bytes,
    }
