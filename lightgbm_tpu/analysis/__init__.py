"""Static analysis + runtime guard rails for the TPU training stack.

Two halves (see ISSUE/README "Static analysis & runtime guards"):

  * :mod:`lightgbm_tpu.analysis.tpulint` — an AST pass with repo-specific
    hazard rules (R001-R005), run by ``scripts/tpulint`` and by the tier-1
    suite (tests/test_tpulint.py). Import is dependency-light: the static
    half never imports jax.
  * :mod:`lightgbm_tpu.analysis.guards` — runtime assertions (recompile
    counter, host-transfer guard) for steady-state training regions;
    imports jax, so it is imported lazily here.
"""
from .tpulint import lint_paths, load_allowlist, main  # noqa: F401


def __getattr__(name):
    if name in ("compile_counter", "no_host_transfers",
                "steady_state_guard", "CompileCount", "HostTransferError"):
        from . import guards
        return getattr(guards, name)
    raise AttributeError(name)
