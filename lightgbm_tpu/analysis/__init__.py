"""Static analysis + runtime guard rails for the TPU training stack.

Three layers (see README "Static analysis & runtime guards" and "HLO
contracts & concurrency sanitizer"):

  * :mod:`lightgbm_tpu.analysis.tpulint` — an AST pass with repo-specific
    hazard rules (R001-R007), run by ``scripts/tpulint`` and by the tier-1
    suite (tests/test_tpulint.py). Import is dependency-light: the static
    half never imports jax.
  * :mod:`lightgbm_tpu.analysis.hlo_check` — post-lowering verification of
    the compiled step programs against the checked-in learner-mode
    contracts (``analysis/contracts/*.json``): collective inventory and
    byte budgets, zero host ops, int32-accumulating integer dots, stable
    program fingerprints. The text parser it shares with
    ``parallel/comm_accounting.py`` is :mod:`lightgbm_tpu.analysis.hlo`.
  * :mod:`lightgbm_tpu.analysis.guards` — runtime assertions (recompile
    counter, host-transfer guard, API race sanitizer) for steady-state
    training regions; imports jax, so it is imported lazily here.
"""
from .tpulint import lint_paths, load_allowlist, main  # noqa: F401


def __getattr__(name):
    if name in ("compile_counter", "no_host_transfers",
                "steady_state_guard", "CompileCount", "HostTransferError",
                "api_race_sanitizer", "ApiRaceSanitizer", "ApiRaceError"):
        from . import guards
        return getattr(guards, name)
    raise AttributeError(name)
