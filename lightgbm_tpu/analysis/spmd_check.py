"""spmd_check — the pod-scale static flight check (no hardware needed).

ROADMAP 2 wants the full 13.2M x 4228 Allstate on >= 8 chips, but r05
died before a single at-scale program ever ran, and every pod failure
mode we can actually hit is a *static* property of the lowered HLO under
a faked mesh:

* **accidental replication** — a row-sharded operand (the bin matrix,
  the per-row gradient/score vectors) silently lowered as replicated
  multiplies per-chip HBM by the mesh size and usually OOMs at
  allocation; after SPMD partitioning the per-chip program's parameter
  shapes carry the answer (a healthy program only ever sees
  ``rows/num_shards``);
* **per-chip HBM overflow** — the reference trains full Allstate in
  ~1 GB/rank because the bin matrix is the only O(rows) resident
  (docs/Experiments.rst); our equivalent budget is verified by the
  buffer-liveness walk in ``analysis/memory.py`` over the SAME per-chip
  lowering, gated at 16 GiB/chip for the pod shape;
* **rank-divergent collective schedules** — the reference fixes a
  per-rank collective schedule at InitTrain (``src/network/``); under
  GSPMD the schedule is the program's collective instruction sequence,
  and divergence shows up statically as replica groups that do not
  cover every partition exactly once, or as unequal per-rank payloads.
  (Python-level divergence — rank-dependent branches reaching a
  collective — is R010's half, rules/r010_divergence.py.)

The harness lowers the four distributed learner-mode step programs
(``data_scatter``, ``voting`` and their ``tpu_hist_overlap`` twins) and
the GSPMD row-sharded serving dispatch under faked N-chip meshes
(``tpu_mesh_shape``: 4 / 8 / 32 chips, 1-D row and 2-D row x feature),
on the CPU backend — exactly how hlo_check captures the native
contracts. Checked-in facts live in the contract files
(analysis/contracts/*.json):

    "spmd":   {"<mesh>": {"collectives": [...],       # allowed inventory
                          "schedule": [[kind, bytes_per_rank], ...]}}
    "memory": {"<mesh>": {"budget_bytes": ..., "estimate_bytes": ...,
                          "headroom_bytes": ..., ...}}

``check`` fails on: a replicated row-proportional parameter, a
collective kind absent from the mesh's inventory (implicit
all-gather/resharding inserted by a sharding change), replica groups
that miss or double-count a rank, per-rank schedule drift against the
recorded sequence, and a memory estimate above the recorded budget.
``--update`` re-records the spmd/memory blocks (budgets are sticky:
set once, they only move when edited deliberately).

The pod go/no-go gate itself (``FLIGHT_SHAPES["allstate_pod"]``) trains
a tiny 512-row booster at the REAL feature width (4228, pack4-nibbled),
then AOT-relowers the captured step at 13.2M rows via
``GBDT.aot_lower_program`` — abstract shapes only, so the full-scale
per-chip program compiles on this host in seconds and its memory walk
answers the 16 GiB question before a chip is rented.

CLI: ``scripts/tpulint spmd [--mesh NxM] [--update] [mode ...]``;
tier-1 runs the 4-chip check + the allstate gate in
tests/test_spmd_check.py (32-chip and 2-D sweeps are slow-lane).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import memory
from .hlo import (collective_bytes, collective_kind,
                  collective_payload_shapes, entry_computation,
                  num_partitions, parse_instructions, replica_groups_of,
                  tensor_bytes)
from .hlo_check import (MODE_TEMPLATES, ContractFinding, capture_mode,
                        check_host_ops, contract_path, load_contract)

#: the distributed learner-mode step programs the flight check covers
FLIGHT_MODES = ("data_scatter", "voting", "data_scatter_overlap",
                "voting_overlap")

#: fake-mesh matrix: 1-D row meshes and 2-D row x feature folds
FLIGHT_MESHES = ("4", "8", "32", "4x2", "8x4")

#: the fast-lane default (tier-1 + bare CLI): one non-native mesh size
DEFAULT_MESHES = ("4",)

#: the pod-run go/no-go shapes, AOT-relowered at full scale.
#: allstate_pod: the full Allstate claim_prediction matrix
#: (docs/Experiments.rst:121 — 13.2M rows x 4228 mostly-one-hot
#: columns), data-parallel compact grower with reduce-scatter histograms
#: and pack4 nibble bins (one-hot columns realize <= 16 bins, and
#: WITHOUT pack4 the u8 work+scratch pair alone busts 16 GiB/chip).
FLIGHT_SHAPES: Dict[str, dict] = {
    "allstate_pod": {
        "description": "full-Allstate pod shape: 13.2M x 4228 one-hot, "
                       "8 chips, data-parallel compact grower, "
                       "reduce-scatter histograms, pack4 nibble bins — "
                       "the static go/no-go gate for ROADMAP 2's pod run "
                       "at 16 GiB/chip",
        "base_mode": "data_scatter",
        "extra_params": {"tpu_bin_pack4": True},
        "program": "compact_step_k0",
        "rows": 13_200_000,
        "mesh": "8",
        "budget_bytes": 16 * (1 << 30),
        "problem": {"n": 512, "f": 4228, "seed": 0},
    },
}


def mesh_shape_of(key: str) -> Tuple[int, ...]:
    return tuple(int(p) for p in key.lower().split("x"))


def mesh_devices(key: str) -> int:
    n = 1
    for d in mesh_shape_of(key):
        n *= d
    return n


def is_2d(key: str) -> bool:
    return len(mesh_shape_of(key)) == 2


@dataclasses.dataclass
class FlightCapture:
    """One lowered (mode, mesh) program plus the facts checks need."""
    mode: str
    mesh_key: str
    program: str
    hlo_text: str
    row_dims: Set[int]       # GLOBAL row-proportional dims (forbidden
    #                          in per-chip parameter shapes when S > 1)
    num_shards: int          # row shards (the mesh's data-axis size)
    gbdt: object = None      # the trained booster (verify_flight reuses
    #                          the voting one for the serving dispatch)


def flight_template(mode: str, mesh_key: str) -> dict:
    """The mode's MODE_TEMPLATE adjusted to lower under ``mesh_key``.

    2-D meshes run the masked GSPMD grower for the data modes: the
    compact grower's shard_map physically owns the row axis only, while
    the masked path's bin matrix shards over BOTH axes
    (``row_feature_sharding``) — which is the whole point of the 2-D
    fold for the wide one-hot shape.
    """
    t = dict(MODE_TEMPLATES[mode])
    params = dict(t["params"], tpu_mesh_shape=mesh_key)
    if is_2d(mesh_key) and params.get("tpu_grower") == "compact":
        params["tpu_grower"] = "masked"
        t["program"] = "step"
    t["params"] = params
    t["num_devices"] = mesh_devices(mesh_key)
    return t


def _capture_rows(gbdt) -> Tuple[Set[int], int]:
    """(global row-proportional dims, row shards) of a trained GBDT."""
    from ..parallel.mesh import mesh_axis_sizes
    s_rows = mesh_axis_sizes(gbdt.mesh)[0] if gbdt.mesh is not None else 1
    dims = {int(gbdt.num_data)}
    c = getattr(gbdt, "_compact", None)
    if c and c.get("work") is not None:
        dims.add(int(c["work"].shape[0]))
    return dims, s_rows


def capture_flight(mode: str, mesh_key: str, iterations: int = 2
                   ) -> FlightCapture:
    t = flight_template(mode, mesh_key)
    cap = capture_mode(mode, template=t, iterations=iterations)
    row_dims, s_rows = _capture_rows(cap.gbdt)
    return FlightCapture(mode, mesh_key, t["program"], cap.hlo_text,
                         row_dims, s_rows, gbdt=cap.gbdt)


# ---------------------------------------------------------------------------
# checks (pure text; no jax)
# ---------------------------------------------------------------------------
def check_row_replication(hlo_text: str, row_dims: Set[int],
                          num_shards: int, mode: str, mesh_key: str
                          ) -> List[ContractFinding]:
    """A per-chip program parameter carrying a GLOBAL row dimension is a
    replicated row-proportional operand — the accidental-replication OOM.

    Scoped to entry parameters (the program's resident operands): the
    bin matrix / gradients / scores arrive as parameters, and fusion
    bodies may legally flatten per-shard tensors into products that
    collide with the global row count.
    """
    if num_shards <= 1:
        return []
    entry = entry_computation(hlo_text)
    if entry is None:
        return []
    out: List[ContractFinding] = []
    for instr in entry.instructions:
        if instr.opcode != "parameter":
            continue
        bad = sorted(set(instr.result_dims) & row_dims)
        if bad:
            out.append(ContractFinding(
                mode, "spmd-replication",
                f"mesh {mesh_key}: parameter '{instr.name}' carries the "
                f"GLOBAL row dimension {bad[0]} in the per-chip program "
                f"(shapes {instr.result_shapes}) — a row-proportional "
                f"operand lowered as replicated costs {num_shards}x its "
                "sharded footprint per chip and OOMs the pod at "
                "allocation; fix the in_sharding/device_put of this "
                "operand (parallel/mesh.py row shardings)"))
    return out


def schedule_of(hlo_text: str) -> List[List[Any]]:
    """The per-rank collective schedule: ``[kind, bytes_per_rank]`` in
    program order. Under SPMD every rank runs the same sequence; the
    per-rank payload is the instruction's (already per-shard) result."""
    out: List[List[Any]] = []
    for instr in parse_instructions(hlo_text):
        kind = collective_kind(instr.opcode)
        if kind is None or instr.opcode.endswith("-done"):
            continue
        nbytes = sum(tensor_bytes(d, dims)
                     for d, dims in collective_payload_shapes(instr))
        out.append([kind, nbytes])
    return out


def check_rank_schedule(hlo_text: str, mode: str, mesh_key: str
                        ) -> List[ContractFinding]:
    """Replica-group sanity of every collective: the groups must cover
    each partition exactly once and be uniformly sized — a missing rank
    deadlocks the pod (it never joins), a double-counted rank or ragged
    group sizes mean the per-rank sequences disagree on bytes."""
    nparts = num_partitions(hlo_text)
    out: List[ContractFinding] = []
    for instr in parse_instructions(hlo_text):
        kind = collective_kind(instr.opcode)
        if kind is None or instr.opcode.endswith("-done"):
            continue
        groups = replica_groups_of(instr)
        if not groups:           # absent/empty = one implicit all-ranks group
            continue
        seen: Dict[int, int] = {}
        for grp in groups:
            for r in grp:
                seen[r] = seen.get(r, 0) + 1
        missing = sorted(set(range(nparts)) - set(seen))
        doubled = sorted(r for r, c in seen.items() if c > 1)
        if missing or doubled:
            out.append(ContractFinding(
                mode, "spmd-schedule",
                f"mesh {mesh_key}: '{instr.opcode}' at HLO line "
                f"{instr.line} has replica_groups covering "
                f"{sorted(seen)} of {nparts} partitions"
                + (f" (missing {missing})" if missing else "")
                + (f" (duplicated {doubled})" if doubled else "")
                + " — a rank outside the groups never joins this "
                "collective and the pod deadlocks at its first tree"))
        sizes = {len(g) for g in groups}
        if len(sizes) > 1:
            out.append(ContractFinding(
                mode, "spmd-schedule",
                f"mesh {mesh_key}: '{instr.opcode}' at HLO line "
                f"{instr.line} has ragged replica groups (sizes "
                f"{sorted(sizes)}) — per-rank transfer bytes differ "
                "across the pod, so the fixed per-rank schedule no "
                "longer holds"))
    return out


def check_inventory(hlo_text: str, contract: dict, mode: str,
                    mesh_key: str) -> List[ContractFinding]:
    """Collective kinds must stay inside the mesh's recorded inventory
    (falling back to the native ``collectives.allow``): an implicit
    all-gather/resharding inserted by a sharding change is cross-chip
    traffic nobody budgeted."""
    spmd = contract.get("spmd", {}).get(mesh_key)
    allow = set(spmd["collectives"]) if spmd \
        else set(contract.get("collectives", {}).get("allow", []))
    acct = collective_bytes(hlo_text)
    observed = {k for k, v in acct.items()
                if k not in ("total", "count") and v > 0}
    out: List[ContractFinding] = []
    for kind in sorted(observed - allow):
        out.append(ContractFinding(
            mode, "spmd-inventory",
            f"mesh {mesh_key}: collective '{kind}' "
            f"({acct[kind]} B) is not in the contract inventory "
            f"({sorted(allow) or 'none'}) — an implicit "
            "all-gather/resharding crept into the step program; if the "
            "sharding change is deliberate, re-record with "
            "scripts/tpulint spmd --update"))
    return out


def check_schedule_drift(hlo_text: str, contract: dict, mode: str,
                         mesh_key: str) -> List[ContractFinding]:
    spmd = contract.get("spmd", {}).get(mesh_key)
    if not spmd or "schedule" not in spmd:
        return []
    fresh = schedule_of(hlo_text)
    recorded = [list(x) for x in spmd["schedule"]]
    if fresh == recorded:
        return []
    return [ContractFinding(
        mode, "spmd-schedule",
        f"mesh {mesh_key}: per-rank collective schedule drifted — "
        f"recorded {recorded}, lowered {fresh} (kind, bytes-per-rank, "
        "program order). Comm protocol changes must be re-recorded "
        "(scripts/tpulint spmd --update) and reviewed")]


def check_flight_memory(hlo_text: str, contract: dict, mode: str,
                        mesh_key: str) -> List[ContractFinding]:
    """Budget regression: the walk's estimate must stay under the
    contract's recorded per-chip budget for this mesh."""
    block = contract.get("memory", {}).get(mesh_key)
    if not block:
        return []
    est = memory.estimate(hlo_text)
    budget = int(block["budget_bytes"])
    if est.peak_bytes <= budget:
        return []
    top = ", ".join(f"{name}={memory.render_bytes(b)}"
                    for name, b in est.largest[:3])
    return [ContractFinding(
        mode, "memory",
        f"mesh {mesh_key}: static per-chip peak "
        f"{memory.render_bytes(est.peak_bytes)} exceeds the "
        f"{memory.render_bytes(budget)} budget (recorded estimate was "
        f"{memory.render_bytes(int(block.get('estimate_bytes', 0)))}; "
        f"largest buffers: {top}) — a pod run at this shape would OOM "
        "at allocation. Shrink the resident state (pack4/quantized "
        "bins, smaller mbatch) or raise budget_bytes deliberately in "
        "the contract's memory block")]


def check_flight(cap: FlightCapture, contract: dict
                 ) -> List[ContractFinding]:
    """All static checks for one lowered (mode, mesh) program."""
    return (check_row_replication(cap.hlo_text, cap.row_dims,
                                  cap.num_shards, cap.mode, cap.mesh_key)
            + check_rank_schedule(cap.hlo_text, cap.mode, cap.mesh_key)
            + check_inventory(cap.hlo_text, contract, cap.mode,
                              cap.mesh_key)
            + check_schedule_drift(cap.hlo_text, contract, cap.mode,
                                   cap.mesh_key)
            + check_flight_memory(cap.hlo_text, contract, cap.mode,
                                  cap.mesh_key)
            + check_host_ops(cap.hlo_text,
                             {"mode": cap.mode, "forbid_host_ops":
                              contract.get("forbid_host_ops", True)}))


# ---------------------------------------------------------------------------
# recording (--update)
# ---------------------------------------------------------------------------
def record_blocks(name: str, mesh_key: str, hlo_text: str,
                  budget_bytes: Optional[int] = None,
                  description: Optional[str] = None) -> dict:
    """Write/refresh one contract file's spmd+memory blocks for a mesh."""
    path = contract_path(name)
    data: dict = {}
    if os.path.exists(path):
        with open(path) as fh:
            data = json.load(fh)
    data.setdefault("mode", name)
    if description and "description" not in data:
        data["description"] = description
    acct = collective_bytes(hlo_text)
    data.setdefault("spmd", {})[mesh_key] = {
        "collectives": sorted(k for k, v in acct.items()
                              if k not in ("total", "count") and v > 0),
        "schedule": schedule_of(hlo_text),
    }
    prior = data.get("memory", {}).get(mesh_key)
    data.setdefault("memory", {})[mesh_key] = memory.contract_block(
        hlo_text, budget_bytes, prior)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return data


# ---------------------------------------------------------------------------
# the harness passes (import jax lazily through hlo_check.capture_mode)
# ---------------------------------------------------------------------------
def verify_flight(modes: Sequence[str] = FLIGHT_MODES,
                  meshes: Sequence[str] = DEFAULT_MESHES,
                  update: bool = False,
                  include_serving: bool = True,
                  include_shapes: bool = True) -> List[ContractFinding]:
    """The full flight check: every (mode, mesh) lowering verified (or
    re-recorded with ``update``), the serving dispatch lowered over the
    first mesh, and the FLIGHT_SHAPES go/no-go gates AOT-verified."""
    findings: List[ContractFinding] = []
    serving_gbdt = None
    for mode in modes:
        for mesh_key in meshes:
            cap = capture_flight(mode, mesh_key)
            if mode == "voting" and mesh_key == meshes[0] \
                    and not is_2d(mesh_key):
                # reuse this booster for the serving dispatch below
                # instead of training a second identical one
                serving_gbdt = cap.gbdt
            if update:
                record_blocks(mode, mesh_key, cap.hlo_text)
            contract = load_contract(mode) if os.path.exists(
                contract_path(mode)) else {}
            findings += check_flight(cap, contract)
    if include_serving:
        findings += verify_serving(meshes[0], update=update,
                                   gbdt=serving_gbdt)
    if include_shapes:
        for name in FLIGHT_SHAPES:
            findings += verify_flight_shape(name, update=update)
    return findings


def verify_serving(mesh_key: str, update: bool = False,
                   gbdt=None) -> List[ContractFinding]:
    """Lower the GSPMD row-sharded serving dispatch under a faked mesh
    and run the same static checks (its contract file is
    ``serving_sharded.json`` — spmd/memory blocks only)."""
    name = "serving_sharded"
    if is_2d(mesh_key):
        # serving shards rows only (row_sharding_2d); fold a 2-D key
        # down to its row factor so the ladder math stays honest
        mesh_key = str(mesh_shape_of(mesh_key)[0])
    if gbdt is None:
        t = flight_template("voting", mesh_key)
        cap = capture_mode("voting", template=t, iterations=2)
        gbdt = cap.gbdt
    from ..parallel.mesh import mesh_axis_sizes
    s_rows = mesh_axis_sizes(gbdt.mesh)[0]
    _, ladder, _ = gbdt._predict_cfg()
    n_rows = int(ladder[-1]) * s_rows        # top rung on every shard
    lowered = gbdt.aot_lower_sharded_predict(n_rows)
    text = lowered.compile().as_text()
    if update:
        record_blocks(
            name, mesh_key, text,
            description="GSPMD row-sharded serving dispatch "
                        "(predict_raw_device oversize branch): one "
                        "ladder-rung program per shard, no cross-chip "
                        "traffic beyond the final score layout")
    contract = load_contract(name) if os.path.exists(
        contract_path(name)) else {}
    cap = FlightCapture(name, mesh_key, "predict_raw_batched", text,
                        {n_rows}, s_rows)
    return check_flight(cap, contract)


def verify_flight_shape(name: str, update: bool = False
                        ) -> List[ContractFinding]:
    """AOT-verify one FLIGHT_SHAPES gate (the pod go/no-go): capture the
    step at a tiny row count but the REAL feature width, relower at the
    full row count, then run every static check at scale."""
    spec = FLIGHT_SHAPES[name]
    mesh_key = spec["mesh"]
    base = MODE_TEMPLATES[spec["base_mode"]]
    t = dict(base)
    t["params"] = dict(base["params"], tpu_mesh_shape=mesh_key,
                       **spec.get("extra_params", {}))
    t["program"] = spec["program"]
    t["num_devices"] = mesh_devices(mesh_key)
    t["problem"] = spec["problem"]
    cap = capture_mode(name, template=t, iterations=2)
    g = cap.gbdt
    dim_map = g.flight_row_dims(spec["rows"])
    text = g.aot_lower_program(spec["program"], dim_map).compile().as_text()
    if update:
        record_blocks(name, mesh_key, text,
                      budget_bytes=int(spec["budget_bytes"]),
                      description=spec["description"])
    contract = load_contract(name) if os.path.exists(
        contract_path(name)) else {}
    # the go/no-go budget is the spec's even when the file is absent
    contract.setdefault("memory", {}).setdefault(
        mesh_key, {"budget_bytes": int(spec["budget_bytes"])})
    row_dims, s_rows = set(dim_map.values()), _capture_rows(g)[1]
    fcap = FlightCapture(name, mesh_key, spec["program"], text,
                         row_dims, s_rows)
    return check_flight(fcap, contract)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI body for ``scripts/tpulint spmd`` (which sets the CPU
    platform + virtual device count env BEFORE jax imports)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="tpulint spmd",
        description="pod-scale static flight check: SPMD sharding, "
                    "per-chip memory and collective schedules under "
                    "faked meshes, on the CPU backend")
    ap.add_argument("modes", nargs="*", default=list(FLIGHT_MODES),
                    help=f"learner modes (default {list(FLIGHT_MODES)})")
    ap.add_argument("--mesh", action="append", default=None,
                    help="mesh key: N (1-D) or RxC (2-D rows x "
                         f"features); repeatable (default "
                         f"{list(DEFAULT_MESHES)}, full matrix "
                         f"{list(FLIGHT_MESHES)})")
    ap.add_argument("--update", action="store_true",
                    help="re-record the contracts' spmd/memory blocks "
                         "from the current lowering")
    ap.add_argument("--no-serving", action="store_true",
                    help="skip the sharded serving dispatch")
    ap.add_argument("--no-shapes", action="store_true",
                    help="skip the FLIGHT_SHAPES go/no-go gates")
    args = ap.parse_args(argv)
    modes = args.modes or list(FLIGHT_MODES)
    unknown = [m for m in modes if m not in FLIGHT_MODES]
    if unknown:
        print(f"spmd_check: unknown mode(s) {unknown}; "
              f"known: {list(FLIGHT_MODES)}")
        return 2
    meshes = tuple(args.mesh) if args.mesh else DEFAULT_MESHES
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    findings = verify_flight(modes, meshes, update=args.update,
                             include_serving=not args.no_serving,
                             include_shapes=not args.no_shapes)
    for f in findings:
        print(f.render())
    if not findings:
        what = f"{len(modes)} mode(s) x {list(meshes)}"
        print(f"spmd_check: flight check clean ({what}"
              + ("" if args.no_shapes else
                 f" + {list(FLIGHT_SHAPES)} go/no-go") + ")")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
