"""Compiled-HLO text parsing shared by comm accounting and hlo_check.

The reference budgets its distributed learners by hand-written message
sizes (ReduceScatter of per-feature histograms,
src/treelearner/data_parallel_tree_learner.cpp:223-300; voting-parallel
reduces only the elected top-2k features' histograms,
voting_parallel_tree_learner.cpp). Under GSPMD/shard_map the collectives
are inserted by XLA, so the honest measurement is to read them back out
of the compiled HLO. This module is the one parser for that text:
``parallel/comm_accounting.py`` sums collective bytes through it and
``analysis/hlo_check.py`` verifies whole-program contracts with it.

Deliberately dependency-light: plain string/regex work, no jax import, so
``scripts/tpulint`` can load it on hosts without a working backend.
"""
from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Dict, List, Optional, Tuple

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
}

# async forms (-start) are what post-optimization TPU HLO emits; each
# start/done pair counts once (the -done carries no shape of its own here)
_COLLECTIVES = ("all-reduce-start", "all-gather-start",
                "reduce-scatter-start", "collective-permute-start",
                "all-to-all-start", "all-reduce", "all-gather",
                "reduce-scatter", "collective-permute", "all-to-all")

# async ops whose transferred payload is the RESULT shape (second element of
# the (operand, result, ...) async tuple): all-gather's result is num_devices
# times the operand, so counting the operand under-reports the gathered
# bytes; reduce-scatter/all-to-all/collective-permute likewise carry the
# payload in the result slot (accounting convention: output bytes).
_RESULT_SHAPE_STARTS = ("all-gather-start", "reduce-scatter-start",
                        "collective-permute-start", "all-to-all-start")

#: ops that move data between host and device inside a program — a
#: steady-state jitted step must contain none of these
HOST_OPS = ("infeed", "outfeed", "send", "send-done", "recv", "recv-done")

#: custom-call targets that funnel back into host Python (jax callbacks)
HOST_CUSTOM_CALL_MARKERS = ("callback", "python", "host")

INT_NARROW = ("s8", "s16", "u8", "u16")

COLLECTIVE_KINDS = _COLLECTIVES

# one shaped tensor, e.g. f32[7,8,64]{2,1,0} — shapes can be scalar []
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")

# `%name = <result shape(s)> opcode(operands...), attrs` with optional ROOT
_INSTR_RE = re.compile(
    r"^(?:ROOT\s+)?%?([\w.\-]+)\s+=\s+(.*?)\s*([a-z][a-z0-9\-]*)\((.*)$")

# operand references inside the operand region: `f32[8]{0} %add.5, ...`
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def tensor_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


@dataclasses.dataclass
class Instruction:
    """One parsed HLO instruction line (post-optimization text form)."""
    name: str
    opcode: str
    result_shapes: List[Tuple[str, str]]   # [(dtype, "dims"), ...]
    operand_shapes: List[Tuple[str, str]]
    line: int                              # 1-based within the module text
    raw: str
    operand_names: List[str] = dataclasses.field(default_factory=list)

    @property
    def result_bytes(self) -> int:
        return sum(tensor_bytes(d, dims) for d, dims in self.result_shapes)

    @property
    def result_dims(self) -> List[int]:
        """Every result dimension, flattened across tuple elements."""
        out: List[int] = []
        for _, dims in self.result_shapes:
            out.extend(int(d) for d in dims.split(",") if d)
        return out


def _split_operands(rest: str) -> str:
    """The operand text of ``opcode(<operands>), attrs...`` (balanced)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def parse_instructions(hlo_text: str) -> List[Instruction]:
    """Parse every instruction line of compiled HLO text.

    Tolerant by construction: lines that are not instructions (module
    headers, computation braces, comments) are skipped, and shapes are
    extracted by pattern so layout annotations (``{2,1,0}``) and sharding
    attrs don't need a real grammar.
    """
    out: List[Instruction] = []
    for lineno, line in enumerate(hlo_text.splitlines(), 1):
        s = line.strip()
        if " = " not in s:
            continue
        m = _INSTR_RE.match(s)
        if m is None:
            continue
        name, head, opcode, rest = m.groups()
        operands = _split_operands(rest)
        out.append(Instruction(
            name=name, opcode=opcode,
            result_shapes=_SHAPE_RE.findall(head),
            operand_shapes=_SHAPE_RE.findall(operands),
            line=lineno, raw=s,
            operand_names=_OPERAND_NAME_RE.findall(operands)))
    return out


def collective_kind(opcode: str) -> Optional[str]:
    return opcode if opcode in _COLLECTIVES else None


def collective_payload_shapes(instr: Instruction) -> List[Tuple[str, str]]:
    """The shapes whose bytes a collective instruction transfers."""
    shapes = instr.result_shapes
    if instr.opcode.endswith("-start") and shapes:
        # async tuple output carries (operand, result, ...); count the
        # transferred payload once
        if instr.opcode in _RESULT_SHAPE_STARTS:
            # result shape (second tuple element); fall back to the
            # operand if the tuple was flattened to a single shape
            return shapes[1:2] if len(shapes) > 1 else shapes[:1]
        # all-reduce-start: operand and result shapes are identical
        return shapes[:1]
    return shapes


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Sum output bytes of every collective instruction in compiled HLO.

    Returns {kind: bytes, ..., "total": bytes, "count": n_instructions}.
    """
    out: Dict[str, int] = {k: 0 for k in _COLLECTIVES}
    count = 0
    for instr in parse_instructions(hlo_text):
        kind = collective_kind(instr.opcode)
        if kind is None:
            continue
        out[kind] += sum(tensor_bytes(d, dims)
                         for d, dims in collective_payload_shapes(instr))
        count += 1
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    out["count"] = count
    return out


# name suffixes XLA appends freely (%fusion.3, %dot.12) plus metadata and
# buffer-assignment noise that changes run to run without changing the
# program — stripped before fingerprinting
_ID_RE = re.compile(r"%([\w\-]+?)\.[0-9]+\b")
_METADATA_RE = re.compile(r",?\s*metadata=\{[^}]*\}")
_MODULE_RE = re.compile(r"^HloModule\s+\S+", re.MULTILINE)
_IDS_ATTR_RE = re.compile(r"\bid=\d+")


def canonicalize(hlo_text: str) -> str:
    """Compiled HLO text with unstable naming noise removed, so two
    lowerings of the SAME program fingerprint identically while any real
    change — a new collective, a dtype flip, a different loop body —
    changes the fingerprint."""
    text = _MODULE_RE.sub("HloModule _", hlo_text)
    text = _METADATA_RE.sub("", text)
    text = _ID_RE.sub(r"%\1", text)
    text = _IDS_ATTR_RE.sub("id=_", text)
    return "\n".join(ln.strip() for ln in text.splitlines() if ln.strip())


def fingerprint(hlo_text: str) -> str:
    """Stable short hash of a compiled program (see ``canonicalize``)."""
    return hashlib.sha256(canonicalize(hlo_text).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# computation structure + SPMD attributes (analysis/memory.py and
# analysis/spmd_check.py build on these; still plain text, no jax)
# ---------------------------------------------------------------------------
# `%comp.1 (p: f32[8]) -> f32[8] {` and `ENTRY %main.4 (...) -> ... {`
_COMP_HEAD_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{\s*$")

_REPLICA_GROUPS_LIST_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_REPLICA_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")
_ALIAS_RE = re.compile(r"\{([0-9, ]*)\}:\s*\((\d+)")


@dataclasses.dataclass
class Computation:
    """One HLO computation block: its instructions in program order."""
    name: str
    is_entry: bool
    instructions: List[Instruction]

    @property
    def root(self) -> Optional[Instruction]:
        for instr in reversed(self.instructions):
            if instr.raw.startswith("ROOT "):
                return instr
        return self.instructions[-1] if self.instructions else None


def parse_computations(hlo_text: str) -> List[Computation]:
    """Split module text into computations, instructions kept in order.

    The brace structure of post-optimization HLO text is flat — one
    ``name (params) -> result {`` header per computation, instructions
    until the closing ``}`` on its own line — so a line scan suffices;
    attribute braces (``sharding={...}``) never start a line.
    """
    out: List[Computation] = []
    current: Optional[Computation] = None
    for lineno, line in enumerate(hlo_text.splitlines(), 1):
        s = line.strip()
        if current is None:
            m = _COMP_HEAD_RE.match(s)
            if m is not None and " = " not in s:
                current = Computation(m.group(2), bool(m.group(1)), [])
            continue
        if s.startswith("}"):
            out.append(current)
            current = None
            continue
        if " = " not in s:
            continue
        m = _INSTR_RE.match(s)
        if m is None:
            continue
        name, head, opcode, rest = m.groups()
        operands = _split_operands(rest)
        current.instructions.append(Instruction(
            name=name, opcode=opcode,
            result_shapes=_SHAPE_RE.findall(head),
            operand_shapes=_SHAPE_RE.findall(operands),
            line=lineno, raw=s,
            operand_names=_OPERAND_NAME_RE.findall(operands)))
    if current is not None:       # unterminated block (fixture tolerance)
        out.append(current)
    return out


def entry_computation(hlo_text: str) -> Optional[Computation]:
    for comp in parse_computations(hlo_text):
        if comp.is_entry:
            return comp
    return None


def num_partitions(hlo_text: str) -> int:
    """SPMD partition count the module was compiled for (1 if absent)."""
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else 1


def input_output_aliases(hlo_text: str) -> Dict[Tuple[int, ...], int]:
    """Donation map {output tuple index: parameter number} from the
    module header's ``input_output_alias={ {0}: (0, {}, may-alias) }``."""
    _, sep, rest = hlo_text.partition("input_output_alias={")
    if not sep:
        return {}
    # the alias map is a flat `{out_idx}: (param, {param_idx}[, kind])`
    # sequence; the pair pattern (brace-list followed by a colon and an
    # opening paren) occurs nowhere else in the header line
    out: Dict[Tuple[int, ...], int] = {}
    for om, pm in _ALIAS_RE.findall(rest.split("\n", 1)[0]):
        idx = tuple(int(x) for x in om.replace(" ", "").split(",") if x)
        out[idx] = int(pm)
    return out


def replica_groups_of(instr: Instruction) -> Optional[List[List[int]]]:
    """Partition groups of a collective instruction, resolved to explicit
    id lists. Handles both the literal form ``{{0,1},{2,3}}`` and the
    iota form ``[2,2]<=[4]`` (optionally transposed, ``<=[2,2]T(1,0)``).
    Returns None when the instruction carries no replica_groups attr;
    ``[]`` (one implicit all-ranks group) is returned as ``[]``.
    """
    m = _REPLICA_GROUPS_LIST_RE.search(instr.raw)
    if m is not None:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(ids)
        return groups
    m = _REPLICA_GROUPS_IOTA_RE.search(instr.raw)
    if m is not None:
        dims = [int(x) for x in m.group(1).split(",")]
        reshape = [int(x) for x in m.group(2).split(",")]
        total = 1
        for d in reshape:
            total *= d
        ids = list(range(total))
        if m.group(3):
            # iota over `reshape`, transposed by T(perm), flattened
            perm = [int(x) for x in m.group(3).split(",")]
            strides = [0] * len(reshape)
            acc = 1
            for i in range(len(reshape) - 1, -1, -1):
                strides[i] = acc
                acc *= reshape[i]
            tdims = [reshape[p] for p in perm]
            tstrides = [strides[p] for p in perm]
            ids = []
            idx = [0] * len(tdims)
            for _ in range(total):
                ids.append(sum(i * s for i, s in zip(idx, tstrides)))
                for ax in range(len(tdims) - 1, -1, -1):
                    idx[ax] += 1
                    if idx[ax] < tdims[ax]:
                        break
                    idx[ax] = 0
        rows, cols = dims[0], 1
        for d in dims[1:]:
            cols *= d
        return [ids[r * cols:(r + 1) * cols] for r in range(rows)]
    if "replica_groups" in instr.raw:
        return []
    return None
