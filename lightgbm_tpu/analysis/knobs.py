"""Dead-knob lint: config schema vs. code vs. docs (tpulint ``knobs``).

Every ``tpu_*`` key in config.py's PARAMS table must be (a) READ
somewhere in the package — a knob nothing consults is dead weight that
silently no-ops for users who set it — and (b) documented in README's
knob docs, because config/doc drift is the static-analysis analogue of
contract drift (the HLO and collective contracts get the same
treatment from hlo_check/spmd_check). Pure text/AST, jax-free.

The read check matches the literal key string (``"tpu_x"``) anywhere in
package sources outside config.py: every consumer goes through
``cfg.get("tpu_x", ...)`` or ``config["tpu_x"]``, so a knob whose name
appears nowhere else is unread. README must mention the key name
verbatim (the docs render them in backticks, but any mention counts).
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tpu_params(config_path: str) -> Dict[str, int]:
    """``tpu_*`` keys in PARAMS -> definition line, via AST (no import:
    the schema is a module-level dict literal by design)."""
    with open(config_path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=config_path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "PARAMS" and \
                    isinstance(node.value, ast.Dict):
                out: Dict[str, int] = {}
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            k.value.startswith("tpu_"):
                        out[k.value] = k.lineno
                return out
    raise RuntimeError(f"no module-level PARAMS dict in {config_path}")


def check_knobs(package_dir: Optional[str] = None,
                readme_path: Optional[str] = None
                ) -> Tuple[List[str], Dict[str, int]]:
    """(problem lines, knob->def line). Empty problems == no drift."""
    pkg = package_dir or _package_dir()
    config_path = os.path.join(pkg, "config.py")
    if readme_path is None:
        readme_path = os.path.join(os.path.dirname(pkg), "README.md")
    knobs = tpu_params(config_path)

    sources: List[str] = []
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            path = os.path.join(root, name)
            if name.endswith(".py") and \
                    os.path.abspath(path) != os.path.abspath(config_path):
                try:
                    with open(path, encoding="utf-8") as fh:
                        sources.append(fh.read())
                except (OSError, UnicodeDecodeError):
                    pass
    code = "\n".join(sources)
    try:
        with open(readme_path, encoding="utf-8") as fh:
            readme = fh.read()
    except OSError:
        readme = ""

    problems: List[str] = []
    rel_config = os.path.relpath(config_path)
    for knob, line in sorted(knobs.items()):
        if knob not in code:
            problems.append(
                f"{rel_config}:{line}: knob {knob} is never read in the "
                "package — dead weight that silently no-ops for users "
                "who set it; read it or drop it from PARAMS")
        if knob not in readme:
            problems.append(
                f"{rel_config}:{line}: knob {knob} is undocumented in "
                f"{os.path.relpath(readme_path)} — config/doc drift; "
                "add it to the knob docs")
    return problems, knobs


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="tpulint knobs",
        description="tpu_* config keys must be read in the package and "
                    "documented in README (dead-knob / doc-drift lint)")
    ap.add_argument("--package", default=None,
                    help="package directory (default: lightgbm_tpu)")
    ap.add_argument("--readme", default=None,
                    help="README path (default: next to the package)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    try:
        problems, knobs = check_knobs(args.package, args.readme)
    except (OSError, RuntimeError, SyntaxError) as err:
        print(f"tpulint knobs: error: {err}", file=sys.stderr)
        return 2
    if args.as_json:
        import json
        print(json.dumps({"knobs": len(knobs), "problems": problems},
                         indent=1))
    else:
        for p in problems:
            print(p)
        print(f"tpulint knobs: {len(knobs)} tpu_* knob(s), "
              f"{len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
